"""ForwardExporter: serialize a trained forward chain for serving.

Rebuilds the reference's ``ForwardExporter`` (reference:
``znicz/nn_units.py`` / libZnicz — the trained forward chain written in
a format a standalone C++ inference engine could execute without the
training framework).

TPU-native format: one ``.npz`` bundle holding a JSON manifest (layer
types + constructor configs + input geometry) beside the parameter
arrays.  :class:`ExportedModel` reloads the bundle **without any
workflow, loader or training machinery** and rebuilds the forward
chain from the layer-type registry — the same unit code that trained
is the inference spec — then compiles it into a single jitted
inference function (or runs the numpy oracle path).
"""

from __future__ import annotations

import io
import json
import os

import numpy as np

from znicz_tpu.backends import Device, NumpyDevice
from znicz_tpu.dummy import DummyUnit, DummyWorkflow
from znicz_tpu.memory import Vector

FORMAT_NAME = "znicz-tpu-forward"
FORMAT_VERSION = 1


def _manifest_for(workflow) -> dict:
    """Collect layer specs + geometry from a trained
    StandardWorkflow."""
    layers = []
    for spec, unit in zip(workflow.layers_config, workflow.forwards):
        entry = {
            "type": spec["type"],
            "config": spec.get("->", {}),
            "has_weights": bool(unit.weights),
            "has_bias": bool(unit.bias),
            "name": unit.name,
        }
        if spec.get("tied_to") is not None:
            # autoencoder decoder layers reference the encoder layer
            # they invert; serialize the tie so _build_chain can rewire
            # Deconv.output_shape_source / Depooling.pooling_unit
            entry["tied_to"] = int(spec["tied_to"])
            entry["tied_weights"] = bool(spec.get("tied_weights"))
        layers.append(entry)
    return {
        "format": FORMAT_NAME,
        "version": FORMAT_VERSION,
        "workflow": workflow.name,
        "loss": workflow.loss,
        "input_shape": list(workflow.loader.minibatch_data.shape[1:]),
        "layers": layers,
    }


def export_forward(workflow, path: str) -> str:
    """Write the trained forward chain of a ``StandardWorkflow`` to
    ``path`` (``.npz`` bundle).  Returns the path written."""
    manifest = _manifest_for(workflow)
    arrays: dict[str, np.ndarray] = {}
    for i, unit in enumerate(workflow.forwards):
        for attr in unit.EXPORT_PARAMS:
            vec = getattr(unit, attr)
            if vec:
                vec.map_read()
                arrays[f"layer{i}_{attr}"] = np.array(vec.mem, copy=True)
    arrays["manifest"] = np.frombuffer(
        json.dumps(manifest).encode(), dtype=np.uint8)
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "wb") as f:
        f.write(buf.getvalue())
    os.replace(tmp, path)
    return path


class ExportedModel:
    """A servable forward chain loaded from an exported bundle.

    ``model(x)`` maps a float32 batch (NHWC or flat, matching the
    training loader's sample shape) to the final layer's output
    (softmax head → class probabilities).  Stochastic layers (dropout)
    run in eval mode.  The XLA path compiles the whole chain into one
    program; the numpy path is the oracle."""

    def __init__(self, manifest: dict,
                 params: dict[str, np.ndarray],
                 device: Device | None = None) -> None:
        if manifest.get("format") != FORMAT_NAME:
            raise ValueError("not a znicz-tpu forward bundle")
        if manifest.get("version", 0) > FORMAT_VERSION:
            raise ValueError(
                f"bundle version {manifest['version']} is newer than "
                f"this framework ({FORMAT_VERSION})")
        self.manifest = manifest
        self.input_shape = tuple(manifest["input_shape"])
        self.device = device or Device.create()
        self._params = params
        self._params_loaded = False
        self._by_batch: dict[int, "callable"] = {}  # jit fn per size
        self._cur_batch: int | None = None
        self._build_chain()

    @classmethod
    def load(cls, path: str,
             device: Device | None = None) -> "ExportedModel":
        with np.load(path) as bundle:
            manifest = json.loads(bytes(bundle["manifest"]).decode())
            params = {k: bundle[k] for k in bundle.files
                      if k != "manifest"}
        return cls(manifest, params, device=device)

    # ------------------------------------------------------------------
    def _build_chain(self) -> None:
        from znicz_tpu.models.standard_workflow import layer_type
        from znicz_tpu.ops import deconv, depooling
        wf = DummyWorkflow(device=self.device)
        self._input_vec = Vector(name="export.input", batch_major=True)
        source = DummyUnit(wf, output=self._input_vec)
        self.forwards = []
        prev = source
        for i, layer in enumerate(self.manifest["layers"]):
            cls = layer_type(layer["type"])
            cfg = dict(layer["config"])
            tied = layer.get("tied_to")
            if tied is not None and issubclass(cls, deconv.Deconv):
                # geometry mirrors the tied conv layer (same defaulting
                # as StandardWorkflow.link_forwards)
                tied_cfg = self.manifest["layers"][tied]["config"]
                for key in ("n_kernels", "kx", "ky", "sliding",
                            "padding"):
                    if key in tied_cfg:
                        cfg.setdefault(key, tied_cfg[key])
            unit = cls(wf, **cfg)
            if tied is not None:
                if issubclass(cls, deconv.Deconv):
                    unit.output_shape_source = self.forwards[tied].input
                    if layer.get("tied_weights"):
                        # restore encoder/decoder weight sharing, not
                        # just numerically-equal copies
                        unit.link_attrs(self.forwards[tied], "weights")
                elif issubclass(cls, depooling.Depooling):
                    unit.pooling_unit = self.forwards[tied]
                else:
                    raise ValueError(
                        f"layer {i} type '{layer['type']}' does not "
                        f"support tied_to")
            unit.link_attrs(prev, ("input", "output"))
            if "forward_mode" in unit.__dict__:
                unit.forward_mode = "eval"  # dropout = identity
            self.forwards.append(unit)
            prev = unit
        self._wf = wf

    def _initialize(self, batch: int) -> None:
        """(Re-)shape the chain for a batch size.  Parameters load
        exactly once — unit re-initialization keeps non-empty
        weights/bias, so only the input and intermediate activations
        reallocate per batch size."""
        self._input_vec.reset(np.zeros(
            (batch,) + self.input_shape, dtype=np.float32))
        self._input_vec.initialize(self.device)
        for i, unit in enumerate(self.forwards):
            if not self._params_loaded:
                # units must see the stored params BEFORE their first
                # initialize (so they skip the random fill)
                for attr in unit.EXPORT_PARAMS:
                    key = f"layer{i}_{attr}"
                    if key in self._params:
                        getattr(unit, attr).reset(
                            np.array(self._params[key], copy=True))
            unit.initialize(device=self.device)
            if not self._params_loaded:
                for attr in unit.EXPORT_PARAMS:
                    key = f"layer{i}_{attr}"
                    vec = getattr(unit, attr)
                    if key in self._params:
                        if tuple(vec.shape) != self._params[key].shape:
                            raise ValueError(
                                f"layer {i} {attr}: bundle shape "
                                f"{self._params[key].shape} != rebuilt "
                                f"{tuple(vec.shape)}")
                    else:
                        spec = self.manifest["layers"][i]
                        if vec and not (spec.get("tied_weights")
                                        and attr == "weights"):
                            # a non-empty parameter the bundle does
                            # not carry means initialize random-filled
                            # it — serving would be silently corrupted
                            # (e.g. a truncated or pre-EXPORT_PARAMS
                            # bundle)
                            raise ValueError(
                                f"layer {i} ({spec['type']}): "
                                f"parameter '{attr}' missing from the "
                                f"bundle — refusing to serve a random-"
                                f"initialized substitute")
        self._params_loaded = True
        self._cur_batch = batch

    # ------------------------------------------------------------------
    def _compile(self):
        import jax

        vectors: list[Vector] = []
        seen = {id(self._input_vec)}
        for unit in self.forwards:
            for vec in unit.region_vectors():
                if id(vec) not in seen:
                    seen.add(id(vec))
                    vectors.append(vec)
        for vec in vectors:
            vec.unmap()
        units = self.forwards
        input_vec = self._input_vec

        def fn(x, *leaves):
            for vec, leaf in zip(vectors, leaves):
                vec._tracing = True
                vec._devmem = leaf
            input_vec._tracing = True
            input_vec._devmem = x
            try:
                for unit in units:
                    unit.xla_run()
                return units[-1].output._devmem
            finally:
                input_vec._tracing = False
                for vec in vectors:
                    vec._tracing = False

        jitted = jax.jit(fn)
        leaves = [vec._devmem for vec in vectors]
        input_leaf = input_vec._devmem

        def call(x):
            out = jitted(x, *leaves)
            # tracing wrote tracers into vec._devmem; restore the real
            # arrays so later _initialize/_compile rounds (other batch
            # sizes) never snapshot a dead tracer
            for vec, leaf in zip(vectors, leaves):
                vec._devmem = leaf
            input_vec._devmem = input_leaf
            return out

        return call

    def __call__(self, x: np.ndarray) -> np.ndarray:
        x = np.ascontiguousarray(x, dtype=np.float32)
        if x.shape[1:] != self.input_shape:
            raise ValueError(f"input sample shape {x.shape[1:]} != "
                             f"exported {self.input_shape}")
        batch = x.shape[0]
        if isinstance(self.device, NumpyDevice):
            if self._cur_batch != batch:
                self._initialize(batch)
            self._input_vec.map_invalidate()
            self._input_vec.mem[...] = x
            for unit in self.forwards:
                unit.numpy_run()
            out = self.forwards[-1].output
            out.map_read()
            return np.array(out.mem, copy=True)
        # XLA: one compiled program per batch size, cached — ragged
        # serving streams (64,64,37,64,…) pay each size's trace once
        fn = self._by_batch.get(batch)
        if fn is None:
            self._initialize(batch)
            fn = self._by_batch[batch] = self._compile()
        return np.asarray(fn(x))

    def predict_classes(self, x: np.ndarray) -> np.ndarray:
        return np.argmax(self(x), axis=1)
