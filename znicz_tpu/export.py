"""ForwardExporter: serialize a trained forward chain for serving.

Rebuilds the reference's ``ForwardExporter`` (reference:
``znicz/nn_units.py`` / libZnicz — the trained forward chain written in
a format a standalone C++ inference engine could execute without the
training framework).

TPU-native format: one ``.npz`` bundle holding a JSON manifest (layer
types + constructor configs + input geometry + trained compute dtype)
beside the parameter arrays.  :class:`ExportedModel` reloads the
bundle **without any workflow, loader or training machinery** and
rebuilds the forward chain from the layer-type registry — the same
unit code that trained is the inference spec — then compiles it ahead
of time (or runs the numpy oracle path).

Program cache (round 8): batch sizes round up to a power-of-two
**bucket ladder** (``serving.buckets``) so a ragged request stream
(64, 64, 37, 1, …) shares ``log2(max_batch)+1`` compiled programs
instead of paying one trace+compile per distinct size, and residents
are LRU-bounded so a one-off odd size can no longer pin a program
forever.  Each program is ``jit(...).lower(...).compile()``d — real
AOT, so :meth:`ExportedModel.warmup` at engine start means zero
compiles at serve time — with the input buffer donated on platforms
that support donation (TPU/GPU; XLA then reuses the request's HBM for
intermediates instead of allocating fresh).  The throughput path on
top of this cache is :class:`znicz_tpu.serving.ServingEngine`.
"""

from __future__ import annotations

import io
import json
import os
import threading
from collections import Counter, OrderedDict

import numpy as np

from znicz_tpu.backends import Device, NumpyDevice
from znicz_tpu.dummy import DummyUnit, DummyWorkflow
from znicz_tpu.memory import Vector
from znicz_tpu.observe import metrics as _metrics
from znicz_tpu.observe import tracing as _tracing
from znicz_tpu.utils.logger import Logger
from znicz_tpu.serving.buckets import bucket_for, ladder
from znicz_tpu.serving import quantize as _quantize

FORMAT_NAME = "znicz-tpu-forward"
FORMAT_VERSION = 1


class SwapIncompatible(RuntimeError):
    """A candidate weight set does not fit the serving chain (layer
    table, parameter shapes or dtypes disagree with the manifest the
    programs were compiled against).  Raised BEFORE anything is
    staged or flipped — the incumbent weights are untouched and the
    engine keeps serving them."""


def read_bundle(path: str) -> tuple[dict, dict]:
    """Load an exported ``.npz`` bundle's ``(manifest, params)``
    without building a model — the publication watcher and the swap
    path read candidates through this."""
    with np.load(path) as bundle:
        manifest = json.loads(bytes(bundle["manifest"]).decode())
        params = {k: bundle[k] for k in bundle.files if k != "manifest"}
    return manifest, params

#: default ladder cap for direct ``ExportedModel`` use (the engine
#: passes its own, typically much smaller, ``max_batch``)
DEFAULT_MAX_BATCH = 1024


def _sequence_meta(layers: list[dict],
                   input_shape: tuple) -> dict | None:
    """Decode metadata for an autoregressive LM chain, derived from
    the layer specs: the sequence axis (``train_t``), the vocabulary,
    and one cache-shape entry per stateful layer (attention K/V pages,
    LSTM carries).  Returns ``None`` for chains the decode path cannot
    drive — not token-first (no leading ``embedding``), stateless
    (nothing to cache), or non-causal attention (a bidirectional layer
    has no valid incremental step).

    This is ALSO the legacy-bundle fallback: bundles exported before
    round 12 carry no ``kind``/``sequence`` keys, so
    :class:`ExportedModel` re-derives both from the layer table it
    always had (mirroring the round-8 dtype-default pattern)."""
    if not layers or layers[0]["type"] != "embedding":
        return None
    cfg0 = layers[0].get("config", {})
    vocab = int(cfg0["vocab_size"])
    dim = int(cfg0["dim"])
    d = dim
    cache: list[dict] = []
    for i, spec in enumerate(layers):
        kind, cfg = spec["type"], spec.get("config", {})
        if kind == "attention":
            if not cfg.get("causal"):
                return None  # bidirectional: no incremental step
            heads = int(cfg["n_heads"])
            cache.append({"layer": i, "kind": "attention",
                          "heads": heads, "head_dim": d // heads,
                          "features": d})
        elif kind == "lstm":
            hidden = cfg.get("units",
                             cfg.get("output_sample_shape"))
            cache.append({"layer": i, "kind": "lstm",
                          "hidden": int(hidden)})
            d = int(hidden)
    if not cache:
        return None
    return {"train_t": int(input_shape[0]), "vocab": vocab,
            "dim": dim, "cache": cache}


def _manifest_for(workflow) -> dict:
    """Collect layer specs + geometry from a trained
    StandardWorkflow."""
    layers = []
    for spec, unit in zip(workflow.layers_config, workflow.forwards):
        entry = {
            "type": spec["type"],
            "config": spec.get("->", {}),
            "has_weights": bool(unit.weights),
            "has_bias": bool(unit.bias),
            "name": unit.name,
        }
        if spec.get("tied_to") is not None:
            # autoencoder decoder layers reference the encoder layer
            # they invert; serialize the tie so _build_chain can rewire
            # Deconv.output_shape_source / Depooling.pooling_unit
            entry["tied_to"] = int(spec["tied_to"])
            entry["tied_weights"] = bool(spec.get("tied_weights"))
        layers.append(entry)
    device = getattr(workflow, "device", None)
    if device is not None:
        dtype = np.dtype(device.compute_dtype)
    else:
        from znicz_tpu.utils.config import root
        dtype = np.dtype(root.common.get("precision_type", "float32"))
    input_shape = tuple(workflow.loader.minibatch_data.shape[1:])
    manifest = {
        "format": FORMAT_NAME,
        "version": FORMAT_VERSION,
        "workflow": workflow.name,
        "loss": workflow.loss,
        "input_shape": list(input_shape),
        # the precision mode the net TRAINED under — serving must run
        # the same mode, not silently upcast bf16 nets to f32
        "dtype": str(dtype),
        "layers": layers,
    }
    # round 12: model kind + sequence/cache metadata so the serving
    # layer can construct decode state (KV pages, LSTM carries,
    # prompt-length ladder) from the bundle alone; scorer bundles
    # carry the kind so the engine refuses generate() loudly
    seq = _sequence_meta(layers, input_shape)
    manifest["kind"] = "lm" if seq is not None else "scorer"
    if seq is not None:
        manifest["sequence"] = seq
    return manifest


def attach_decode_meta(path: str, *, page_tokens: int | None = None,
                       pool_tokens: int | None = None,
                       drafter: str | None = None,
                       spec_draft_k: int | None = None) -> dict:
    """Stamp decode-plane defaults into an existing LM bundle's
    manifest (round 15): the paged-cache geometry
    (``kv_page_tokens`` / ``pool_tokens``) and the speculative
    drafter reference (a published bundle path + ``spec_draft_k``),
    so a :class:`~znicz_tpu.serving.DecodeEngine` built from the
    bundle alone serves with the intended data plane.  Merges into
    any existing ``decode`` section; returns the section written.
    The file is rewritten atomically (same temp+rename discipline as
    :func:`export_forward`)."""
    manifest, params = read_bundle(path)
    if manifest.get("kind", "lm") != "lm":
        raise ValueError(f"bundle '{path}' is a "
                         f"'{manifest.get('kind')}' — decode metadata "
                         f"belongs on LM bundles")
    meta = dict(manifest.get("decode", {}))
    for key, value in (("kv_page_tokens", page_tokens),
                       ("pool_tokens", pool_tokens),
                       ("drafter", drafter),
                       ("spec_draft_k", spec_draft_k)):
        if value is not None:
            meta[key] = value
    manifest["decode"] = meta
    arrays = {k: np.asarray(v) for k, v in params.items()}
    arrays["manifest"] = np.frombuffer(
        json.dumps(manifest).encode(), dtype=np.uint8)
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "wb") as f:
        f.write(buf.getvalue())
    os.replace(tmp, path)
    sidecar = f"{path}.sha256"
    if os.path.exists(sidecar):
        # published bundles carry a digest sidecar the
        # PublicationWatcher verifies on load — a stale hash after
        # the rewrite would brick the bundle at serve time
        from znicz_tpu.utils.snapshotter import _sha256_file
        side_tmp = f"{sidecar}.{os.getpid()}.tmp"
        with open(side_tmp, "w") as f:
            f.write(_sha256_file(path) + "\n")
        os.replace(side_tmp, sidecar)
    return meta


def export_forward(workflow, path: str) -> str:
    """Write the trained forward chain of a ``StandardWorkflow`` to
    ``path`` (``.npz`` bundle).  Returns the path written."""
    manifest = _manifest_for(workflow)
    arrays: dict[str, np.ndarray] = {}
    for i, unit in enumerate(workflow.forwards):
        for attr in unit.EXPORT_PARAMS:
            vec = getattr(unit, attr)
            if vec:
                vec.map_read()
                arrays[f"layer{i}_{attr}"] = np.array(vec.mem, copy=True)
    arrays["manifest"] = np.frombuffer(
        json.dumps(manifest).encode(), dtype=np.uint8)
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "wb") as f:
        f.write(buf.getvalue())
    os.replace(tmp, path)
    return path


class ExportedModel(Logger):
    """A servable forward chain loaded from an exported bundle.

    ``model(x)`` maps a batch (NHWC or flat, matching the training
    loader's sample shape) to the final layer's output (softmax head →
    class probabilities).  Inputs are cast to the MANIFEST dtype — the
    precision mode the net trained under — not unconditionally to
    float32.  Stochastic layers (dropout) run in eval mode.

    XLA path: requests round up to the power-of-two bucket ladder and
    run AOT-compiled programs from a bounded LRU cache (``max_batch``
    caps the ladder; ``bucketing=False`` restores the historical
    per-exact-size unbounded cache for A/B benchmarks).  The numpy
    path is the oracle and always computes in float32."""

    def __init__(self, manifest: dict,
                 params: dict[str, np.ndarray],
                 device: Device | None = None,
                 max_batch: int = DEFAULT_MAX_BATCH,
                 bucketing: bool = True) -> None:
        super().__init__()
        if manifest.get("format") != FORMAT_NAME:
            raise ValueError("not a znicz-tpu forward bundle")
        if manifest.get("version", 0) > FORMAT_VERSION:
            raise ValueError(
                f"bundle version {manifest['version']} is newer than "
                f"this framework ({FORMAT_VERSION})")
        self.manifest = manifest
        self.input_shape = tuple(manifest["input_shape"])
        self.device = device or Device.create()
        self.dtype = np.dtype(manifest.get("dtype", "float32"))
        if not self.device.is_host_only \
                and self.device.compute_dtype != self.dtype:
            # the chain must rebuild under the TRAINED precision mode
            # (MXU input dtype, activation storage) — a bf16 net served
            # through an f32-configured device would silently change
            # the program that validated
            self.device.compute_dtype = self.dtype
        self.max_batch = int(max_batch)
        self.bucketing = bucketing
        self._params = params
        # round 21: int8 weight-only quantization — the manifest's
        # quant record names the int8 tensors (their per-channel
        # scales ride as <key>_scale leaves).  Unit vectors always
        # hold the DEQUANTIZED f32 values (the numpy oracle and the
        # trace templates), while AOT programs take (q, scale)
        # operand pairs so the HBM-resident copy stays int8 and the
        # program dequantizes on load.
        self._quant = manifest.get("quant") or None
        self._qkeys = frozenset((self._quant or {}).get("weights", []))
        self._qops: dict | None = None
        self._params_loaded = False
        #: AOT programs keyed by PADDED batch size, LRU-ordered
        self._programs: OrderedDict[int, "callable"] = OrderedDict()
        self.program_hits: Counter = Counter()  # size → cache hits
        self.compile_count = 0
        #: programs DESERIALIZED from the persisted AOT cache instead
        #: of compiled (round 23) — a load is never a compile
        self.load_count = 0
        self._cur_batch: int | None = None
        # hot-swap state (round 13): trained parameters are CALL-TIME
        # operands of every AOT program, published as one immutable
        # tuple a dispatch reads exactly once — swapping replaces the
        # tuple between dispatches, never a buffer under a running
        # program
        self._param_vecs: "list[tuple[str, Vector]] | None" = None
        self._live_params: tuple = ()
        self._swap_lock = threading.RLock()
        self.weights_version = 0
        # round 16: an optional FLEET-shared ladder budget — when many
        # resident models share one device, program-cache pressure is
        # a cross-model decision (evict the lowest-priority tenant's
        # buckets first), so the fleet attaches one accountant here
        self._shared_budget = None
        self._budget_key: str | None = None
        self._budget_priority = 0
        self._build_chain()

    @classmethod
    def load(cls, path: str, device: Device | None = None,
             **kwargs) -> "ExportedModel":
        manifest, params = read_bundle(path)
        return cls(manifest, params, device=device, **kwargs)

    # ------------------------------------------------------------------
    @property
    def kind(self) -> str:
        """``"lm"`` (token-first causal chain the decode engine can
        drive) or ``"scorer"`` (one-shot forward).  Legacy bundles
        (pre-round-12, no ``kind`` key) re-derive it from the layer
        table — the round-8 dtype-default pattern."""
        kind = self.manifest.get("kind")
        if kind is None:
            kind = "lm" if self.sequence is not None else "scorer"
        return kind

    @property
    def sequence(self) -> dict | None:
        """Decode metadata (``train_t``, ``vocab``, per-layer cache
        shapes) for LM bundles; ``None`` for scorers.  Derived on the
        fly for legacy bundles."""
        seq = self.manifest.get("sequence")
        if seq is None and "kind" not in self.manifest:
            seq = _sequence_meta(self.manifest["layers"],
                                 self.input_shape)
        return seq

    @property
    def serve_dtype(self) -> np.dtype:
        """Input/compute dtype requests are cast to: the manifest
        (training) dtype on accelerator devices; the numpy oracle
        always runs float32."""
        if self.device.is_host_only:
            return np.dtype(np.float32)
        return self.dtype

    @property
    def _align(self) -> int:
        """Bucket alignment: on a data-parallel mesh every bucket must
        divide evenly over the data axis."""
        return max(1, getattr(self.device, "n_data_shards", 1))

    @property
    def _program_capacity(self) -> int:
        return len(ladder(self.max_batch, self._align))

    # ------------------------------------------------------------------
    def _build_chain(self) -> None:
        from znicz_tpu.models.standard_workflow import layer_type
        from znicz_tpu.ops import deconv, depooling
        wf = DummyWorkflow(device=self.device)
        self._input_vec = Vector(name="export.input", batch_major=True)
        source = DummyUnit(wf, output=self._input_vec)
        self.forwards = []
        prev = source
        for i, layer in enumerate(self.manifest["layers"]):
            cls = layer_type(layer["type"])
            cfg = dict(layer["config"])
            tied = layer.get("tied_to")
            if tied is not None and issubclass(cls, deconv.Deconv):
                # geometry mirrors the tied conv layer (same defaulting
                # as StandardWorkflow.link_forwards)
                tied_cfg = self.manifest["layers"][tied]["config"]
                for key in ("n_kernels", "kx", "ky", "sliding",
                            "padding"):
                    if key in tied_cfg:
                        cfg.setdefault(key, tied_cfg[key])
            unit = cls(wf, **cfg)
            if tied is not None:
                if issubclass(cls, deconv.Deconv):
                    unit.output_shape_source = self.forwards[tied].input
                    if layer.get("tied_weights"):
                        # restore encoder/decoder weight sharing, not
                        # just numerically-equal copies
                        unit.link_attrs(self.forwards[tied], "weights")
                elif issubclass(cls, depooling.Depooling):
                    unit.pooling_unit = self.forwards[tied]
                else:
                    raise ValueError(
                        f"layer {i} type '{layer['type']}' does not "
                        f"support tied_to")
            unit.link_attrs(prev, ("input", "output"))
            if "forward_mode" in unit.__dict__:
                unit.forward_mode = "eval"  # dropout = identity
            self.forwards.append(unit)
            prev = unit
        self._wf = wf

    def _initialize(self, batch: int) -> None:
        """(Re-)shape the chain for a batch size.  Parameters load
        exactly once — unit re-initialization keeps non-empty
        weights/bias, so only the input and intermediate activations
        reallocate per batch size."""
        self._input_vec.reset(np.zeros(
            (batch,) + self.input_shape, dtype=self.serve_dtype))
        self._input_vec.initialize(self.device)
        for i, unit in enumerate(self.forwards):
            if not self._params_loaded:
                # units must see the stored params BEFORE their first
                # initialize (so they skip the random fill)
                for attr in unit.EXPORT_PARAMS:
                    key = f"layer{i}_{attr}"
                    if key in self._params:
                        arr = self._params[key]
                        if key in self._qkeys:
                            arr = _quantize.dequantize_array(
                                arr, self._params[
                                    _quantize.scale_key(key)]
                            ).astype(self.dtype)
                        getattr(unit, attr).reset(
                            np.array(arr, copy=True))
            unit.initialize(device=self.device)
            if not self._params_loaded:
                for attr in unit.EXPORT_PARAMS:
                    key = f"layer{i}_{attr}"
                    vec = getattr(unit, attr)
                    if key in self._params:
                        if tuple(vec.shape) != self._params[key].shape:
                            raise ValueError(
                                f"layer {i} {attr}: bundle shape "
                                f"{self._params[key].shape} != rebuilt "
                                f"{tuple(vec.shape)}")
                    else:
                        spec = self.manifest["layers"][i]
                        if vec and not (spec.get("tied_weights")
                                        and attr == "weights"):
                            # a non-empty parameter the bundle does
                            # not carry means initialize random-filled
                            # it — serving would be silently corrupted
                            # (e.g. a truncated or pre-EXPORT_PARAMS
                            # bundle)
                            raise ValueError(
                                f"layer {i} ({spec['type']}): "
                                f"parameter '{attr}' missing from the "
                                f"bundle — refusing to serve a random-"
                                f"initialized substitute")
        self._params_loaded = True
        self._cur_batch = batch

    # ------------------------------------------------------------------
    def _donate_choice(self) -> bool:
        """Donate the request buffer into the program?  Auto: yes on
        platforms where XLA implements input donation (TPU/GPU — the
        input's HBM is then recycled for intermediates, so steady-state
        serving allocates nothing per request); no on CPU, where
        donation is unimplemented and only emits warnings.
        ``root.common.serving.donate`` overrides."""
        from znicz_tpu.utils.config import root
        cfg = root.common.serving.get("donate", None)
        if cfg is not None:
            return bool(cfg)
        return bool(getattr(self.device, "supports_donation", False))

    def _ensure_param_vecs(self) -> "list[tuple[str, Vector]]":
        """The trained-parameter vectors in canonical (layer, attr)
        order, deduped by identity (tied autoencoder weights appear
        once).  These are the leaves :meth:`swap_weights` replaces and
        every AOT program takes as call-time operands."""
        if self._param_vecs is None:
            if self._cur_batch is None:
                # swap before any request: build + load the chain at
                # the smallest bucket so the vectors exist
                self._initialize(self._align)
            seen: set[int] = set()
            out: list[tuple[str, Vector]] = []
            for i, unit in enumerate(self.forwards):
                for attr in unit.EXPORT_PARAMS:
                    vec = getattr(unit, attr)
                    if vec and id(vec) not in seen:
                        seen.add(id(vec))
                        out.append((f"layer{i}_{attr}", vec))
            self._param_vecs = out
        return self._param_vecs

    @property
    def live_params(self) -> tuple:
        """The currently-published weight tuple.  Immutable; a
        dispatcher reads it ONCE per batch and passes it to the
        program, so an in-flight dispatch finishes on the weights it
        started with no matter when a swap lands."""
        return self._live_params

    def _quant_operands(self) -> dict:
        """Device-resident ``(q int8, scale f32)`` operand pairs for
        the quantized keys (round 21), uploaded ONCE and shared by
        every bucket's program — a quantized model's weights live in
        HBM as int8; each program dequantizes on load.  Empty for f32
        bundles and for the numpy oracle device."""
        if not self._qkeys or isinstance(self.device, NumpyDevice):
            return {}
        if self._qops is None:
            import jax
            put = self._quant_put()
            ops = {}
            for key in sorted(self._qkeys):
                ops[key] = (
                    put(np.asarray(self._params[key], np.int8)),
                    put(np.asarray(
                        self._params[_quantize.scale_key(key)],
                        np.float32)))
            self._qops = ops
        return self._qops

    def _quant_put(self):
        """``device_put`` for int8/scale operands, matching the f32
        param leaves' placement: on a multi-device backend the param
        vectors are fully replicated, and a program cannot mix
        replicated f32 leaves with single-device int8 leaves — reuse
        the replication sharding when one exists."""
        import jax
        template = None
        for _key, vec in self._ensure_param_vecs():
            s = getattr(vec._devmem, "sharding", None)
            if s is not None and getattr(s, "is_fully_replicated",
                                         False):
                template = s
                break

        def put(arr):
            return (jax.device_put(arr, template)
                    if template is not None else jax.device_put(arr))
        return put

    def _aot_compile(self):
        """AOT-compile the chain at the CURRENT batch size (the caller
        just ran :meth:`_initialize`): ``jit(...).lower(...).compile()``
        — the compile happens HERE, not on first call, so warmup really
        front-loads every trace.

        Trained parameters are passed as one tuple operand (round 13):
        the program's weight leaves come from :attr:`live_params` at
        call time instead of being captured at compile time, which is
        what makes :meth:`swap_weights` recompile-free — same shapes,
        same shardings, different buffers."""
        import jax
        import jax.numpy as jnp

        param_pairs = self._ensure_param_vecs()
        pvecs = [vec for _k, vec in param_pairs]
        qops = self._quant_operands()
        wdtype = np.dtype(self.dtype)
        param_ids = {id(v) for v in pvecs}
        vectors: list[Vector] = []
        seen = {id(self._input_vec)} | param_ids
        for unit in self.forwards:
            for vec in unit.region_vectors():
                if id(vec) not in seen:
                    seen.add(id(vec))
                    vectors.append(vec)
        for vec in pvecs + vectors:
            vec.unmap()
        units = self.forwards
        input_vec = self._input_vec

        def fn(x, params, *leaves):
            for vec, leaf in zip(pvecs, params):
                vec._tracing = True
                if isinstance(leaf, tuple):
                    # int8 weight + per-output-channel scales:
                    # dequantize on LOAD inside the program — the
                    # call-time operand (and its HBM residency) stays
                    # int8 + a (out,)-vector of scales
                    q, s = leaf
                    leaf = (q.astype(jnp.float32) * s).astype(wdtype)
                vec._devmem = leaf
            for vec, leaf in zip(vectors, leaves):
                vec._tracing = True
                vec._devmem = leaf
            input_vec._tracing = True
            input_vec._devmem = x
            try:
                for unit in units:
                    unit.xla_run()
                return units[-1].output._devmem
            finally:
                input_vec._tracing = False
                for vec in pvecs + vectors:
                    vec._tracing = False

        donate = self._donate_choice()
        jitted = jax.jit(fn, donate_argnums=(0,) if donate else ())
        real_param_devs = [vec._devmem for vec in pvecs]
        param_leaves = tuple(
            qops[key] if key in qops else vec._devmem
            for key, vec in param_pairs)
        leaves = [vec._devmem for vec in vectors]
        input_leaf = input_vec._devmem

        def struct(arr):
            return jax.ShapeDtypeStruct(
                np.shape(arr), np.dtype(arr.dtype),
                sharding=getattr(arr, "sharding", None))

        in_structs = (struct(input_leaf),
                      jax.tree_util.tree_map(struct, param_leaves),
                      *[struct(leaf) for leaf in leaves])

        # round 23: try the persisted executable store BEFORE tracing.
        # The key covers the bundle's architecture digest, bucket,
        # operand structs (shapes/dtypes/shardings carry the mesh),
        # donation, platform and build — a mismatch on any of them is
        # a plain miss and we trace exactly as before.
        from znicz_tpu.serving import aot_cache as _aot
        cache = _aot.active_cache()
        key = digest = None
        compiled = None
        if cache is not None:
            digest = _aot.program_digest(self.manifest)
            key = _aot.entry_key("serving-aot", digest=digest,
                                 geometry=(self._cur_batch,),
                                 structs=in_structs, donate=donate)
            compiled = cache.get(key, "serving-aot")
        if compiled is not None:
            # a deserialized load is NOT a compile: compile_count and
            # the serving-aot xla_compiles series stay untouched (the
            # retrace guard's zero-compile contracts depend on that) —
            # residency is tallied on load_count instead
            compiled = _aot.guard_donated(compiled,
                                          (0,) if donate else ())
            self.load_count += 1
        else:
            with _tracing.TRACER.span(
                    f"aot_compile:b{self._cur_batch}", cat="compile"):
                compiled = jitted.lower(*in_structs).compile()
            # the same series the jit regions count on — the serving
            # side of the steady-state retrace guard watches this site
            _metrics.xla_compiles("serving-aot").inc()
            self.compile_count += 1
            if cache is not None:
                cache.put(key, compiled, "serving-aot",
                          meta={"family": "serving-aot",
                                "program_digest": digest,
                                "geometry": [self._cur_batch]})
        # lowering traced fn, which wrote tracers into vec._devmem;
        # restore the real arrays so later _initialize rounds (other
        # bucket sizes) never snapshot a dead tracer
        for vec, leaf in zip(pvecs, real_param_devs):
            vec._devmem = leaf
        for vec, leaf in zip(vectors, leaves):
            vec._devmem = leaf
        input_vec._devmem = input_leaf
        self._live_params = param_leaves

        def call(x, _params=None):
            # x: host array or committed jax.Array of the padded
            # bucket shape; donated to the program when enabled.
            # _params lets a dispatcher pin the weight tuple it read
            # at dispatch start (the mid-swap atomicity contract);
            # default is whatever is published right now.
            p = self._live_params if _params is None else _params
            return compiled(x, p, *leaves)

        return call

    def attach_program_budget(self, budget, key: str,
                              priority: int = 0) -> None:
        """Join a fleet-shared ladder budget (round 16): every program
        this model compiles is charged to ``budget`` under ``key`` at
        the model's tenant ``priority`` (smaller = more important).
        The budget may call :meth:`drop_program` back on ANY attached
        model to relieve pressure — lowest-priority ladders first."""
        self._shared_budget = budget
        self._budget_key = str(key)
        self._budget_priority = int(priority)
        budget.register(key, self, priority)

    def program_nbytes(self, size: int) -> int:
        """Rough per-program working-set estimate used by the shared
        ladder budget: the padded input batch bytes times the chain
        depth (a proxy for the activations each bucket's program keeps
        live — parameters are shared across buckets and excluded)."""
        sample = int(np.prod(self.input_shape or (1,)))
        return (size * sample * np.dtype(self.serve_dtype).itemsize
                * (len(self.forwards) + 1))

    def weights_nbytes(self) -> int:
        """Parameter bytes of this bundle as published — int8 quant
        bundles land at ~0.5× their f32 twin (q tensors + the
        per-channel scale vectors).  The fleet's SharedLadderBudget
        charges this as a protected per-model entry (round 21), so
        halved weight bytes visibly raise program residency."""
        return int(sum(np.asarray(v).nbytes
                       for v in self._params.values()))

    def drop_program(self, size: int) -> bool:
        """Evict one bucket's AOT program (shared-budget pressure or
        explicit trimming).  A dispatch already holding the callable
        keeps it alive; the next request for this bucket recompiles.
        Returns True when a resident program was dropped."""
        with self._swap_lock:
            if self._programs.pop(size, None) is None:
                return False
            self.debug("dropped program for batch %d (shared ladder "
                       "budget pressure)", size)
            return True

    def program_for(self, size: int):
        """The AOT program serving a PADDED batch of exactly ``size``
        rows, compiled on first use and LRU-cached.  The engine warms
        the whole ladder through this; ``__call__`` routes through it
        after rounding up.  Thread-safe: fleet replica engines share
        one model, so the hit path takes the same lock the compile and
        swap paths hold."""
        compiled = False
        local_evicted: list[int] = []
        with self._swap_lock:  # compile never races a weight flip
            fn = self._programs.get(size)
            if fn is not None:
                self._programs.move_to_end(size)
                self.program_hits[size] += 1
            else:
                compiled = True
                self._initialize(size)
                fn = self._aot_compile()
                self._programs[size] = fn
                if self.bucketing:
                    while len(self._programs) > self._program_capacity:
                        evicted, _ = self._programs.popitem(last=False)
                        local_evicted.append(evicted)
                        self.debug(
                            "evicted program for batch %d (LRU, cap "
                            "%d)", evicted, self._program_capacity)
        # the shared budget is touched OUTSIDE the model lock: its
        # pressure handler takes other models' locks (drop_program),
        # so holding ours here would invert the lock order
        budget = self._shared_budget
        if budget is not None:
            for gone in local_evicted:
                budget.forget(self._budget_key, gone)
            if compiled:
                budget.charge(self._budget_key, size,
                              self.program_nbytes(size))
            else:
                budget.touch(self._budget_key, size)
        return fn

    # ------------------------------------------------------------------
    # weight hot-swap (round 13)
    # ------------------------------------------------------------------
    def check_compatible(self, manifest: dict | None,
                         params: dict) -> "list[tuple[str, Vector]]":
        """Validate a candidate against the chain the programs were
        compiled for; raises :class:`SwapIncompatible` (incumbent
        untouched) on any mismatch.  Returns the canonical param-vec
        pairs the swap will replace."""
        if manifest is not None:
            mine = [layer["type"] for layer in self.manifest["layers"]]
            theirs = [layer["type"] for layer in
                      manifest.get("layers", [])]
            if mine != theirs:
                raise SwapIncompatible(
                    f"candidate layer table {theirs} != serving chain "
                    f"{mine}")
            if tuple(manifest.get("input_shape", self.input_shape)) \
                    != self.input_shape:
                raise SwapIncompatible(
                    f"candidate input shape "
                    f"{tuple(manifest['input_shape'])} != exported "
                    f"{self.input_shape}")
            cand_dtype = np.dtype(manifest.get("dtype", "float32"))
            if cand_dtype != self.dtype:
                raise SwapIncompatible(
                    f"candidate dtype {cand_dtype} != trained "
                    f"{self.dtype} — the compiled programs are pinned "
                    f"to the trained precision mode")
        pairs = self._ensure_param_vecs()
        for key, vec in pairs:
            arr = params.get(key)
            if arr is None:
                raise SwapIncompatible(
                    f"candidate is missing parameter '{key}'")
            if tuple(np.shape(arr)) != tuple(vec.shape):
                raise SwapIncompatible(
                    f"{key}: candidate shape {tuple(np.shape(arr))} != "
                    f"compiled {tuple(vec.shape)}")
        return pairs

    def swap_weights(self, params: dict,
                     manifest: dict | None = None) -> int:
        """Replace the trained parameters of a LIVE model without
        recompiling anything.

        ``params`` maps the export keys (``layer<i>_<attr>``) to host
        arrays (a published bundle's array dict, or a training
        snapshot's exported view).  The three phases of the contract:

        1. **validate** — shapes/dtypes against the manifest/chain;
           any mismatch raises :class:`SwapIncompatible` with the old
           weights untouched;
        2. **stage** — new buffers are uploaded onto the serving
           device/mesh (re-sharded to each parameter's existing
           placement) and fenced, entirely off the dispatch path;
        3. **publish** — the immutable :attr:`live_params` tuple is
           replaced in one assignment.  A dispatch reads the tuple
           once, so in-flight requests finish on the old weights and
           no request ever sees a torn mix.

        Returns the new :attr:`weights_version`."""
        cand_rec = _quantize.is_quantized(manifest)
        if self._qkeys:
            if cand_rec is None:
                raise SwapIncompatible(
                    "candidate is f32 but the serving chain compiled "
                    "int8 dequantize-on-load programs — republish the "
                    "candidate with quantize='int8'")
            if set(cand_rec.get("weights", [])) != set(self._qkeys):
                raise SwapIncompatible(
                    f"candidate quantizes "
                    f"{sorted(cand_rec.get('weights', []))} != "
                    f"compiled {sorted(self._qkeys)}")
            dq = _quantize.dequantize_params(manifest, params)
        elif cand_rec is not None:
            # quantized candidate into an f32-compiled chain: stage
            # the DEQUANTIZED values — exactly the numbers the int8
            # program computes on load, so canary/probation judged
            # the same arithmetic — keeping the swap recompile-free
            # (the compiled programs' operand structure is pinned)
            params = dq = _quantize.dequantize_params(manifest, params)
            cand_rec = None
        else:
            dq = params
        pairs = self.check_compatible(manifest, dq)
        if isinstance(self.device, NumpyDevice):
            with self._swap_lock:
                for key, vec in pairs:
                    new = np.asarray(dq[key]).astype(vec.dtype)
                    vec.map_write()
                    vec.mem[...] = new
                    self._store_swapped(key, new, params, cand_rec)
                self.weights_version += 1
                return self.weights_version
        import jax

        staged = []
        for key, vec in pairs:
            new = np.asarray(dq[key]).astype(vec.dtype)
            old = vec.devmem
            sharding = getattr(old, "sharding", None)
            arr = (jax.device_put(new, sharding)
                   if sharding is not None else jax.device_put(new))
            staged.append((key, vec, new, arr))
        qstaged = {}
        qput = self._quant_put() if cand_rec else None
        for key in (sorted(self._qkeys) if cand_rec else ()):
            sk = _quantize.scale_key(key)
            qstaged[key] = (
                qput(np.asarray(params[key], np.int8)),
                qput(np.asarray(params[sk], np.float32)))
        for _k, _v, _h, arr in staged:  # fence off the dispatch path
            arr.block_until_ready()
        for q, s in qstaged.values():
            q.block_until_ready()
            s.block_until_ready()
        with self._swap_lock:
            for key, vec, host, arr in staged:
                vec.accept_device(arr)
                self._store_swapped(key, host, params, cand_rec)
            if qstaged:
                self._qops = qstaged
            qops = self._qops if self._qkeys else None
            self._live_params = tuple(
                qops[key] if qops and key in qops else vec._devmem
                for key, vec in pairs)
            self.weights_version += 1
            return self.weights_version

    def _store_swapped(self, key: str, host, params: dict,
                       cand_rec) -> None:
        """Refresh the host-side bundle dict after a swap: quantized
        chains keep the candidate's int8 + scale leaves (so
        :meth:`weights_nbytes` stays honest), f32 chains keep the
        staged f32 array."""
        if cand_rec and key in self._qkeys:
            sk = _quantize.scale_key(key)
            self._params[key] = np.asarray(params[key], np.int8)
            self._params[sk] = np.asarray(params[sk], np.float32)
        else:
            self._params[key] = np.array(host, copy=True)

    def warmup(self, max_batch: int | None = None) -> int:
        """Eagerly make every ladder bucket up to ``max_batch``
        (default: this model's cap) RESIDENT so serve time pays ZERO
        compiles.  Returns the number of programs made resident —
        compiled + deserialized from the persisted AOT cache.  With
        the cache disabled (the default) ``load_count`` stays 0 and
        this is exactly the compile count it always was; a cache hit
        must never masquerade as a compile (``compile_count`` and the
        ``site="serving-aot"`` counter only move on real traces) or
        every retrace-guard-style assertion goes blind."""
        if max_batch is not None:
            self.max_batch = max(self.max_batch, int(max_batch))
        before = self.compile_count + self.load_count
        for size in ladder(max_batch or self.max_batch, self._align):
            self.program_for(size)
        return (self.compile_count + self.load_count) - before

    def __call__(self, x: np.ndarray) -> np.ndarray:
        x = np.ascontiguousarray(x, dtype=self.serve_dtype)
        if x.shape[1:] != self.input_shape:
            raise ValueError(f"input sample shape {x.shape[1:]} != "
                             f"exported {self.input_shape}")
        batch = x.shape[0]
        if isinstance(self.device, NumpyDevice):
            if self._cur_batch != batch:
                self._initialize(batch)
            self._input_vec.map_invalidate()
            self._input_vec.mem[...] = x
            for unit in self.forwards:
                unit.numpy_run()
            out = self.forwards[-1].output
            out.map_read()
            return np.array(out.mem, copy=True)
        # XLA: round up to the bucket ladder; the padded rows compute
        # garbage that is sliced off before anyone sees it
        size = bucket_for(batch, self._align) if self.bucketing else batch
        fn = self.program_for(size)
        if size != batch:
            padded = np.zeros((size,) + self.input_shape, dtype=x.dtype)
            padded[:batch] = x
            x = padded
        out = np.asarray(fn(x))
        return np.array(out[:batch]) if size != batch else out

    def predict_classes(self, x: np.ndarray) -> np.ndarray:
        return np.argmax(np.asarray(self(x), dtype=np.float32), axis=1)
