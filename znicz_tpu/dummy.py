"""Test fixtures (reference: ``veles/dummy.py`` — ``DummyWorkflow``/
``DummyUnit`` let any unit initialize and run without a CLI, launcher,
or full training loop)."""

from __future__ import annotations

import numpy as np

from znicz_tpu.accelerated_units import AcceleratedWorkflow
from znicz_tpu.backends import Device
from znicz_tpu.memory import Vector
from znicz_tpu.units import Unit


class DummyWorkflow(AcceleratedWorkflow):
    """A bare workflow container for unit tests."""

    def __init__(self, device: Device | None = None, **kwargs) -> None:
        super().__init__(None, name="dummy", **kwargs)
        if device is not None:
            self.device = device


class DummyUnit(Unit):
    """A unit that exposes arbitrary attributes passed to __init__ —
    handy as a link_attrs source."""

    def __init__(self, workflow=None, **attrs) -> None:
        super().__init__(workflow)
        for name, value in attrs.items():
            setattr(self, name, value)


def vector_of(arr, device: Device, name: str = "fixture") -> Vector:
    """A device-initialized Vector from a numpy array."""
    vec = Vector(np.asarray(arr), name=name)
    vec.initialize(device)
    return vec
