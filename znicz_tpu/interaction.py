"""Interactive shell unit.

Rebuilds the reference's ``veles/interaction.py`` ``Shell`` — a unit
that drops the run into an interactive Python console so the user can
inspect/poke the live workflow between steps, then resume by exiting
the shell.  IPython is used when importable, stdlib ``code.interact``
otherwise.

Wire it like any side-chain unit and gate as desired, e.g.::

    shell = Shell(wf)
    shell.link_from(wf.decision)
    shell.gate_skip = ~wf.decision.epoch_ended   # once per epoch
"""

from __future__ import annotations

from znicz_tpu.units import Unit


class Shell(Unit):
    """Drop into an interactive console when fired.

    The namespace exposes ``workflow``, ``shell`` (this unit) and
    everything in ``extra_locals``.  Set ``shell.enabled = False``
    from inside the console to stop future firings.
    """

    def __init__(self, workflow, name: str | None = None,
                 banner: str | None = None,
                 extra_locals: dict | None = None,
                 interact_fn=None, **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.enabled = True
        self.banner = banner
        self.extra_locals = dict(extra_locals or {})
        #: injectable for tests / embedding; defaults to IPython or
        #: code.interact
        self._interact_fn = interact_fn

    def _default_interact(self, banner: str, local: dict) -> None:
        try:  # pragma: no cover - depends on IPython presence
            from IPython import embed
            embed(banner1=banner, user_ns=local,
                  colors="neutral")
            return
        except ImportError:
            pass
        import code
        code.interact(banner=banner, local=local)

    def run(self) -> None:
        if not self.enabled:
            return
        wf = self.workflow
        local = {"workflow": wf, "shell": self}
        if wf is not None:
            for attr in ("loader", "decision", "evaluator", "forwards",
                         "gds"):
                value = getattr(wf, attr, None)
                if value is not None:
                    local[attr] = value
        local.update(self.extra_locals)
        banner = self.banner or (
            f"znicz_tpu shell — workflow "
            f"'{wf.name if wf else '?'}' paused; locals: "
            f"{', '.join(sorted(local))}.  Exit to resume; "
            f"shell.enabled=False to stop appearing.")
        interact = self._interact_fn or self._default_interact
        interact(banner, local)
