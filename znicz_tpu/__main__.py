"""CLI entry point: ``python -m znicz_tpu <workflow> [<config>]``.

Rebuilds the reference's console entry (reference:
``veles/__main__.py`` + ``scripts/velescli.py`` — the ``veles
<workflow.py> <config.py>`` command): import the config module (it
mutates the global ``root`` tree), import the workflow module, locate
its ``run(load, main)``, and drive it through a
:class:`~znicz_tpu.launcher.Launcher`.

``<workflow>`` may be a file path, a dotted module name, or a bare
sample name (``mnist`` → ``znicz_tpu.models.samples.mnist``).
Config-leaf overrides ride as repeated ``--root key=value`` flags
(reference CLI override behavior), evaluated as Python literals when
possible.
"""

from __future__ import annotations

import argparse
import ast
import importlib
import importlib.util
import os
import sys

from znicz_tpu.launcher import Launcher
from znicz_tpu.utils import prng
from znicz_tpu.utils.config import root
from znicz_tpu.utils.logger import Logger

SAMPLES_PACKAGE = "znicz_tpu.models.samples"


def _import_module(spec: str, kind: str):
    """Import by file path, dotted name, or bare sample name."""
    if os.sep in spec or spec.endswith(".py"):
        path = os.path.abspath(spec)
        if not os.path.exists(path):
            raise FileNotFoundError(f"{kind} file not found: {spec}")
        name = os.path.splitext(os.path.basename(path))[0]
        mod_spec = importlib.util.spec_from_file_location(name, path)
        module = importlib.util.module_from_spec(mod_spec)
        # register BEFORE exec so classes defined in the file pickle
        # against the module actually in sys.modules
        sys.modules[name] = module
        mod_spec.loader.exec_module(module)
        return module
    try:
        return importlib.import_module(spec)
    except ModuleNotFoundError as exc:
        # fall back to the samples package only when the missing module
        # IS the requested one (not a dependency it failed to import)
        if exc.name != spec.split(".")[0] and exc.name != spec:
            raise
    return importlib.import_module(f"{SAMPLES_PACKAGE}.{spec}")


def _apply_root_overrides(pairs: list[str]) -> None:
    for pair in pairs:
        if "=" not in pair:
            raise ValueError(f"--root expects key=value, got '{pair}'")
        key, raw = pair.split("=", 1)
        stripped = raw.strip()
        if stripped.startswith("Tune(") and stripped.endswith(")"):
            # tunable range for --optimize, e.g.
            # --root wine.learning_rate="Tune(0.3, 0.05, 0.8)"
            from znicz_tpu.genetics import Tune
            value = Tune(*ast.literal_eval(stripped[len("Tune"):]))
        else:
            try:
                value = ast.literal_eval(raw)
            except (ValueError, SyntaxError):
                value = raw  # plain string leaf
        node = root
        parts = key.split(".")
        if parts[0] == "root":
            parts = parts[1:]
        for part in parts[:-1]:
            node = getattr(node, part)
        setattr(node, parts[-1], value)


def _list_samples() -> list[str]:
    pkg = importlib.import_module(SAMPLES_PACKAGE)
    out = []
    for entry in sorted(os.listdir(os.path.dirname(pkg.__file__))):
        if entry.endswith(".py") and not entry.startswith("_") \
                and not entry.endswith("_config.py"):
            out.append(entry[:-3])
    return out


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="znicz_tpu",
        description="TPU-native Veles/Znicz: run a workflow "
                    "(reference CLI: `veles <workflow.py> <config.py>`)")
    p.add_argument("workflow", nargs="?",
                   help="workflow .py file, module, or sample name")
    p.add_argument("config", nargs="?",
                   help="config .py file/module mutating the root tree")
    p.add_argument("-s", "--snapshot", help="resume from snapshot file")
    p.add_argument("-b", "--backend", choices=("xla", "tpu", "numpy"),
                   help="device backend (default: root.common.engine."
                        "backend)")
    p.add_argument("-l", "--listen", metavar="HOST:PORT",
                   help="coordinate a multi-host run (process 0; "
                        "reference: master --listen)")
    p.add_argument("-m", "--master", metavar="HOST:PORT",
                   help="join a multi-host run (reference: slave "
                        "--master)")
    p.add_argument("--nodes", type=int, help="total process count")
    p.add_argument("--process-id", type=int, help="this process's index")
    p.add_argument("--retries", type=int, default=0,
                   help="auto-resume attempts after a crash")
    p.add_argument("--seed", type=int, help="override root.common.seed")
    p.add_argument("--root", action="append", default=[],
                   metavar="KEY=VALUE",
                   help="config-leaf override (repeatable), e.g. "
                        "--root mnist.learning_rate=0.01")
    p.add_argument("-v", "--verbose", action="store_true",
                   help="debug-level logging (region compiles, timings)")
    p.add_argument("--no-graphics", action="store_true",
                   help="disable the plotting render thread")
    p.add_argument("--web-status", type=int, metavar="PORT",
                   help="serve the live status dashboard on PORT "
                        "(0 picks a free port)")
    p.add_argument("--web-status-host", default="127.0.0.1",
                   metavar="HOST",
                   help="dashboard bind address (0.0.0.0 to allow "
                        "remote browsers)")
    p.add_argument("--optimize", metavar="GENSxPOP",
                   help="genetic hyperparameter search over Tune "
                        "leaves in the config tree, e.g. "
                        "--optimize 5x8 (reference: veles/genetics)")
    p.add_argument("--chunk", type=int, default=1, metavar="N",
                   help="train N minibatch steps per device dispatch "
                        "(lax.scan over the jit region; amortizes "
                        "dispatch/RPC latency — see "
                        "StandardWorkflow.run_chunked)")
    p.add_argument("--n-model", type=int, default=1, metavar="M",
                   help="model-axis size of the distributed device "
                        "grid (tensor parallelism: layers with "
                        "model_parallel='column'/'row' shard over it; "
                        "requires --listen/--master)")
    p.add_argument("--dump-graph", metavar="FILE",
                   help="write the workflow's Graphviz DOT and exit")
    p.add_argument("--dry-run", action="store_true",
                   help="build + initialize only; do not train")
    p.add_argument("--list-samples", action="store_true",
                   help="list bundled sample workflows and exit")
    return p


class Main(Logger):
    """The CLI driver (reference: ``veles/__main__.py`` ``Main``)."""

    def run(self, argv: list[str] | None = None) -> int:
        args = make_parser().parse_args(argv)
        import logging

        from znicz_tpu.utils.logger import setup_logging
        setup_logging(logging.DEBUG if args.verbose else logging.INFO)
        if args.list_samples:
            print("\n".join(_list_samples()))
            return 0
        if not args.workflow:
            make_parser().print_usage()
            return 2
        if args.config:
            _import_module(args.config, "config")
        _apply_root_overrides(args.root)
        if args.seed is not None:
            root.common.seed = args.seed
        prng.seed_all(int(root.common.seed))

        module = _import_module(args.workflow, "workflow")
        run_fn = getattr(module, "run", None)
        if run_fn is None:
            self.error("workflow module %s has no run(load, main)",
                       module.__name__)
            return 1

        launcher = Launcher(
            backend=args.backend, snapshot=args.snapshot,
            listen=args.listen, master=args.master,
            n_processes=args.nodes, process_id=args.process_id,
            retries=args.retries,
            graphics=False if args.no_graphics else None,
            web_status=args.web_status,
            web_status_host=args.web_status_host,
            chunk=args.chunk, n_model=args.n_model)
        self.launcher = launcher  # introspection (tests, embedding)
        if args.dump_graph or args.dry_run:
            # build (and initialize) without training
            wf = None

            def fake_main(**kwargs):
                nonlocal wf
                wf = launcher.workflow
                if args.dry_run:
                    wf.initialize(device=launcher.make_device(), **kwargs)
                    if launcher._snapshot_state is not None:
                        # validate the staged snapshot actually applies
                        wf.load_state(launcher._snapshot_state)
                        launcher._snapshot_state = None

            run_fn(launcher._load, fake_main)
            wf = wf or launcher.workflow
            if args.dump_graph:
                dot = wf.generate_graph()
                with open(args.dump_graph, "w") as f:
                    f.write(dot)
                self.info("graph → %s", args.dump_graph)
            return 0
        if args.optimize:
            return self._optimize(args, run_fn)
        try:
            launcher.boot(run_fn)
        except KeyboardInterrupt:
            self.warning("interrupted")
            return 130
        return 0

    def _optimize(self, args, run_fn) -> int:
        """Genetic search: every ``Tune`` leaf in the config tree
        (outside ``root.common``) is a gene; each candidate trains a
        fresh workflow via the sample's own ``run(load, main)``."""
        from znicz_tpu.genetics import (GeneticsOptimizer, apply_genome,
                                        collect_tunes, workflow_fitness)
        gens, _, pop = args.optimize.partition("x")
        generations, population = int(gens), int(pop or 8)
        space = {path: tune
                 for path, tune in collect_tunes(root).items()
                 if not path.startswith("common.")}
        if not space:
            self.error("--optimize given but no Tune leaves in the "
                       "config tree")
            return 1
        self.info("optimizing %d genes: %s", len(space), sorted(space))

        def fitness(genome: dict) -> float:
            # same init/shuffle streams per candidate: scores compare
            # hyperparameters, not seed luck
            prng.seed_all(int(root.common.seed))
            # dotted genes hit the config tree; plain genes ride into
            # the sample's build via the trial launcher
            build_kwargs = apply_genome(genome)
            trial = Launcher(
                backend=args.backend,
                graphics=False if args.no_graphics else None,
                load_kwargs=build_kwargs)
            trial.boot(run_fn)
            return workflow_fitness(trial.workflow)

        opt = GeneticsOptimizer(
            space=space, fitness_fn=fitness, generations=generations,
            population_size=population, seed=int(root.common.seed))
        best = opt.run()
        self.best_genome = best  # introspection
        self.info("best genome (fitness %.4f): %s",
                  opt.best_fitness, best)
        return 0


def main(argv: list[str] | None = None) -> int:
    return Main().run(argv)


if __name__ == "__main__":
    sys.exit(main())
