"""Plotter units: workflow nodes that feed the graphics service.

Rebuilds the reference's plotter-unit family (reference:
``veles/plotting_units.py`` — ``AccumulatingPlotter``,
``MatrixPlotter``, ``ImagePlotter`` riding a ``Plotter`` base that
shipped payloads to the graphics server).  The unit API shape is kept
so sample workflows port cleanly; the transport behind it is
:mod:`znicz_tpu.graphics` (render thread + jsonl metrics + optional
zmq PUB) instead of a mandatory separate process.

All plotters are host-side units: wire them on the epoch side chain
(``plotter.link_from(decision)`` with ``gate_skip`` following
``~decision.epoch_ended``) so they never touch the per-minibatch hot
path.
"""

from __future__ import annotations

from typing import Callable

import numpy as np

from znicz_tpu import graphics
from znicz_tpu.memory import Vector
from znicz_tpu.units import Unit


class Plotter(Unit):
    """Base plotter: resolves the graphics server, counts steps."""

    def __init__(self, workflow, name: str | None = None,
                 server: "graphics.GraphicsServer | None" = None,
                 **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self._server = server
        self.step = 0

    @property
    def server(self) -> "graphics.GraphicsServer":
        if self._server is None:
            self._server = graphics.get_server()
        return self._server

    def make_payload(self) -> dict | None:
        raise NotImplementedError

    def run(self) -> None:
        payload = self.make_payload()
        if payload is None:
            return
        payload.setdefault("name", self.name)
        payload.setdefault("step", self.step)
        self.server.submit(payload)
        self.step += 1


class AccumulatingPlotter(Plotter):
    """Accumulates scalar series over time and plots them as curves
    (reference: error-percentage curves per class).

    Add series with :meth:`add_series`: each is a label plus a
    callable returning the current scalar (or ``None`` to skip the
    point this firing).
    """

    SNAPSHOT_ATTRS = ("values", "step")

    def __init__(self, workflow, name: str | None = None,
                 ylabel: str = "", **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.ylabel = ylabel
        self._series: list[tuple[str, Callable[[], float | None]]] = []
        self.values: dict[str, list[list[float]]] = {}

    def add_series(self, label: str,
                   fetch: Callable[[], float | None]) -> None:
        self._series.append((label, fetch))
        self.values.setdefault(label, [[], []])

    def make_payload(self) -> dict | None:
        for label, fetch in self._series:
            value = fetch()
            if value is None:
                continue
            xs, ys = self.values.setdefault(label, [[], []])
            xs.append(float(self.step))
            ys.append(float(value))
        if not any(xs for xs, _ in self.values.values()):
            return None
        return {"kind": "curve", "ylabel": self.ylabel,
                "series": {k: [list(xs), list(ys)]
                           for k, (xs, ys) in self.values.items() if xs}}


class MatrixPlotter(Plotter):
    """Plots a matrix (e.g. the confusion matrix) as a heatmap with
    cell values (reference: ``MatrixPlotter``)."""

    def __init__(self, workflow, name: str | None = None,
                 fetch: Callable[[], np.ndarray | None] | None = None,
                 labels=None, **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.fetch = fetch
        self.labels = labels
        self.input = None  # alternative: a Vector / ndarray attribute

    def _matrix(self) -> np.ndarray | None:
        if self.fetch is not None:
            return self.fetch()
        src = self.input
        if isinstance(src, Vector):
            if not src:
                return None
            src.map_read()
            return np.array(src.mem)
        return None if src is None else np.asarray(src)

    def make_payload(self) -> dict | None:
        m = self._matrix()
        if m is None:
            return None
        return {"kind": "matrix", "data": np.asarray(m),
                "labels": self.labels}


class ImagePlotter(Plotter):
    """Plots one 2-D array (or the first sample of a batch) as an
    image (reference: ``ImagePlotter``)."""

    def __init__(self, workflow, name: str | None = None,
                 fetch: Callable[[], np.ndarray | None] | None = None,
                 **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.fetch = fetch
        self.input = None

    def make_payload(self) -> dict | None:
        if self.fetch is not None:
            img = self.fetch()
        else:
            src = self.input
            if isinstance(src, Vector):
                if not src:
                    return None
                src.map_read()
                img = np.array(src.mem)
            elif src is None:
                return None
            else:
                img = np.asarray(src)
        if img is None:
            return None
        img = np.asarray(img)
        while img.ndim > 2 and img.shape[-1] not in (1, 3):
            img = img[0]
        if img.ndim == 3 and img.shape[-1] == 1:
            img = img[..., 0]
        return {"kind": "image", "data": img}
