"""Continuous batcher: an async request queue drained into buckets.

The Orca insight (Yu et al., OSDI 2022) applied to this framework's
forward path: requests arrive asynchronously and individually, but the
accelerator wants large batches — so a scheduler thread coalesces
whatever is pending into one batch per dispatch, instead of locking
the serving loop to fixed request boundaries.

Policy (all knobs on the constructor):

- a flush happens when pending rows reach ``max_batch`` (full bucket)
  OR the **oldest** pending request has waited ``max_delay_ms`` (the
  admission window: a lone size-1 request is never parked behind an
  empty queue for long);
- coalescing is FIFO-prefix: requests keep arrival order and are never
  reordered past each other, so per-caller ordering holds;
- the queue is bounded in ROWS (``max_queue``): when it is full,
  :meth:`submit` raises :class:`QueueFull` immediately — callers see
  backpressure, the server never queues itself into OOM;
- shutdown drains: everything admitted before :meth:`shutdown` is
  served before the scheduler exits.

Round-11 resilience (graceful degradation under in-flight faults):

- **deadlines** — ``submit(x, deadline_ms=…)``; a request whose
  deadline passes while queued fails fast with
  :class:`DeadlineExceeded` and is **evicted before dispatch** — a
  timed-out caller's rows never occupy a bucket;
- **retry budget** — a dispatch that raises re-queues its requests at
  the queue front up to ``retry_budget`` times each (0 = the
  fail-the-batch seed behavior) before failing their futures; a
  request served after a retry counts a
  ``znicz_recoveries_total{kind=serving_retry}``;
- **circuit breaker** — closed → open when the recent-dispatch
  failure rate crosses ``breaker_failure_rate`` (over a
  ``breaker_window`` outcome window, min ``breaker_min_samples``) or
  the oldest pending request exceeds ``max_queue_age_ms``; while open,
  :meth:`submit` sheds load with a fast :class:`Overloaded` (a
  ``QueueFull`` subclass, so existing backpressure handling still
  catches it); after ``breaker_cooldown_ms`` the breaker goes
  half-open and the next dispatch outcome decides (success → closed,
  failure → open again).  Every transition is a registry counter and
  the live state a gauge (``/metrics``, ``/readyz``).

Round-16 tenancy (the fleet's admission plane): every request may
carry a ``tenant`` + ``priority``.  Pending requests live in priority
CLASSES — strict priority across classes (smaller number dispatches
first), FIFO within a class — so a low-priority flood can delay a
high-priority request by at most the dispatch already in flight.  The
row bound becomes preemptive: when the queue is full and a
higher-priority request arrives, the NEWEST lower-priority rows are
shed (:class:`Overloaded`) to make room — the flooding class absorbs
its own overload.  Per-request ``retry_budget`` overrides the engine
default (per-tenant SLOs), per-tenant row bounds
(``tenant_max_rows``) cap any one tenant's share of the queue, and
the breaker's stall-trip watches the HIGHEST-priority head only — a
starved low class is a shedding/deadline problem for that class, not
evidence of a stalled device.

The batcher knows nothing about models or devices — it hands each
coalesced batch (a list of :class:`Request`) to the ``run_batch``
callable and that callable resolves the futures.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future

import numpy as np

from znicz_tpu.observe import metrics as _metrics
from znicz_tpu.observe import recorder as _recorder
from znicz_tpu.observe import tracing as _tracing
from znicz_tpu.utils.logger import Logger


class QueueFull(RuntimeError):
    """Raised by :meth:`ContinuousBatcher.submit` when the bounded
    request queue has no room — the caller's backpressure signal."""


class Overloaded(QueueFull):
    """Load shed: the circuit breaker is open (recent dispatches
    failing, or the queue has grown stale) — the caller gets this
    reply in microseconds instead of a future that times out."""


class DeadlineExceeded(TimeoutError):
    """The request's ``deadline_ms`` passed while it was queued; it
    was evicted before ever reaching a program."""


#: breaker states, also the gauge encoding on /metrics
_CLOSED, _HALF_OPEN, _OPEN = "closed", "half_open", "open"
_STATE_CODE = {_CLOSED: 0, _HALF_OPEN: 1, _OPEN: 2}


class TokenBudget:
    """Token-denominated admission budget (round 15).

    The row-bounded queue above fits one-shot scoring, where every
    request costs one program dispatch; a *decode* queue holds work
    proportional to ``prompt + max_new_tokens`` TOKENS per request,
    and the paged KV pool's capacity is tokens too — so the decode
    engine bounds admission in the same currency.  ``try_acquire`` is
    non-blocking (admission control wants an immediate
    :class:`QueueFull`, never a hidden wait); ``release`` returns a
    request's charge when it completes, fails or expires.

    Round 16 tightened the accounting contract to exactly-once: a
    reservation must be released exactly one time across every exit
    path (served, dispatch-failed after retries, deadline-evicted,
    preempted, shed at the pool) — a retry that re-queues a request
    at the queue front KEEPS its reservation (the work is still
    pending).  A release that exceeds what is held no longer clamps
    silently: it is counted on :attr:`over_released` (and the excess
    discarded), so a double-release shows up as a nonzero counter in
    the accounting tests instead of as quiet over-admission."""

    __slots__ = ("capacity", "_used", "_lock", "over_released")

    def __init__(self, capacity: int) -> None:
        if capacity < 1:
            raise ValueError(f"need capacity >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._used = 0
        self._lock = threading.Lock()
        #: tokens released beyond what was held — MUST stay 0; any
        #: nonzero value is a double-release bug in a caller
        self.over_released = 0

    @property
    def used(self) -> int:
        return self._used

    @property
    def available(self) -> int:
        return self.capacity - self._used

    def try_acquire(self, n: int) -> bool:
        n = int(n)
        with self._lock:
            # a request bigger than the whole budget must still be
            # admissible when the queue is empty, or it could never
            # run at all — the pool-fit check downstream decides
            if self._used + n > self.capacity and self._used > 0:
                return False
            self._used += n
            return True

    def release(self, n: int) -> None:
        n = int(n)
        with self._lock:
            if n > self._used:
                self.over_released += n - self._used
                n = self._used
            self._used -= n

    def balanced(self) -> bool:
        """True when every reservation was returned exactly once —
        nothing outstanding, nothing over-released (assert this when
        the owning queue is idle)."""
        with self._lock:
            return self._used == 0 and self.over_released == 0


class TokenBucketLimiter:
    """Classic token-bucket rate limiter (round 16): ``rate`` units
    refill per second up to ``burst``; ``try_acquire`` is non-blocking
    — admission control sheds instead of waiting.  ``rate=None``
    disables limiting (always admits).  Thread-safe; refill is
    computed lazily from the monotonic clock, so an idle bucket needs
    no timer thread."""

    __slots__ = ("rate", "burst", "_level", "_t_last", "_lock")

    def __init__(self, rate: float | None, burst: float | None = None
                 ) -> None:
        self.rate = None if rate is None else float(rate)
        if self.rate is not None and self.rate <= 0:
            raise ValueError(f"need rate > 0 (or None), got {rate}")
        self.burst = float(burst if burst is not None
                           else (self.rate or 1.0))
        if self.burst <= 0:
            raise ValueError(f"need burst > 0, got {burst}")
        self._level = self.burst
        self._t_last = time.monotonic()
        self._lock = threading.Lock()

    def _refill(self, now: float) -> None:
        self._level = min(self.burst, self._level
                          + (now - self._t_last) * (self.rate or 0.0))
        self._t_last = now

    @property
    def level(self) -> float:
        """Current token level (telemetry)."""
        if self.rate is None:
            return self.burst
        with self._lock:
            self._refill(time.monotonic())
            return self._level

    def try_acquire(self, n: float = 1.0) -> bool:
        if self.rate is None:
            return True
        with self._lock:
            self._refill(time.monotonic())
            if self._level < n:
                return False
            self._level -= n
            return True


class PriorityQueue:
    """Pending requests in strict priority classes (round 16).

    Smaller ``priority`` dispatches first; FIFO within a class.  Works
    for any request object carrying ``priority``, ``n`` (rows/tokens)
    and ``t_submit``.  NOT thread-safe — callers hold their own
    condition lock (the batcher's ``_cond``)."""

    __slots__ = ("_classes",)

    def __init__(self) -> None:
        self._classes: dict[int, deque] = {}

    def append(self, req) -> None:
        prio = int(getattr(req, "priority", 0))
        self._classes.setdefault(prio, deque()).append(req)

    def appendleft(self, req) -> None:
        prio = int(getattr(req, "priority", 0))
        self._classes.setdefault(prio, deque()).appendleft(req)

    def requeue_front(self, reqs) -> None:
        """Retry path: requests re-enter the FRONT of their own
        class, original order preserved."""
        for req in reversed(list(reqs)):
            self.appendleft(req)

    def peek(self):
        """The request that would dispatch next (None when empty)."""
        for prio in sorted(self._classes):
            q = self._classes[prio]
            if q:
                return q[0]
        return None

    def popleft(self):
        for prio in sorted(self._classes):
            q = self._classes[prio]
            if q:
                req = q.popleft()
                if not q:
                    del self._classes[prio]
                return req
        raise IndexError("pop from empty PriorityQueue")

    def __len__(self) -> int:
        # telemetry readers (stats, gauges) call this without the
        # owner's lock — retry on a concurrent class-dict mutation
        try:
            return sum(len(q) for q in self._classes.values())
        except RuntimeError:
            return sum(len(q) for q in list(self._classes.values()))

    def __bool__(self) -> bool:
        try:
            return any(self._classes.values())
        except RuntimeError:
            return any(list(self._classes.values()))

    def __iter__(self):
        for prio in sorted(self._classes):
            yield from list(self._classes[prio])

    def oldest_t(self) -> float | None:
        """Submit time of the oldest pending request across ALL
        classes (admission-window clock + queue-age telemetry)."""
        heads = [q[0].t_submit for q in self._classes.values() if q]
        return min(heads) if heads else None

    def sweep(self, pred) -> list:
        """Remove and return every request matching ``pred``
        (deadline eviction)."""
        removed: list = []
        for prio in list(self._classes):
            q = self._classes[prio]
            hits = [r for r in q if pred(r)]
            if not hits:
                continue
            removed.extend(hits)
            keep = deque(r for r in q if not pred(r))
            if keep:
                self._classes[prio] = keep
            else:
                del self._classes[prio]
        return removed

    def rows_below(self, priority: int) -> int:
        """Rows held by classes STRICTLY lower-priority (numerically
        greater) than ``priority`` — what preemption could free."""
        return sum(r.n for prio, q in self._classes.items()
                   if prio > priority for r in q)

    def evict_below(self, priority: int, rows_needed: int) -> list:
        """Preemption: pop the NEWEST requests from the lowest class
        upward (strictly below ``priority``) until ``rows_needed``
        rows are freed; returns the evicted requests.  Newest-first
        within a class: the evicted waited least, so the least sunk
        queue time is thrown away."""
        evicted: list = []
        freed = 0
        for prio in sorted(self._classes, reverse=True):
            if prio <= priority:
                break
            q = self._classes[prio]
            while q and freed < rows_needed:
                req = q.pop()
                evicted.append(req)
                freed += req.n
            if not q:
                del self._classes[prio]
            if freed >= rows_needed:
                break
        return evicted


class Request:
    """One submitted batch of rows riding the queue."""

    __slots__ = ("x", "n", "future", "t_submit", "deadline", "attempts",
                 "tenant", "priority", "retry_budget", "trace")

    def __init__(self, x: np.ndarray,
                 deadline_ms: float | None = None,
                 tenant: str | None = None, priority: int = 0,
                 retry_budget: int | None = None) -> None:
        self.x = x
        self.n = int(x.shape[0])
        self.future: Future = Future()
        self.t_submit = time.monotonic()
        self.deadline = (None if deadline_ms is None
                         else self.t_submit + float(deadline_ms) / 1e3)
        self.attempts = 0
        self.tenant = tenant
        self.priority = int(priority)
        #: per-request override of the batcher's retry budget (the
        #: fleet sets this from the tenant's SLO class)
        self.retry_budget = retry_budget
        #: request-scoped trace (round 24): minted at submit (or
        #: adopted from the fleet router), rides the request through
        #: queue wait → coalesced dispatch
        self.trace = (_tracing.adopt_pending_trace()
                      or _tracing.new_request_trace(
                          "request", rows=self.n, tenant=tenant or "-"))
        self.trace.phase_begin("queue")

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now >= self.deadline


class ContinuousBatcher(Logger):
    """FIFO request queue + scheduler thread coalescing into batches."""

    def __init__(self, run_batch, *, max_batch: int,
                 max_delay_ms: float = 5.0, max_queue: int = 1024,
                 name: str = "serving", queue_gauge=None,
                 retry_budget: int = 0,
                 breaker_failure_rate: float = 0.5,
                 breaker_window: int = 8,
                 breaker_min_samples: int = 4,
                 breaker_cooldown_ms: float = 1000.0,
                 max_queue_age_ms: float | None = 10_000.0,
                 obs_id: str | None = None) -> None:
        super().__init__()
        if max_queue < max_batch:
            raise ValueError(
                f"max_queue ({max_queue}) must be >= max_batch "
                f"({max_batch}) or full buckets could never form")
        self._run_batch = run_batch
        self.max_batch = int(max_batch)
        self.max_delay = float(max_delay_ms) / 1000.0
        self.max_queue = int(max_queue)
        self.retry_budget = max(0, int(retry_budget))
        self.breaker_failure_rate = float(breaker_failure_rate)
        self.breaker_min_samples = int(breaker_min_samples)
        self.breaker_cooldown = float(breaker_cooldown_ms) / 1e3
        self.max_queue_age = (None if max_queue_age_ms is None
                              else float(max_queue_age_ms) / 1e3)
        #: optional observe.metrics Gauge tracking pending rows live
        #: (the engine passes its per-engine-labeled child)
        self._queue_gauge = queue_gauge
        #: per-engine label for the breaker/deadline registry series
        #: (None = bare batcher: counters tracked locally only)
        self._obs_id = obs_id
        self._m_state = (_metrics.serving_breaker_state(obs_id)
                         if obs_id else None)
        if self._m_state is not None:
            self._m_state.set(_STATE_CODE[_CLOSED])
            # pool="all": the one-shot batcher is a single queue —
            # the per-pool children (prefill/decode) belong to the
            # round-22 disaggregated engine
            _metrics.serving_queue_age_seconds(
                obs_id, pool="all").set_function(self.oldest_age_s)
        self._pending = PriorityQueue()
        self._rows = 0
        #: rows pending per tenant (per-tenant queue bounds)
        self._tenant_rows: dict[str, int] = {}
        self._cond = threading.Condition()
        self._stop = False
        self._flush_now = False
        # breaker state (all under _cond)
        self._state = _CLOSED
        self._opened_at = 0.0
        self._outcomes: deque[bool] = deque(maxlen=int(breaker_window))
        # plain counters (stats views; registry series ride obs_id)
        self.expired_total = 0
        self.shed_total = 0
        self.retries_total = 0
        self._thread = threading.Thread(
            target=self._loop, name=f"{name}-batcher", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------------
    @property
    def queue_rows(self) -> int:
        """Rows currently pending (telemetry; racy by nature)."""
        return self._rows

    @property
    def breaker_state(self) -> str:
        return self._state

    def tenant_rows(self, tenant: str) -> int:
        """Rows currently pending for one tenant (telemetry)."""
        return self._tenant_rows.get(tenant, 0)

    def oldest_age_s(self) -> float:
        """Age of the oldest pending request across all priority
        classes (0 when idle; telemetry — the breaker's stall-trip
        watches the highest-priority head instead, see
        :meth:`_breaker_tick`)."""
        try:
            oldest = self._pending.oldest_t()
        except RuntimeError:  # classes dict mutated mid-iteration
            return 0.0
        if oldest is None:
            return 0.0
        return max(0.0, time.monotonic() - oldest)

    # -- row accounting (call under _cond) ------------------------------
    def _account_add(self, req: Request) -> None:
        self._rows += req.n
        if req.tenant is not None:
            self._tenant_rows[req.tenant] = \
                self._tenant_rows.get(req.tenant, 0) + req.n
        if self._queue_gauge is not None:
            self._queue_gauge.set(self._rows)

    def _account_remove(self, req: Request) -> None:
        self._rows -= req.n
        if req.tenant is not None:
            left = self._tenant_rows.get(req.tenant, 0) - req.n
            if left > 0:
                self._tenant_rows[req.tenant] = left
            else:
                self._tenant_rows.pop(req.tenant, None)
        if self._queue_gauge is not None:
            self._queue_gauge.set(self._rows)

    # ------------------------------------------------------------------
    # circuit breaker (call under _cond)
    # ------------------------------------------------------------------
    def _transition(self, state: str) -> None:
        if state == self._state:
            return
        self.warning("circuit breaker %s → %s", self._state, state)
        _recorder.record("breaker", engine=self._obs_id or "batcher",
                         src=self._state, to=state)
        self._state = state
        if state == _OPEN:
            self._opened_at = time.monotonic()
        if self._m_state is not None:
            self._m_state.set(_STATE_CODE[state])
        if self._obs_id:
            _metrics.serving_breaker_transitions(self._obs_id,
                                                 state).inc()

    def _trip(self, why: str) -> None:
        if self._state != _OPEN:
            self.warning("circuit breaker tripped: %s", why)
            self._transition(_OPEN)
            self._outcomes.clear()
            # a stale queue is a stall: force the pending prefix out
            # rather than letting it age further behind the window
            self._flush_now = True
            self._cond.notify_all()

    def _breaker_tick(self, now: float) -> None:
        """Open → half-open after the cooldown; age-trip when the
        HIGHEST-priority pending head exceeds the stall threshold.
        The stall-trip exists to detect a wedged dispatch path: under
        priority scheduling a starved low class ages unboundedly while
        the device is perfectly healthy, so only the head that would
        dispatch next is evidence of a stall — a starved class is
        handled by its own deadlines, bounds and preemption."""
        if self._state == _OPEN \
                and now - self._opened_at >= self.breaker_cooldown:
            self._transition(_HALF_OPEN)
        head = self._pending.peek()
        if (self._state == _CLOSED and self.max_queue_age is not None
                and head is not None
                and now - head.t_submit > self.max_queue_age):
            self._trip(f"next-dispatch request pending "
                       f"{now - head.t_submit:.1f}s "
                       f"(> {self.max_queue_age:.1f}s)")

    def _record_outcome(self, ok: bool) -> None:
        with self._cond:
            if self._state == _HALF_OPEN:
                # the probe decides: healthy again, or back to shedding
                self._transition(_CLOSED if ok else _OPEN)
                self._outcomes.clear()
                return
            self._outcomes.append(ok)
            n = len(self._outcomes)
            if n >= self.breaker_min_samples:
                failure_rate = self._outcomes.count(False) / n
                if failure_rate >= self.breaker_failure_rate:
                    self._trip(f"failure rate {failure_rate:.0%} over "
                               f"last {n} dispatches")

    # ------------------------------------------------------------------
    def submit(self, x: np.ndarray,
               deadline_ms: float | None = None, *,
               tenant: str | None = None, priority: int = 0,
               retry_budget: int | None = None,
               tenant_max_rows: int | None = None) -> Future:
        """Enqueue a request; returns the future of its output rows.

        ``priority`` (smaller = more important) selects the priority
        class; ``tenant`` labels the rows for per-tenant bounds
        (``tenant_max_rows`` caps THIS tenant's pending rows);
        ``retry_budget`` overrides the engine default per request.

        Raises :class:`QueueFull` when the bounded queue has no room
        (after preempting strictly lower-priority rows if that frees
        enough), :class:`Overloaded` while the breaker sheds load,
        :class:`DeadlineExceeded` for a non-positive deadline, and
        ``RuntimeError`` after shutdown."""
        req = Request(x, deadline_ms=deadline_ms, tenant=tenant,
                      priority=priority, retry_budget=retry_budget)
        if req.n < 1 or req.n > self.max_batch:
            raise ValueError(
                f"request of {req.n} rows outside 1..{self.max_batch} "
                f"(max_batch) — split it client-side")
        if deadline_ms is not None and deadline_ms <= 0:
            raise DeadlineExceeded(
                f"deadline_ms={deadline_ms} already expired at submit")
        preempted: list[Request] = []
        with self._cond:
            if self._stop:
                raise RuntimeError("batcher is shut down")
            self._breaker_tick(time.monotonic())
            if self._state == _OPEN:
                self.shed_total += 1
                if self._obs_id:
                    _metrics.serving_requests(self._obs_id,
                                              "shed").inc()
                req.trace.event("breaker_shed",
                                engine=self._obs_id or "batcher")
                self._finish_trace(req, "shed")
                raise Overloaded(
                    "circuit breaker open — load shed (retry after "
                    f"{self.breaker_cooldown * 1e3:.0f}ms)")
            if tenant_max_rows is not None and tenant is not None \
                    and self.tenant_rows(tenant) + req.n \
                    > int(tenant_max_rows):
                self._finish_trace(req, "shed")
                raise QueueFull(
                    f"tenant '{tenant}' queue bound reached "
                    f"({self.tenant_rows(tenant)} rows pending, "
                    f"limit {tenant_max_rows})")
            if self._rows + req.n > self.max_queue:
                # preemptive admission: shed the NEWEST strictly
                # lower-priority rows when that fully makes room — a
                # flooding class absorbs its own overload instead of
                # bouncing higher-priority traffic
                need = self._rows + req.n - self.max_queue
                if self._pending.rows_below(req.priority) >= need:
                    preempted = self._pending.evict_below(req.priority,
                                                          need)
                    for ev in preempted:
                        self._account_remove(ev)
                        self.shed_total += 1
                        if self._obs_id:
                            _metrics.serving_requests(
                                self._obs_id, "shed").inc()
                else:
                    self._finish_trace(req, "shed")
                    raise QueueFull(
                        f"serving queue full ({self._rows} rows "
                        f"pending, limit {self.max_queue})")
            self._pending.append(req)
            self._account_add(req)
            self._cond.notify_all()
        # fail preempted futures OUTSIDE the lock: done-callbacks (the
        # fleet's per-tenant outcome accounting) must never run under
        # the batcher condition
        for ev in preempted:
            ev.trace.event("preempted",
                           engine=self._obs_id or "batcher")
            self._finish_trace(ev, "shed")
            if not ev.future.done():
                ev.future.set_exception(Overloaded(
                    "preempted by higher-priority traffic while the "
                    "queue was full"))
        return req.future

    def _finish_trace(self, req: Request, outcome: str) -> None:
        if self._obs_id:
            _metrics.trace_requests(self._obs_id, outcome).inc()
        req.trace.finish(outcome)

    def flush(self) -> None:
        """Dispatch whatever is pending without waiting out the
        admission window (tests, graceful drain points)."""
        with self._cond:
            self._flush_now = True
            self._cond.notify_all()

    def shutdown(self, timeout: float = 30.0) -> None:
        """Stop the scheduler after draining everything pending."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout=timeout)

    # ------------------------------------------------------------------
    def _evict_expired(self, now: float) -> None:
        """Fail-fast every pending request whose deadline passed —
        they are removed BEFORE coalescing, so a timed-out request
        never occupies bucket rows.  Call under ``_cond``."""
        if not any(r.deadline is not None for r in self._pending):
            return
        expired = self._pending.sweep(lambda r: r.expired(now))
        for req in expired:
            self._account_remove(req)
            self.expired_total += 1
            if self._obs_id:
                _metrics.serving_requests(self._obs_id,
                                          "expired").inc()
            req.trace.event("deadline_evicted",
                            engine=self._obs_id or "batcher")
            self._finish_trace(req, "expired")
            req.future.set_exception(DeadlineExceeded(
                f"deadline passed after "
                f"{(now - req.t_submit) * 1e3:.0f}ms in queue"))

    def _wait_timeout(self, now: float) -> float:
        """How long the admission wait may sleep: bounded by the
        window remainder, the nearest pending deadline, and a 250 ms
        housekeeping tick (age-trip + eviction responsiveness)."""
        oldest = self._pending.oldest_t()
        remain = (oldest if oldest is not None else now) \
            + self.max_delay - now
        deadlines = [r.deadline for r in self._pending
                     if r.deadline is not None]
        if deadlines:
            remain = min(remain, max(0.0, min(deadlines) - now))
        if self.max_queue_age is not None:
            remain = min(remain, 0.25)
        return remain

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._stop:
                    self._cond.wait()
                if not self._pending and self._stop:
                    return
                # admission window: sleep until the batch fills, the
                # oldest request's delay budget runs out, or someone
                # forces a flush; expired requests are swept out and
                # the breaker's stall detector runs on each tick
                while not self._stop and not self._flush_now:
                    now = time.monotonic()
                    self._evict_expired(now)
                    self._breaker_tick(now)
                    if not self._pending:
                        break
                    if self._rows >= self.max_batch:
                        break
                    remain = self._wait_timeout(now)
                    if remain <= 0:
                        break
                    self._cond.wait(timeout=remain)
                self._evict_expired(time.monotonic())
                batch: list[Request] = []
                rows = 0
                while self._pending:
                    # strict priority order: the highest class's FIFO
                    # prefix fills the bucket first; stop at the first
                    # head that does not fit (no head-of-line skip —
                    # per-class ordering holds)
                    nxt = self._pending.peek()
                    if rows + nxt.n > self.max_batch:
                        break
                    req = self._pending.popleft()
                    rows += req.n
                    batch.append(req)
                    self._account_remove(req)
                    req.trace.phase_end("queue",
                                        engine=self._obs_id or "batcher")
                    req.trace.phase_begin("decode")
                self._flush_now = False
                self._cond.notify_all()
            if not batch:  # everything expired / spurious wakeup
                continue
            try:
                with _tracing.TRACER.span("serve_batch", cat="serving",
                                          requests=len(batch),
                                          rows=rows):
                    self._run_batch(batch)
            except Exception as exc:  # noqa: BLE001 - isolate the batch
                self._record_outcome(False)
                self._dispatch_failed(batch, exc)
            else:
                self._record_outcome(True)
                for req in batch:
                    req.trace.phase_end("decode",
                                        engine=self._obs_id or "batcher")
                    self._finish_trace(req, "ok")
                retried = sum(1 for r in batch if r.attempts)
                if retried:
                    _metrics.recoveries("serving_retry").inc(retried)

    def _dispatch_failed(self, batch: list[Request], exc) -> None:
        """Retry-budget accounting: requests with budget left re-enter
        the FRONT of their own priority class (order preserved); the
        rest fail.  A per-request ``retry_budget`` (the fleet's
        per-tenant SLO) overrides the engine default.  During shutdown
        nothing retries — the drain must terminate."""
        retry: list[Request] = []
        now = time.monotonic()
        with self._cond:
            for req in batch:
                budget = (req.retry_budget if req.retry_budget
                          is not None else self.retry_budget)
                if (not self._stop and req.attempts < budget
                        and not req.expired(now)):
                    req.attempts += 1
                    retry.append(req)
            if retry:
                self.retries_total += len(retry)
                if self._obs_id:
                    _metrics.serving_requests(
                        self._obs_id, "retried").inc(len(retry))
                self._pending.requeue_front(retry)
                for req in retry:
                    self._account_add(req)
                    req.trace.event("dispatch_retry",
                                    engine=self._obs_id or "batcher",
                                    attempt=req.attempts)
                    req.trace.phase_begin("queue")
                self._cond.notify_all()
        failed = [r for r in batch if r not in retry]
        if failed:
            self.warning("batch of %d requests failed: %s",
                         len(failed), exc)
        for req in failed:
            self._finish_trace(req, "failed")
            if not req.future.done():
                req.future.set_exception(exc)
