"""Continuous batcher: an async request queue drained into buckets.

The Orca insight (Yu et al., OSDI 2022) applied to this framework's
forward path: requests arrive asynchronously and individually, but the
accelerator wants large batches — so a scheduler thread coalesces
whatever is pending into one batch per dispatch, instead of locking
the serving loop to fixed request boundaries.

Policy (all knobs on the constructor):

- a flush happens when pending rows reach ``max_batch`` (full bucket)
  OR the **oldest** pending request has waited ``max_delay_ms`` (the
  admission window: a lone size-1 request is never parked behind an
  empty queue for long);
- coalescing is FIFO-prefix: requests keep arrival order and are never
  reordered past each other, so per-caller ordering holds;
- the queue is bounded in ROWS (``max_queue``): when it is full,
  :meth:`submit` raises :class:`QueueFull` immediately — callers see
  backpressure, the server never queues itself into OOM;
- shutdown drains: everything admitted before :meth:`shutdown` is
  served before the scheduler exits.

The batcher knows nothing about models or devices — it hands each
coalesced batch (a list of :class:`Request`) to the ``run_batch``
callable and that callable resolves the futures.  Exceptions from
``run_batch`` fail that batch's futures and the scheduler keeps going.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future

import numpy as np

from znicz_tpu.observe import tracing as _tracing
from znicz_tpu.utils.logger import Logger


class QueueFull(RuntimeError):
    """Raised by :meth:`ContinuousBatcher.submit` when the bounded
    request queue has no room — the caller's backpressure signal."""


class Request:
    """One submitted batch of rows riding the queue."""

    __slots__ = ("x", "n", "future", "t_submit")

    def __init__(self, x: np.ndarray) -> None:
        self.x = x
        self.n = int(x.shape[0])
        self.future: Future = Future()
        self.t_submit = time.monotonic()


class ContinuousBatcher(Logger):
    """FIFO request queue + scheduler thread coalescing into batches."""

    def __init__(self, run_batch, *, max_batch: int,
                 max_delay_ms: float = 5.0, max_queue: int = 1024,
                 name: str = "serving", queue_gauge=None) -> None:
        super().__init__()
        if max_queue < max_batch:
            raise ValueError(
                f"max_queue ({max_queue}) must be >= max_batch "
                f"({max_batch}) or full buckets could never form")
        self._run_batch = run_batch
        self.max_batch = int(max_batch)
        self.max_delay = float(max_delay_ms) / 1000.0
        self.max_queue = int(max_queue)
        #: optional observe.metrics Gauge tracking pending rows live
        #: (the engine passes its per-engine-labeled child)
        self._queue_gauge = queue_gauge
        self._pending: deque[Request] = deque()
        self._rows = 0
        self._cond = threading.Condition()
        self._stop = False
        self._flush_now = False
        self._thread = threading.Thread(
            target=self._loop, name=f"{name}-batcher", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------------
    @property
    def queue_rows(self) -> int:
        """Rows currently pending (telemetry; racy by nature)."""
        return self._rows

    def submit(self, x: np.ndarray) -> Future:
        """Enqueue a request; returns the future of its output rows.

        Raises :class:`QueueFull` when the bounded queue has no room
        for ``x``'s rows, and ``RuntimeError`` after shutdown."""
        req = Request(x)
        if req.n < 1 or req.n > self.max_batch:
            raise ValueError(
                f"request of {req.n} rows outside 1..{self.max_batch} "
                f"(max_batch) — split it client-side")
        with self._cond:
            if self._stop:
                raise RuntimeError("batcher is shut down")
            if self._rows + req.n > self.max_queue:
                raise QueueFull(
                    f"serving queue full ({self._rows} rows pending, "
                    f"limit {self.max_queue})")
            self._pending.append(req)
            self._rows += req.n
            if self._queue_gauge is not None:
                self._queue_gauge.set(self._rows)
            self._cond.notify_all()
        return req.future

    def flush(self) -> None:
        """Dispatch whatever is pending without waiting out the
        admission window (tests, graceful drain points)."""
        with self._cond:
            self._flush_now = True
            self._cond.notify_all()

    def shutdown(self, timeout: float = 30.0) -> None:
        """Stop the scheduler after draining everything pending."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout=timeout)

    # ------------------------------------------------------------------
    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._stop:
                    self._cond.wait()
                if not self._pending and self._stop:
                    return
                # admission window: sleep until the batch fills, the
                # oldest request's delay budget runs out, or someone
                # forces a flush
                while (self._rows < self.max_batch and not self._stop
                       and not self._flush_now):
                    remain = (self._pending[0].t_submit + self.max_delay
                              - time.monotonic())
                    if remain <= 0:
                        break
                    self._cond.wait(timeout=remain)
                batch: list[Request] = []
                rows = 0
                while (self._pending
                       and rows + self._pending[0].n <= self.max_batch):
                    req = self._pending.popleft()
                    rows += req.n
                    batch.append(req)
                self._rows -= rows
                if self._queue_gauge is not None:
                    self._queue_gauge.set(self._rows)
                self._flush_now = False
                self._cond.notify_all()
            if not batch:  # pragma: no cover - spurious wakeup guard
                continue
            try:
                with _tracing.TRACER.span("serve_batch", cat="serving",
                                          requests=len(batch),
                                          rows=rows):
                    self._run_batch(batch)
            except Exception as exc:  # noqa: BLE001 - fail THIS batch only
                self.warning("batch of %d requests failed: %s",
                             len(batch), exc)
                for req in batch:
                    if not req.future.done():
                        req.future.set_exception(exc)
