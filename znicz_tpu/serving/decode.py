"""Autoregressive decode serving: KV-cache + prefill/decode AOT split
+ continuous token batching.

The round-8 engine scores fixed-shape one-shot requests; this module
is the *generation* half of the serving story (ROADMAP item 2 — the
heaviest-traffic scenario a millions-of-users deployment runs).  It
converts any exported causal LM bundle (``manifest["kind"] == "lm"``:
token-first chain of embedding / pos_encoding / causal attention /
LSTM, a position-independent head) into a continuous-batching token
server, built from three pieces:

1. **KV cache** (:class:`KVCache`) — per-replica device buffers
   preallocated at :meth:`DecodeModel.warmup`: one (S+1, maxT, H, Dh)
   K and V page array per attention layer and one (S+1, H) carry pair
   per LSTM layer, where S is ``max_slots`` sequence slots (+1 scratch
   row that absorbs padded decode lanes).  Pages are *functionally*
   updated by the decode program and donated back, so on
   donation-capable platforms a warmed decode loop mutates HBM in
   place and allocates nothing per token.

2. **Prefill / decode AOT split** (:class:`DecodeModel`) — two
   separate program families, both real ``jit().lower().compile()``
   AOT like the round-8 ladder:

   - *prefill*, bucketed on **prompt length** via the same
     ``serving/buckets.py`` ladder math applied to the T axis
     (``prompt_align·2^k``): runs the full causal forward over the
     padded prompt, writes every position's K/V (or the masked LSTM
     carry) into the request's slot, and returns the last real
     position's logits — the first token;
   - *decode*, bucketed on **live-batch size**: one token for every
     in-flight sequence per dispatch — embedding gather → positional
     offset add → per-layer cached step
     (``MultiHeadAttention.xla_decode_step`` /
     ``LSTM.xla_decode_step``) → head logits — with ragged per-lane
     position indices, so sequences at different depths share one
     program.

   Warmed, the token loop performs ZERO XLA compiles
   (``znicz_xla_compiles_total{site=serving-prefill|serving-decode}``
   stays flat — pinned by tests/test_retrace_guard.py).

3. **Continuous token batching** (:class:`DecodeEngine`) — the Orca
   iteration-level insight applied to generation: the scheduler
   admits queued prompts into the *in-flight* decode batch between
   token steps (``admission="continuous"``; ``"static"`` keeps the
   run-to-completion behavior as the measured A/B arm in
   serve_bench), and evicts slots the moment a sequence finishes
   (EOS, token budget, or the bucketed max-T page boundary) so a
   long straggler never holds the batch hostage.

Round 15 rebuilds the decode *data plane* around a **paged KV-cache**
(``engine.paged_kv``, default on; the flat per-slot layout above stays
as the measured A/B arm), the vLLM PagedAttention idea (Kwon et al.
2023) expressed in XLA terms:

4. **Paged KV-cache** (:class:`PagedKVCache`) — K/V live in a shared
   page *pool* of fixed ``kv_page_tokens``-token blocks addressed
   through a per-sequence block table, so a sequence holds exactly the
   pages its length needs instead of reserving ``max_t`` rows, live
   capacity is bounded by **tokens** (``pool_tokens``), not slots, and
   attention programs are bucketed on the **block count** — a short
   sequence's decode step reads only the pages it occupies, not the
   full ``max_t`` reservation the flat layout gathers every token.

5. **Prefix sharing** (:class:`PrefixCache`) — prompts are hashed
   block-by-block into a radix trie at admission; requests with a
   common prompt prefix (the dominant system-prompt traffic shape)
   *share* the prefix's full pages by reference (refcounted), a
   partially-matched boundary block is **copied on write** before the
   divergent tail lands, and the tail alone pays prefill.  Pages are
   pinned by the trie, evicted LRU under pool pressure, and the whole
   cache invalidates on a weight swap (cached K/V are a function of
   the weights).

6. **Speculative decoding** — a small *drafter* bundle (a population
   member trained by the round-14 engine and published through the
   round-13 pipeline) proposes ``spec_draft_k`` greedy tokens per
   step; the big model verifies the whole window in ONE batched
   forward (:meth:`DecodeModel.run_verify`) and accepts per
   Leviathan's rule — greedy arms stay token-identical to
   non-speculative decoding by construction, temperature arms use the
   exact rejection-sampling correction.

Telemetry splits decode latency into its two canonical halves —
``znicz_serving_ttft_seconds`` (queue + prefill + first sample) and
``znicz_serving_token_seconds`` (steady-state cadence) — because the
two move independently: admission policy moves TTFT, cache residency
moves per-token.  TTFT clocks stamp from **admission-eligible** time:
a swap drain's admission pause (accumulated in
``znicz_swap_pause_seconds_total``) is excluded, so soak histograms
measure serving, not the drain policy.  Paged state rides
``znicz_kv_pages_{total,used}``, ``znicz_prefix_cache_total{hit|miss}``
and ``znicz_spec_tokens_total{accepted|rejected}``.  Resilience
(round 11 carried forward): ``deadline_ms`` applies to **TTFT** — a
prompt still queued past its deadline is evicted before prefill and
never occupies a slot — and the circuit breaker sheds *new prompts*
with fast :class:`Overloaded` replies while in-flight decodes drain to
completion; **page-pool exhaustion** trips the same breaker, so a
token-capacity overload sheds exactly like a failure-rate overload
while draining lanes release their pages.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from concurrent.futures import Future

import numpy as np

from znicz_tpu.observe import metrics as _metrics
from znicz_tpu.observe import recorder as _recorder
from znicz_tpu.observe import tracing as _tracing
from znicz_tpu.resilience import faults as _faults
from znicz_tpu.serving.batcher import (_CLOSED, _HALF_OPEN, _OPEN,
                                       _STATE_CODE, DeadlineExceeded,
                                       Overloaded, PriorityQueue,
                                       QueueFull)
from znicz_tpu.serving.buckets import bucket_for, ladder, next_pow2
from znicz_tpu.utils.logger import Logger

__all__ = ["DecodeModel", "DecodeEngine", "KVCache", "PagedKVCache",
           "PrefixCache", "PoolExhausted"]


class PoolExhausted(RuntimeError):
    """The paged KV pool has no free page for a required block.  The
    engine translates this into breaker load-shedding (queued prompts)
    or a graceful force-finish (an in-flight lane crossing a block
    boundary) — it never kills neighbors."""

#: distinguishes same-named engines in the registry's labels
_DECODE_SEQ = itertools.count()

#: layer kinds the decode planner knows how to step incrementally
_SEQ_KINDS = ("embedding", "pos_encoding", "attention", "lstm")
_HEAD_KINDS = ("all2all", "all2all_tanh", "all2all_relu",
               "all2all_str", "all2all_sigmoid", "softmax")


class _Op:
    """One planned chain step: the unit (config carrier), the export
    KEYS of its weight leaves, and — for stateful layers — its cache
    array indices.  Weights themselves are NOT baked into the op: the
    traced programs take them as a call-time operand pytree, which is
    what lets :meth:`DecodeModel.swap_weights` replace them without a
    single recompile."""

    __slots__ = ("kind", "unit", "wkeys", "aux", "table")

    def __init__(self, kind, unit, wkeys=(), aux=None, table=None):
        self.kind = kind
        self.unit = unit
        self.wkeys = tuple(wkeys)  # export keys (layer<i>_<attr>)
        self.aux = aux or {}       # cache indices etc.
        self.table = table         # pos_encoding: baked (maxT, D) table


def _dq_leaves(w):
    """Dequantize one op's weight-leaf tuple inside a traced body:
    ``(q int8, scale f32)`` pairs (round-21 int8 bundles) expand to
    f32 on load — exact arithmetic, so the program matches the
    host-side dequantized oracle bitwise; plain leaves pass through."""
    import jax.numpy as jnp
    return tuple(
        leaf[0].astype(jnp.float32) * leaf[1]
        if isinstance(leaf, tuple) else leaf
        for leaf in w)


class KVCache:
    """The preallocated decode state for one replica: the page/carry
    arrays (functionally threaded through every program call) plus the
    host-side slot free list.

    Slot reuse needs no zeroing: prefill overwrites ``[0, t_bucket)``
    of a reused slot, and every attention step masks positions
    ``> pos``, so a prior tenant's rows beyond the new sequence's live
    prefix are unreachable by construction (pinned by
    tests/test_decode.py's eviction-reuse case).
    """

    def __init__(self, specs: list[tuple[str, tuple]], max_slots: int,
                 dtype=np.float32) -> None:
        import jax.numpy as jnp
        self.max_slots = int(max_slots)
        #: scratch row absorbing padded decode lanes (their scattered
        #: writes must land somewhere that is never a live sequence)
        self.trash_slot = self.max_slots
        self.specs = list(specs)
        self.arrays: tuple = tuple(
            jnp.zeros((self.max_slots + 1,) + tuple(shape), dtype)
            for _name, shape in specs)
        self._free = list(range(self.max_slots))

    @property
    def free_slots(self) -> int:
        return len(self._free)

    def acquire(self) -> int:
        return self._free.pop()

    def release(self, slot: int) -> None:
        self._free.append(slot)

    def nbytes(self) -> int:
        return int(sum(a.size * a.dtype.itemsize for a in self.arrays))


class PagedKVCache:
    """Paged decode state: per-attention-layer K/V page POOLS plus the
    host-side block tables, refcounts and free lists.

    Geometry: each pool array is ``(pool_pages + 1, page_tokens, H,
    Dh)`` — the last row is the **trash page** where padded lanes and
    padded window positions scatter their garbage writes.  A sequence
    in slot ``s`` owns ``tables[s]``: one page id per
    ``page_tokens``-token block of its positions, ``trash_page`` where
    no block is allocated.  LSTM carries (``kind="slot"`` specs) stay
    slot-indexed exactly like the flat cache — they are O(H) per
    sequence, not O(T), so paging buys them nothing.

    Sharing: a page's ``ref`` counts every holder — each sequence
    whose table maps a block to it, plus the prefix trie's pin.  Pages
    free when the count hits zero.  Shared pages (``ref > 1`` or
    trie-pinned) are never written: writes always land at a
    sequence's *append* position, past every shared full block, and
    the boundary block of a partial prefix match is copied
    (:meth:`DecodeModel.copy_page`) before the divergent tail lands —
    the copy-on-write contract tests/test_paged_decode.py pins.

    All mutating calls happen on the scheduler thread (same
    single-writer discipline as the flat cache); the gauges read
    integers racily, which is fine for telemetry.
    """

    def __init__(self, specs: list[tuple],
                 max_slots: int, page_tokens: int, max_blocks: int,
                 pool_pages: int, dtype=np.float32) -> None:
        # specs: (name, kind, shape) or (name, kind, shape, dtype) —
        # the 4-tuple form (round 21) gives one pool its own dtype, so
        # int8 K/V pages and their f32 per-(token, head) scale pools
        # coexist in the same cache and share page ids / COW / trash
        # semantics
        import jax.numpy as jnp
        self.max_slots = int(max_slots)
        self.trash_slot = self.max_slots
        self.page_tokens = int(page_tokens)
        self.max_blocks = int(max_blocks)
        self.pool_pages = int(pool_pages)
        self.trash_page = self.pool_pages
        self.specs = list(specs)
        arrays = []
        for spec in specs:
            kind, shape = spec[1], spec[2]
            sdtype = spec[3] if len(spec) > 3 else dtype
            if kind == "page":
                arrays.append(jnp.zeros(
                    (self.pool_pages + 1, self.page_tokens)
                    + tuple(shape), sdtype))
            else:  # slot-indexed (LSTM carries)
                arrays.append(jnp.zeros(
                    (self.max_slots + 1,) + tuple(shape), sdtype))
        self.arrays: tuple = tuple(arrays)
        #: indices (into ``arrays``) of the page pools — the leaves
        #: :meth:`DecodeModel.copy_page` must copy on a COW
        self.pool_indices = tuple(i for i, s in enumerate(specs)
                                  if s[1] == "page")
        #: slot-indexed leaves (LSTM carries) — the rows a
        #: prefill→decode handoff must carry alongside the pages
        self.slot_indices = tuple(i for i, s in enumerate(specs)
                                  if s[1] != "page")
        self.tables = np.full((self.max_slots + 1, self.max_blocks),
                              self.trash_page, np.int32)
        self.ref = np.zeros(self.pool_pages, np.int64)
        self._free_pages = list(range(self.pool_pages - 1, -1, -1))
        self._free = list(range(self.max_slots))

    # -- slots (same protocol as the flat cache) -----------------------
    @property
    def free_slots(self) -> int:
        return len(self._free)

    def acquire(self) -> int:
        return self._free.pop()

    def release(self, slot: int) -> None:
        self._free.append(slot)

    # -- pages ---------------------------------------------------------
    @property
    def free_pages(self) -> int:
        return len(self._free_pages)

    def pages_used(self) -> int:
        return self.pool_pages - len(self._free_pages)

    def alloc_page(self) -> int:
        if not self._free_pages:
            raise PoolExhausted(
                f"KV page pool exhausted ({self.pool_pages} pages "
                f"x {self.page_tokens} tokens all held)")
        pid = self._free_pages.pop()
        self.ref[pid] = 1
        return pid

    def free_page(self, pid: int) -> None:
        self._free_pages.append(pid)

    def ref_dec(self, pid: int) -> None:
        self.ref[pid] -= 1
        if self.ref[pid] == 0:
            self.free_page(pid)

    def share_block(self, slot: int, block: int, pid: int) -> None:
        """Map ``block`` of ``slot`` to an EXISTING page by reference
        (prefix sharing).  The page must be live (ref > 0): a
        zero-ref page sits on the free list, and re-refing it here
        without unlinking it would let ``alloc_page`` hand the same
        page to another sequence — callers must pin matched pages
        before anything (eviction) can drop their last holder."""
        assert int(self.ref[pid]) > 0, \
            f"share_block: page {pid} is on the free list"
        self.tables[slot, block] = pid
        self.ref[pid] += 1

    def new_block(self, slot: int, block: int) -> int:
        """Allocate a fresh private page for ``block`` of ``slot``."""
        pid = self.alloc_page()
        self.tables[slot, block] = pid
        return pid

    def blocks_of(self, slot: int) -> list[int]:
        return [int(p) for p in self.tables[slot]
                if p != self.trash_page]

    def writable(self, slot: int, block: int) -> bool:
        """May ``slot`` write into ``block``'s page?  True iff the
        page is private (ref exactly 1 — this sequence, no sharers,
        no trie pin)."""
        pid = int(self.tables[slot, block])
        return pid != self.trash_page and int(self.ref[pid]) == 1

    def release_slot_pages(self, slot: int) -> None:
        """Drop every page reference ``slot`` holds (pages free when
        their last holder lets go) and reset its table row."""
        for block in range(self.max_blocks):
            pid = int(self.tables[slot, block])
            if pid != self.trash_page:
                self.ref_dec(pid)
        self.tables[slot] = self.trash_page

    def table_operand(self, slot: int, nb: int) -> np.ndarray:
        """The (nb+1,) int32 table row a program dispatch reads: the
        first ``nb`` block entries plus the trash page as the padded
        write sink."""
        out = np.empty(nb + 1, np.int32)
        out[:nb] = self.tables[slot, :nb]
        out[nb] = self.trash_page
        return out

    def trash_operand(self, nb: int) -> np.ndarray:
        return np.full(nb + 1, self.trash_page, np.int32)

    def nbytes(self) -> int:
        return int(sum(a.size * a.dtype.itemsize for a in self.arrays))


class _TrieNode:
    __slots__ = ("key", "page", "host", "children", "parent",
                 "last_use")

    def __init__(self, key, page, parent) -> None:
        self.key = key          # the block's token ids (bytes key)
        self.page = page        # HBM page id, or None while spilled
        self.host = None        # host-tier frame id, or None (round
        #                         22: a block lives in EXACTLY one
        #                         tier — page XOR host)
        self.children: dict = {}
        self.parent = parent
        self.last_use = 0


class PrefixCache:
    """Radix trie over block-aligned prompt prefixes.

    Keys are the raw token ids of one full ``page_tokens`` block
    (hashed by dict machinery); a path root→node spells a block-aligned
    prompt prefix and carries one page id per block.  Matching at
    admission walks full blocks, then refines into the boundary block:
    the longest token-level common prefix with any child selects a
    copy-on-write donor, so divergence mid-block still reuses the
    shared positions' K/V.  Matches are capped at ``len(prompt) - 1``
    tokens — the last prompt position is always recomputed, because
    the first sampled token needs its logits.

    Every node pins its page with one refcount; :meth:`evict` walks
    leaves in LRU order under pool pressure, and :meth:`clear` drops
    everything (a weight swap invalidates all cached K/V)."""

    def __init__(self, page_tokens: int) -> None:
        self.page_tokens = int(page_tokens)
        self.root = _TrieNode(None, None, None)
        self.nodes = 0
        self._clock = 0

    def _tick(self, node: _TrieNode) -> None:
        self._clock += 1
        node.last_use = self._clock

    @staticmethod
    def _key(tokens: np.ndarray) -> bytes:
        return np.ascontiguousarray(tokens, np.int32).tobytes()

    def match_nodes(self, tokens: np.ndarray
                    ) -> tuple[list, int, tuple | None]:
        """Longest cached prefix of ``tokens`` (capped at ``n-1``):
        returns ``(full_block_nodes, matched_tokens, cow)`` where
        ``cow`` is ``(donor_node, extra_tokens)`` for a partial
        boundary-block match (``matched_tokens`` already includes
        ``extra_tokens``) or ``None``.  Nodes — not bare page ids —
        because a matched block may be SPILLED to the host tier
        (``node.page is None``): the caller restores it before
        sharing (round 22)."""
        n = int(tokens.shape[0])
        ptok = self.page_tokens
        node = self.root
        nodes: list[_TrieNode] = []
        matched = 0
        while matched + ptok <= n - 1:
            child = node.children.get(
                self._key(tokens[matched:matched + ptok]))
            if child is None:
                break
            node = child
            self._tick(node)
            nodes.append(node)
            matched += ptok
        # boundary refinement: the longest token-level common prefix
        # with any child of the last matched node
        tail = tokens[matched:min(n - 1, matched + ptok)]
        best, best_common = None, 0
        if len(tail) > 0:
            for child in node.children.values():
                key = np.frombuffer(child.key, np.int32)
                m = int(np.argmin(np.equal(
                    key[:len(tail)], tail).astype(np.int8))) \
                    if not np.array_equal(key[:len(tail)], tail) \
                    else len(tail)
                if m > best_common:
                    best, best_common = child, m
        if best is not None and best_common > 0:
            self._tick(best)
            return nodes, matched + best_common, (best, best_common)
        return nodes, matched, None

    def match(self, tokens: np.ndarray
              ) -> tuple[list[int], int, tuple | None]:
        """Page-id view of :meth:`match_nodes` for HBM-only callers
        (no spill tier: every matched node is resident)."""
        nodes, matched, cow = self.match_nodes(tokens)
        return ([node.page for node in nodes], matched,
                None if cow is None else (cow[0].page, cow[1]))

    def insert(self, tokens: np.ndarray, table_row: np.ndarray,
               cache: PagedKVCache) -> int:
        """Register every FULL prompt block of ``tokens`` (pages from
        the sequence's ``table_row``); new nodes pin their page with
        one extra refcount.  Returns nodes added."""
        n = int(tokens.shape[0])
        ptok = self.page_tokens
        node = self.root
        added = 0
        for block in range(n // ptok):
            key = self._key(tokens[block * ptok:(block + 1) * ptok])
            child = node.children.get(key)
            if child is None:
                pid = int(table_row[block])
                if pid == cache.trash_page:
                    break  # not materialized (shouldn't happen)
                child = _TrieNode(key, pid, node)
                node.children[key] = child
                cache.ref[pid] += 1  # the trie's pin
                self.nodes += 1
                added += 1
            node = child
            self._tick(node)
        return added

    def _leaves(self) -> list[_TrieNode]:
        out, stack = [], [self.root]
        while stack:
            node = stack.pop()
            if node is not self.root and not node.children:
                out.append(node)
            stack.extend(node.children.values())
        return out

    def spill_candidate(self, cache: PagedKVCache):
        """The LRU HBM-resident node held by NOTHING but the trie pin
        (``ref == 1`` — no live sequence maps its page), or None.
        Safe to demote: the node STAYS in the trie, so the block is
        still matchable from the host tier — unlike eviction, a spill
        loses residency, not the hit (round 22).  Interior nodes
        qualify too: demotion never orphans children."""
        best = None
        stack = list(self.root.children.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if node.page is not None \
                    and int(cache.ref[node.page]) == 1 \
                    and (best is None or node.last_use < best.last_use):
                best = node
        return best

    def evict(self, cache: PagedKVCache, pages_needed: int) -> int:
        """Unpin LRU leaf blocks until ``pages_needed`` pages are
        free (or no HBM-resident leaf remains).  An unpinned page
        frees immediately when no live sequence still references it.
        Host-resident leaves are skipped — they hold no HBM page, so
        dropping them frees nothing here.  Returns nodes evicted."""
        evicted = 0
        while cache.free_pages < pages_needed:
            leaves = [lf for lf in self._leaves()
                      if lf.page is not None]
            if not leaves:
                break
            victim = min(leaves, key=lambda n: n.last_use)
            victim.parent.children.pop(victim.key)
            cache.ref_dec(victim.page)
            self.nodes -= 1
            evicted += 1
        return evicted

    def spilled_nodes(self) -> int:
        """Host-tier residents (telemetry + accounting tests)."""
        count, stack = 0, list(self.root.children.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if node.host is not None:
                count += 1
        return count

    def clear(self, cache: PagedKVCache, tier=None) -> int:
        """Drop the whole trie (weight swap: cached K/V are functions
        of the OLD weights) — BOTH tiers: spilled frames free too.
        Returns nodes dropped."""
        dropped = 0
        stack = list(self.root.children.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if node.page is not None:
                cache.ref_dec(node.page)
            elif tier is not None and node.host is not None:
                tier.free(node.host)
            dropped += 1
        self.root.children.clear()
        self.nodes = 0
        return dropped


class DecodeModel(Logger):
    """Prefill/decode program families + KV cache over an exported LM.

    ``model`` is an :class:`~znicz_tpu.export.ExportedModel` (or a
    bundle path); its manifest must describe a causal LM
    (``kind == "lm"`` — legacy pre-round-12 bundles re-derive the
    kind from their layer table, so any previously exported LM
    decodes without re-export).

    Geometry knobs:

    - ``max_slots`` — concurrent sequences (KV pages preallocated);
    - ``max_t`` — cache page length, rounded up to a power of two
      (a sequence reaching it is force-finished);
    - ``max_prompt`` / ``prompt_align`` — the prompt-length ladder:
      prefill programs exist for ``prompt_align·2^k ≤ max_prompt``.

    Paged knobs (round 15; every default reads the manifest's
    ``decode`` section first, then ``root.common.engine``):

    - ``paged`` — page the KV-cache (``engine.paged_kv``, default on;
      ``False`` = the flat per-slot A/B arm, greedy token-identical);
    - ``page_tokens`` — tokens per page (``engine.kv_page_tokens``,
      default 16; power of two dividing ``max_t``);
    - ``pool_tokens`` — the pool's token capacity
      (default ``max_slots · max_t`` — the flat cache's exact byte
      budget, so the paged arm never wins by spending more memory);
    - ``spec_k`` — compile the speculative-verification family for
      ``spec_k``-token draft windows (0 = off).

    Quantization knobs (round 21):

    - ``kv_quant`` — int8 K/V pages with one f32 scale per
      (token, head) row (``engine.kv_quant``, default off; paged
      cache only — the flat A/B arm stays the bitwise greedy-identity
      baseline).  At a fixed pool byte budget the pool holds roughly
      ``2 / (1 + 4/Dh)`` × the bf16 arm's tokens;
    - ``kv_dtype`` — the page pools' dtype when NOT quantizing
      (default f32; ``"bfloat16"`` is the byte-budget baseline arm the
      quant benchmark compares lanes against).

    int8-quantized *weight* bundles need no knob: the manifest's
    ``quant`` record makes :meth:`_gather_weights` keep them int8 in
    HBM as ``(q, scale)`` operand pairs that every traced body
    dequantizes on load.
    """

    def __init__(self, model, *, max_slots: int = 4,
                 max_t: int = 64, max_prompt: int | None = None,
                 prompt_align: int = 8, device=None,
                 paged: bool | None = None,
                 page_tokens: int | None = None,
                 pool_tokens: int | None = None,
                 spec_k: int = 0,
                 kv_quant: bool | None = None,
                 kv_dtype=None) -> None:
        super().__init__()
        from znicz_tpu.export import ExportedModel
        from znicz_tpu.utils.config import root
        if isinstance(model, (str, bytes)) or hasattr(model,
                                                      "__fspath__"):
            model = ExportedModel.load(model, device=device)
        self.model = model
        decode_meta = dict(model.manifest.get("decode", {}))
        if paged is None:
            paged = bool(root.common.engine.get("paged_kv", True))
        self.paged = bool(paged)
        if page_tokens is None:
            page_tokens = int(decode_meta.get(
                "kv_page_tokens",
                root.common.engine.get("kv_page_tokens", 16)))
        if kv_quant is None:
            kv_quant = bool(decode_meta.get(
                "kv_quant", root.common.engine.get("kv_quant", False)))
        self.kv_quant = bool(kv_quant) and self.paged
        self.kv_dtype = np.dtype(kv_dtype if kv_dtype is not None
                                 else np.float32)
        self.spec_k = int(spec_k)
        if model.kind != "lm":
            raise ValueError(
                f"bundle '{model.manifest.get('workflow', '?')}' is a "
                f"'{model.kind}' — decode needs an LM (token-first "
                f"causal chain); re-export a generation model or use "
                f"ServingEngine for one-shot scoring")
        self.seq_meta = dict(model.sequence)
        self.vocab = int(self.seq_meta["vocab"])
        self.dim = int(self.seq_meta["dim"])
        self.max_slots = int(max_slots)
        if self.max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        self.max_t = next_pow2(int(max_t))
        self.prompt_align = int(prompt_align)
        self.max_prompt = int(max_prompt if max_prompt is not None
                              else min(self.max_t // 2,
                                       bucket_for(
                                           self.seq_meta["train_t"],
                                           self.prompt_align)))
        if self.max_prompt >= self.max_t:
            raise ValueError(
                f"max_prompt ({self.max_prompt}) must leave room to "
                f"generate below max_t ({self.max_t})")
        if bucket_for(self.max_prompt, self.prompt_align) > self.max_t:
            raise ValueError(
                f"prompt ladder top "
                f"{bucket_for(self.max_prompt, self.prompt_align)} "
                f"(max_prompt {self.max_prompt} rounded up to the "
                f"prompt_align·2^k ladder) exceeds the max_t "
                f"{self.max_t} cache page — raise max_t or lower "
                f"max_prompt")
        self.device = model.device
        self._plan, cache_specs = self._build_plan()
        self.has_lstm = any(kind == "lstm" for _n, kind, _s
                            in cache_specs)
        if self.paged:
            self.page_tokens = next_pow2(
                min(int(page_tokens), self.max_t))
            self.max_blocks = self.max_t // self.page_tokens
            if pool_tokens is None:
                pool_tokens = int(decode_meta.get(
                    "pool_tokens", self.max_slots * self.max_t))
            pool_pages = max(1, int(pool_tokens) // self.page_tokens)
            self.pool_tokens = pool_pages * self.page_tokens
            specs = []
            for name, kind, shape in cache_specs:
                if kind == "attention":
                    specs.append((name, "page",
                                  (shape[-2], shape[-1]),
                                  np.int8 if self.kv_quant
                                  else self.kv_dtype))
                else:
                    specs.append((name, "slot", shape, np.float32))
            if self.kv_quant:
                # f32 per-(token, head) scale pools, appended AFTER
                # every data spec so the plan's aux indices stay
                # valid; kind "page" → same page ids, COW copies and
                # trash sink as the int8 rows they scale
                for op in self._plan:
                    if op.kind != "attention":
                        continue
                    for side in ("k", "v"):
                        name, _k, shape = cache_specs[op.aux[side]]
                        op.aux[f"{side}_scale"] = len(specs)
                        specs.append((f"{name}_scale", "page",
                                      (shape[-2],), np.float32))
            self.cache = PagedKVCache(
                specs, self.max_slots, self.page_tokens,
                self.max_blocks, pool_pages)
        else:
            self.page_tokens = self.max_t
            self.max_blocks = 1
            self.pool_tokens = self.max_slots * self.max_t
            self.cache = KVCache(
                [(name, shape) for name, _kind, shape in cache_specs],
                self.max_slots)
        if self.spec_k and (not self.paged or self.has_lstm):
            raise ValueError(
                "speculative decoding needs the paged cache and an "
                "attention-only sequence phase (LSTM carries cannot "
                "roll back a rejected draft)")
        self._prefill_programs: dict[int, "callable"] = {}
        self._decode_programs: dict[int, "callable"] = {}
        #: paged families, keyed (t_bucket, nb) / (b_bucket, nb)
        self._paged_prefill_programs: dict[tuple, "callable"] = {}
        self._paged_decode_programs: dict[tuple, "callable"] = {}
        self._verify_programs: dict[tuple, "callable"] = {}
        self._copy_program = None
        #: round 22 page-I/O family: scatter one staged page (spill
        #: restore / pool handoff) or one carry row set into a cache
        self._page_in_program = None
        self._carry_in_program = None
        self.compile_count = 0
        #: programs DESERIALIZED from the persisted AOT cache (round
        #: 23) — residency without a trace; never counted as compiles
        self.load_count = 0
        self.donating = model._donate_choice()
        # the published weight pytree: one immutable tuple-of-tuples
        # (one entry per plan op, None for absent leaves) every
        # prefill/decode dispatch reads exactly once — hot-swap
        # replaces the tuple between dispatches
        self._weights = self._gather_weights(self.model._params)
        self.weights_version = 0

    # ------------------------------------------------------------------
    # chain planning
    # ------------------------------------------------------------------
    def _gather_weights(self, params: dict) -> tuple:
        """Build the weight operand pytree from a bundle's param dict
        (absent leaves — e.g. a bias the export never carried — stay
        ``None``, a legal empty pytree node).

        Keys the bundle's ``quant`` record covers (round 21) become
        ``(q int8, scale f32)`` pairs — int8 stays resident in HBM
        (halved weight bytes per replica) and every traced body
        dequantizes on load via :func:`_dq_leaves`."""
        import jax.numpy as jnp
        from znicz_tpu.serving import quantize as _quantize
        qkeys = getattr(self.model, "_qkeys", frozenset())
        out = []
        for op in self._plan:
            leaves = []
            for key in op.wkeys:
                if key not in params:
                    leaves.append(None)
                elif key in qkeys:
                    leaves.append((
                        jnp.asarray(params[key], jnp.int8),
                        jnp.asarray(params[_quantize.scale_key(key)],
                                    jnp.float32)))
                else:
                    leaves.append(jnp.asarray(params[key],
                                              jnp.float32))
            out.append(tuple(leaves))
        return tuple(out)

    def _build_plan(self) -> tuple[list[_Op], list]:
        """Walk the manifest layers into decode ops + cache specs.

        Chain grammar: a *sequence* phase (embedding first, then
        pos_encoding / causal attention / LSTM), a bridge to
        position-independence (``last_token``, or a final
        ``return_sequence=False`` LSTM), then a *head* phase of
        per-sample FC layers ending in the vocabulary softmax."""
        units = self.model.forwards
        layers = self.model.manifest["layers"]
        plan: list[_Op] = []
        cache_specs: list[tuple[str, tuple]] = []
        phase = "seq"
        d = self.dim
        if not layers or layers[0]["type"] != "embedding":
            raise ValueError("decode chain must start with an "
                             "embedding layer (token-first)")
        for i, (spec, unit) in enumerate(zip(layers, units)):
            kind = spec["type"]
            if phase == "head" and kind not in _HEAD_KINDS:
                raise ValueError(
                    f"layer {i} ({kind}) after the sequence→sample "
                    f"bridge — only head layers {_HEAD_KINDS} may "
                    f"follow")
            if kind == "embedding":
                plan.append(_Op(kind, unit, (f"layer{i}_weights",)))
            elif kind == "pos_encoding":
                import jax.numpy as jnp
                # 2×max_t rows: paged tail-prefill windows slice at
                # an arbitrary start and must never hit the
                # dynamic_slice clamp (rows ≥ max_t feed only padded
                # positions, whose outputs are discarded)
                table = jnp.asarray(
                    unit.table_to(2 * self.max_t, d), jnp.float32)
                plan.append(_Op(kind, unit, table=table))
            elif kind == "attention":
                if not spec.get("config", {}).get("causal"):
                    raise ValueError(
                        f"layer {i}: attention must be causal=True to "
                        f"decode (a bidirectional layer has no valid "
                        f"incremental step)")
                heads = unit.n_heads
                dh = d // heads
                k_idx = len(cache_specs)
                cache_specs.append(
                    (f"l{i}.k", "attention", (self.max_t, heads, dh)))
                cache_specs.append(
                    (f"l{i}.v", "attention", (self.max_t, heads, dh)))
                plan.append(_Op(kind, unit, (
                    f"layer{i}_weights", f"layer{i}_bias",
                    f"layer{i}_weights_out", f"layer{i}_bias_out"),
                    aux={"k": k_idx, "v": k_idx + 1}))
            elif kind == "lstm":
                h_idx = len(cache_specs)
                cache_specs.append((f"l{i}.h", "lstm", (unit.units,)))
                cache_specs.append((f"l{i}.c", "lstm", (unit.units,)))
                plan.append(_Op(kind, unit, (
                    f"layer{i}_weights", f"layer{i}_bias"),
                    aux={"h": h_idx, "c": h_idx + 1}))
                d = unit.units
                if not unit.return_sequence:
                    phase = "head"  # the carry IS the sample bridge
            elif kind == "last_token":
                plan.append(_Op(kind, unit))
                phase = "head"
            elif kind in _HEAD_KINDS:
                if phase != "head":
                    raise ValueError(
                        f"layer {i} ({kind}) inside the sequence "
                        f"phase — FC layers flatten the time axis "
                        f"and cannot decode; bridge with last_token "
                        f"first")
                plan.append(_Op(kind, unit, (
                    f"layer{i}_weights", f"layer{i}_bias")))
            else:
                raise ValueError(
                    f"layer {i} ({kind}): no incremental decode step "
                    f"(supported: {_SEQ_KINDS + _HEAD_KINDS} + "
                    f"last_token)")
        if phase != "head":
            raise ValueError("chain never bridges to per-sample "
                             "features (last_token or a final "
                             "return_sequence=False LSTM)")
        if layers[-1]["type"] != "softmax":
            raise ValueError("decode chain must end in the softmax "
                             "vocabulary head")
        if not cache_specs:
            raise ValueError("stateless chain — nothing to cache, "
                             "nothing to decode")
        return plan, cache_specs

    # ------------------------------------------------------------------
    # traced bodies
    # ------------------------------------------------------------------
    def _head(self, op: _Op, w, x, final: bool):
        """One head layer on (B, D) features; the final softmax layer
        returns raw logits (softmax is monotone — greedy unchanged,
        and sampling normalizes on the host)."""
        import jax.numpy as jnp
        weights, b = w
        if final:
            return op.unit._logits(jnp, x, weights, b)
        return op.unit._forward(jnp, x, weights, b)

    def _prefill_fn(self, t_bucket: int):
        """The traced prefill body for one prompt-length bucket.
        ``weights`` is the per-op operand pytree — an argument, not a
        baked constant, so a hot-swap never invalidates the program."""
        import jax
        import jax.numpy as jnp
        plan = self._plan

        def fn(caches, weights, tokens, slot, length):
            # tokens (1, t_bucket) int32; slot, length () int32
            caches = list(caches)
            feat = None
            logits = None
            for j, op in enumerate(plan):
                w = _dq_leaves(weights[j])
                if op.kind == "embedding":
                    feat = op.unit.xla_embed(w[0], tokens)
                elif op.kind == "pos_encoding":
                    feat = (feat.astype(jnp.float32)
                            + op.table[:t_bucket][None])
                elif op.kind == "attention":
                    feat, k, v = op.unit.xla_prefill(feat, *w)
                    zero = jnp.int32(0)
                    caches[op.aux["k"]] = jax.lax.dynamic_update_slice(
                        caches[op.aux["k"]], k, (slot, zero, zero, zero))
                    caches[op.aux["v"]] = jax.lax.dynamic_update_slice(
                        caches[op.aux["v"]], v, (slot, zero, zero, zero))
                elif op.kind == "lstm":
                    feat, h, c = op.unit.xla_prefill(
                        feat, *w, length=jnp.reshape(length, (1,)))
                    caches[op.aux["h"]] = \
                        caches[op.aux["h"]].at[slot].set(h[0])
                    caches[op.aux["c"]] = \
                        caches[op.aux["c"]].at[slot].set(c[0])
                elif op.kind == "last_token":
                    # the last REAL position, not the padded tail
                    feat = jax.lax.dynamic_index_in_dim(
                        feat, length - 1, axis=1, keepdims=False)
                else:  # head layer
                    logits = self._head(op, w, feat, op is plan[-1])
                    feat = logits
            return tuple(caches), logits
        return fn

    def _decode_fn(self, b_bucket: int):
        """The traced single-token body for one live-batch bucket."""
        plan = self._plan

        def fn(caches, weights, tokens, slots, positions):
            # tokens/slots/positions: (b_bucket,) int32
            import jax.numpy as jnp
            caches = list(caches)
            rows = jnp.arange(b_bucket)
            feat = None
            logits = None
            for j, op in enumerate(plan):
                w = _dq_leaves(weights[j])
                if op.kind == "embedding":
                    feat = op.unit.xla_embed(w[0], tokens)[:, None, :]
                elif op.kind == "pos_encoding":
                    feat = op.unit.xla_decode_step(feat, positions,
                                                   op.table)
                elif op.kind == "attention":
                    k_rows = caches[op.aux["k"]][slots]
                    v_rows = caches[op.aux["v"]][slots]
                    feat, k_rows, v_rows = op.unit.xla_decode_step(
                        feat, k_rows, v_rows, positions, *w)
                    # only position `pos` changed per lane: scatter the
                    # new row back, padded lanes land in the scratch
                    # slot (duplicate-index writes there are garbage
                    # by design)
                    caches[op.aux["k"]] = caches[op.aux["k"]].at[
                        slots, positions].set(k_rows[rows, positions])
                    caches[op.aux["v"]] = caches[op.aux["v"]].at[
                        slots, positions].set(v_rows[rows, positions])
                elif op.kind == "lstm":
                    h = caches[op.aux["h"]][slots]
                    c = caches[op.aux["c"]][slots]
                    feat, h, c = op.unit.xla_decode_step(
                        feat, h, c, *w)
                    caches[op.aux["h"]] = \
                        caches[op.aux["h"]].at[slots].set(h)
                    caches[op.aux["c"]] = \
                        caches[op.aux["c"]].at[slots].set(c)
                    if op.unit.return_sequence:
                        feat = feat[:, None, :]
                elif op.kind == "last_token":
                    feat = feat[:, 0]
                else:
                    if feat.ndim == 3:  # head after a seq-phase bridge
                        feat = feat[:, 0]
                    logits = self._head(op, w, feat, op is plan[-1])
                    feat = logits
            return tuple(caches), logits
        return fn

    # ------------------------------------------------------------------
    # traced bodies — paged variants (round 15)
    # ------------------------------------------------------------------
    def _paged_prefill_fn(self, t_bucket: int, nb: int):
        """One prompt WINDOW (fresh prefill at ``start=0``, or the
        unshared tail after a prefix-cache hit at ``start>0``) written
        and attended through the page table.  ``table`` carries nb+1
        page ids (last = trash)."""
        import jax
        import jax.numpy as jnp
        plan = self._plan

        def fn(caches, weights, tokens, table, slot, start, length):
            # tokens (1, t_bucket); table (nb+1,); slot/start/length ()
            caches = list(caches)
            feat = None
            logits = None
            for j, op in enumerate(plan):
                w = _dq_leaves(weights[j])
                if op.kind == "embedding":
                    feat = op.unit.xla_embed(w[0], tokens)
                elif op.kind == "pos_encoding":
                    pe = jax.lax.dynamic_slice_in_dim(
                        op.table, start, t_bucket, axis=0)
                    feat = feat.astype(jnp.float32) + pe[None]
                elif op.kind == "attention":
                    ks = op.aux.get("k_scale")
                    if ks is None:
                        feat, kp, vp = op.unit.xla_prefill_paged(
                            feat, caches[op.aux["k"]],
                            caches[op.aux["v"]], table, start,
                            length, *w)
                    else:
                        vs = op.aux["v_scale"]
                        (feat, kp, vp, caches[ks],
                         caches[vs]) = op.unit.xla_prefill_paged(
                            feat, caches[op.aux["k"]],
                            caches[op.aux["v"]], table, start,
                            length, *w, k_scale=caches[ks],
                            v_scale=caches[vs])
                    caches[op.aux["k"]] = kp
                    caches[op.aux["v"]] = vp
                elif op.kind == "lstm":
                    # LSTM chains never share prefixes (start is
                    # always 0): the carry is the whole-prefix state
                    feat, h, c = op.unit.xla_prefill(
                        feat, *w, length=jnp.reshape(length, (1,)))
                    caches[op.aux["h"]] = \
                        caches[op.aux["h"]].at[slot].set(h[0])
                    caches[op.aux["c"]] = \
                        caches[op.aux["c"]].at[slot].set(c[0])
                elif op.kind == "last_token":
                    feat = jax.lax.dynamic_index_in_dim(
                        feat, length - 1, axis=1, keepdims=False)
                else:
                    logits = self._head(op, w, feat, op is plan[-1])
                    feat = logits
            return tuple(caches), logits
        return fn

    def _paged_decode_fn(self, b_bucket: int, nb: int):
        """Single-token step through the page table, bucketed on BOTH
        the live-batch size and the deepest lane's block count — a
        shallow batch reads exactly the pages it occupies, never the
        flat layout's full ``max_t`` reservation."""
        plan = self._plan

        def fn(caches, weights, tokens, tables, slots, positions):
            # tokens/slots/positions (b,); tables (b, nb+1)
            import jax.numpy as jnp
            caches = list(caches)
            feat = None
            logits = None
            for j, op in enumerate(plan):
                w = _dq_leaves(weights[j])
                if op.kind == "embedding":
                    feat = op.unit.xla_embed(w[0], tokens)[:, None, :]
                elif op.kind == "pos_encoding":
                    feat = op.unit.xla_decode_step(feat, positions,
                                                   op.table)
                elif op.kind == "attention":
                    ks = op.aux.get("k_scale")
                    if ks is None:
                        feat, kp, vp = op.unit.xla_decode_step_paged(
                            feat, caches[op.aux["k"]],
                            caches[op.aux["v"]], tables, positions,
                            *w)
                    else:
                        vs = op.aux["v_scale"]
                        (feat, kp, vp, caches[ks],
                         caches[vs]) = op.unit.xla_decode_step_paged(
                            feat, caches[op.aux["k"]],
                            caches[op.aux["v"]], tables, positions,
                            *w, k_scale=caches[ks],
                            v_scale=caches[vs])
                    caches[op.aux["k"]] = kp
                    caches[op.aux["v"]] = vp
                elif op.kind == "lstm":
                    h = caches[op.aux["h"]][slots]
                    c = caches[op.aux["c"]][slots]
                    feat, h, c = op.unit.xla_decode_step(
                        feat, h, c, *w)
                    caches[op.aux["h"]] = \
                        caches[op.aux["h"]].at[slots].set(h)
                    caches[op.aux["c"]] = \
                        caches[op.aux["c"]].at[slots].set(c)
                    if op.unit.return_sequence:
                        feat = feat[:, None, :]
                elif op.kind == "last_token":
                    feat = feat[:, 0]
                else:
                    if feat.ndim == 3:
                        feat = feat[:, 0]
                    logits = self._head(op, w, feat, op is plan[-1])
                    feat = logits
            return tuple(caches), logits
        return fn

    def _window_fn(self, b_bucket: int, w_len: int, nb: int):
        """Batched multi-token window per lane, written and attended
        through the page table in ONE forward, returning logits at
        EVERY window position (b, W, V).  Two callers: speculative
        verification (window = last accepted token + K drafts,
        lengths ≡ K+1) and batched tail prefill (window = each lane's
        unshared prompt tail, ragged ``lengths`` — admission
        coalescing, so a burst of prefix-hit prompts pays ONE
        dispatch instead of one each)."""
        import jax.numpy as jnp
        plan = self._plan

        def fn(caches, weights, tokens, tables, positions, lengths):
            # tokens (b, W); tables (b, nb+1); positions/lengths (b,)
            caches = list(caches)
            feat = None
            logits = None
            for j, op in enumerate(plan):
                w = _dq_leaves(weights[j])
                if op.kind == "embedding":
                    feat = op.unit.xla_embed(w[0], tokens)
                elif op.kind == "pos_encoding":
                    idx = jnp.minimum(
                        positions[:, None] + jnp.arange(w_len)[None],
                        op.table.shape[0] - 1)
                    feat = feat.astype(jnp.float32) + op.table[idx]
                elif op.kind == "attention":
                    ks = op.aux.get("k_scale")
                    if ks is None:
                        feat, kp, vp = op.unit.xla_window_paged(
                            feat, caches[op.aux["k"]],
                            caches[op.aux["v"]], tables, positions,
                            lengths, *w)
                    else:
                        vs = op.aux["v_scale"]
                        (feat, kp, vp, caches[ks],
                         caches[vs]) = op.unit.xla_window_paged(
                            feat, caches[op.aux["k"]],
                            caches[op.aux["v"]], tables, positions,
                            lengths, *w, k_scale=caches[ks],
                            v_scale=caches[vs])
                    caches[op.aux["k"]] = kp
                    caches[op.aux["v"]] = vp
                elif op.kind == "last_token":
                    # every window position flows to the head: fold
                    # the window into the batch for the head phase
                    feat = feat.reshape(b_bucket * w_len, -1)
                else:
                    logits = self._head(op, w, feat, op is plan[-1])
                    feat = logits
            return tuple(caches), logits.reshape(b_bucket, w_len, -1)
        return fn

    def _copy_fn(self):
        """Copy one page (every attention pool) — the copy-on-write
        a partial prefix-cache match performs before the divergent
        tail writes into the boundary block."""
        pool_indices = self.cache.pool_indices

        def fn(caches, src, dst):
            caches = list(caches)
            for i in pool_indices:
                caches[i] = caches[i].at[dst].set(caches[i][src])
            return tuple(caches)
        return fn

    def _page_in_fn(self):
        """Scatter ONE staged page (every pool) into row ``dst`` —
        the device half of a spill restore or a prefill→decode
        handoff (round 22).  The page operands arrive via the staging
        ring + uploader thread; only the cache tuple is donated, so
        the uploaded arrays stay valid for the caller."""
        pool_indices = self.cache.pool_indices

        def fn(caches, pages, dst):
            caches = list(caches)
            for j, i in enumerate(pool_indices):
                caches[i] = caches[i].at[dst].set(pages[j])
            return tuple(caches)
        return fn

    def _carry_in_fn(self):
        """Scatter one LSTM carry row set into ``slot`` — the
        slot-indexed half of the handoff contract (carries summarize
        the whole prefix in O(H), so they ride the transfer as rows,
        not pages)."""
        slot_indices = self.cache.slot_indices

        def fn(caches, rows, slot):
            caches = list(caches)
            for j, i in enumerate(slot_indices):
                caches[i] = caches[i].at[slot].set(rows[j])
            return tuple(caches)
        return fn

    # ------------------------------------------------------------------
    # AOT compilation
    # ------------------------------------------------------------------
    def _compile(self, fn, in_structs: tuple, site: str,
                 family: str | None = None, geom: tuple = ()):
        import jax
        donate = (0,) if self.donating else ()
        # round 23: the persisted executable store is consulted BEFORE
        # tracing.  The key covers the program family + bucket
        # geometry explicitly (two families can share a site), the
        # bundle's architecture digest, the operand structs, the
        # decode-plan knobs that shape a body without shaping its
        # operands, donation, platform and build — any mismatch is a
        # plain miss and this compiles exactly as before.
        from znicz_tpu.serving import aot_cache as _aot
        cache = _aot.active_cache()
        key = digest = None
        if cache is not None:
            family = family or site
            digest = _aot.program_digest(self.model.manifest)
            key = _aot.entry_key(
                family, digest=digest, geometry=geom,
                structs=in_structs, donate=self.donating,
                extra=("decode", self.paged, self.page_tokens,
                       self.kv_quant, str(self.kv_dtype), self.spec_k,
                       self.max_t, self.vocab))
            loaded = cache.get(key, site)
            if loaded is not None:
                # a deserialized load is NOT a compile — compile_count
                # and the per-site xla_compiles series stay flat
                self.load_count += 1
                return _aot.guard_donated(loaded, donate)
        with _tracing.TRACER.span(f"aot_compile:{site}",
                                  cat="compile"):
            compiled = jax.jit(fn, donate_argnums=donate).lower(
                *in_structs).compile()
        _metrics.xla_compiles(site).inc()
        self.compile_count += 1
        if cache is not None:
            cache.put(key, compiled, site,
                      meta={"family": family,
                            "program_digest": digest,
                            "geometry": [str(g) for g in geom]})
        return compiled

    def _cache_structs(self) -> tuple:
        import jax
        return tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                     for a in self.cache.arrays)

    def _weight_structs(self) -> tuple:
        import jax

        def struct(a):
            if isinstance(a, tuple):  # (q int8, scale f32) pair
                return tuple(struct(x) for x in a)
            return jax.ShapeDtypeStruct(a.shape, a.dtype,
                                        sharding=getattr(a, "sharding",
                                                         None))
        return tuple(tuple(None if a is None else struct(a)
                           for a in ws) for ws in self._weights)

    def prefill_program(self, t_bucket: int):
        """The AOT prefill program for one prompt-length bucket
        (compiled on first use; :meth:`warmup` front-loads the whole
        ladder)."""
        prog = self._prefill_programs.get(t_bucket)
        if prog is None:
            import jax
            i32 = np.dtype(np.int32)
            prog = self._compile(
                self._prefill_fn(t_bucket),
                (self._cache_structs(), self._weight_structs(),
                 jax.ShapeDtypeStruct((1, t_bucket), i32),
                 jax.ShapeDtypeStruct((), i32),
                 jax.ShapeDtypeStruct((), i32)),
                "serving-prefill", family="prefill",
                geom=(t_bucket,))
            self._prefill_programs[t_bucket] = prog
        return prog

    def decode_program(self, b_bucket: int):
        """The AOT single-token program for one live-batch bucket."""
        prog = self._decode_programs.get(b_bucket)
        if prog is None:
            import jax
            vec = jax.ShapeDtypeStruct((b_bucket,), np.dtype(np.int32))
            prog = self._compile(
                self._decode_fn(b_bucket),
                (self._cache_structs(), self._weight_structs(),
                 vec, vec, vec),
                "serving-decode", family="decode",
                geom=(b_bucket,))
            self._decode_programs[b_bucket] = prog
        return prog

    def paged_prefill_program(self, t_bucket: int, nb: int):
        key = (t_bucket, nb)
        prog = self._paged_prefill_programs.get(key)
        if prog is None:
            import jax
            i32 = np.dtype(np.int32)
            scalar = jax.ShapeDtypeStruct((), i32)
            prog = self._compile(
                self._paged_prefill_fn(t_bucket, nb),
                (self._cache_structs(), self._weight_structs(),
                 jax.ShapeDtypeStruct((1, t_bucket), i32),
                 jax.ShapeDtypeStruct((nb + 1,), i32),
                 scalar, scalar, scalar),
                "serving-prefill", family="paged-prefill",
                geom=key)
            self._paged_prefill_programs[key] = prog
        return prog

    def paged_decode_program(self, b_bucket: int, nb: int):
        key = (b_bucket, nb)
        prog = self._paged_decode_programs.get(key)
        if prog is None:
            import jax
            i32 = np.dtype(np.int32)
            vec = jax.ShapeDtypeStruct((b_bucket,), i32)
            prog = self._compile(
                self._paged_decode_fn(b_bucket, nb),
                (self._cache_structs(), self._weight_structs(),
                 vec, jax.ShapeDtypeStruct((b_bucket, nb + 1), i32),
                 vec, vec),
                "serving-decode", family="paged-decode",
                geom=key)
            self._paged_decode_programs[key] = prog
        return prog

    def window_program(self, b_bucket: int, w_len: int, nb: int,
                       site: str = "serving-verify"):
        key = (b_bucket, w_len, nb)
        prog = self._verify_programs.get(key)
        if prog is None:
            import jax
            i32 = np.dtype(np.int32)
            vec = jax.ShapeDtypeStruct((b_bucket,), i32)
            prog = self._compile(
                self._window_fn(b_bucket, w_len, nb),
                (self._cache_structs(), self._weight_structs(),
                 jax.ShapeDtypeStruct((b_bucket, w_len), i32),
                 jax.ShapeDtypeStruct((b_bucket, nb + 1), i32),
                 vec, vec),
                site, family="window", geom=key)
            self._verify_programs[key] = prog
        return prog

    def verify_program(self, b_bucket: int, nb: int):
        if not self.spec_k:
            raise RuntimeError("spec_k=0 — no verify family planned")
        return self.window_program(b_bucket, self.spec_k + 1, nb)

    def copy_program(self):
        if self._copy_program is None:
            import jax
            i32 = np.dtype(np.int32)
            self._copy_program = self._compile(
                self._copy_fn(),
                (self._cache_structs(),
                 jax.ShapeDtypeStruct((), i32),
                 jax.ShapeDtypeStruct((), i32)),
                "serving-page", family="copy")
        return self._copy_program

    def page_in_program(self):
        if not self.paged:
            raise RuntimeError("page_in needs the paged cache")
        if self._page_in_program is None:
            import jax
            cache = self.cache
            page_structs = tuple(
                jax.ShapeDtypeStruct(
                    (cache.page_tokens,) + tuple(cache.specs[i][2]),
                    cache.arrays[i].dtype)
                for i in cache.pool_indices)
            self._page_in_program = self._compile(
                self._page_in_fn(),
                (self._cache_structs(), page_structs,
                 jax.ShapeDtypeStruct((), np.dtype(np.int32))),
                "serving-page", family="page-in")
        return self._page_in_program

    def carry_in_program(self):
        if not (self.paged and self.has_lstm):
            raise RuntimeError("carry_in needs a paged LSTM chain")
        if self._carry_in_program is None:
            import jax
            cache = self.cache
            row_structs = tuple(
                jax.ShapeDtypeStruct(tuple(cache.specs[i][2]),
                                     cache.arrays[i].dtype)
                for i in cache.slot_indices)
            self._carry_in_program = self._compile(
                self._carry_in_fn(),
                (self._cache_structs(), row_structs,
                 jax.ShapeDtypeStruct((), np.dtype(np.int32))),
                "serving-page", family="carry-in")
        return self._carry_in_program

    def prompt_ladder(self) -> list[int]:
        return ladder(self.max_prompt, self.prompt_align)

    def batch_ladder(self) -> list[int]:
        return ladder(self.max_slots)

    def block_ladder(self) -> list[int]:
        """Power-of-two block-count buckets: a decode dispatch reads
        only ``nb·page_tokens`` cache rows per lane."""
        return ladder(self.max_blocks) if self.paged else [1]

    def nb_for(self, top_position: int) -> int:
        """The block bucket covering positions ``0..top_position``."""
        blocks = -(-(int(top_position) + 1) // self.page_tokens)
        return min(next_pow2(max(1, blocks)), self.max_blocks)

    def fresh_nb(self, t_bucket: int) -> int:
        return self.nb_for(t_bucket - 1)

    def warmup(self, prefix_cache: bool = True,
               page_io: bool = False) -> int:
        """Compile EVERY program family up front — after this, a
        decode loop at any live-batch size, block depth and prompt mix
        performs zero compiles.  Returns programs compiled.

        ``prefix_cache=False`` skips the tail-prefill (start>0)
        variants and the COW copy program — engines without prefix
        sharing never dispatch them.  ``page_io=True`` (round 22)
        adds the page-in scatter (+ the carry scatter on LSTM
        chains): spill restores and pool handoffs then run
        compile-free too.

        "Compiled" means MADE RESIDENT: programs deserialized from the
        persisted AOT cache (round 23) count toward the return value
        (they satisfy the same zero-compiles-at-serve-time contract)
        but never toward ``compile_count``."""
        before = self.compile_count + self.load_count
        if not self.paged:
            for t_b in self.prompt_ladder():
                self.prefill_program(t_b)
            for b_b in self.batch_ladder():
                self.decode_program(b_b)
            return (self.compile_count + self.load_count) - before
        for t_b in self.prompt_ladder():
            for nb in self.block_ladder():
                if nb < self.fresh_nb(t_b):
                    continue  # a window never shrinks its own blocks
                if nb > self.fresh_nb(t_b) and not prefix_cache:
                    continue  # start>0 exists only with prefix hits
                self.paged_prefill_program(t_b, nb)
        for b_b in self.batch_ladder():
            for nb in self.block_ladder():
                self.paged_decode_program(b_b, nb)
                if self.spec_k:
                    self.verify_program(b_b, nb)
                if prefix_cache and not self.has_lstm:
                    # the admission-coalescing window family: a wave
                    # of prefix-hit tails admits in ONE dispatch
                    self.window_program(b_b, self.prompt_align, nb,
                                        site="serving-prefill")
        if prefix_cache:
            self.copy_program()
        if page_io:
            self.page_in_program()
            if self.has_lstm:
                self.carry_in_program()
        return (self.compile_count + self.load_count) - before

    @property
    def programs_live(self) -> int:
        return (len(self._prefill_programs)
                + len(self._decode_programs)
                + len(self._paged_prefill_programs)
                + len(self._paged_decode_programs)
                + len(self._verify_programs)
                + (1 if self._copy_program is not None else 0)
                + (1 if self._page_in_program is not None else 0)
                + (1 if self._carry_in_program is not None else 0))

    # ------------------------------------------------------------------
    # pool replication (round 22): programs are pure functions of the
    # cache operands, so ONE warmed DecodeModel serves any number of
    # same-geometry caches — disaggregated pool replicas scale
    # compile-free
    # ------------------------------------------------------------------
    def make_cache(self) -> PagedKVCache:
        """A fresh :class:`PagedKVCache` with IDENTICAL geometry to
        the model's own — the per-replica state of a disaggregated
        prefill/decode pool member.  Every compiled program accepts
        it via the ``cache=`` dispatch parameter."""
        if not self.paged:
            raise RuntimeError(
                "pool replication needs the paged cache (flat caches "
                "are slot-bound to one engine)")
        cache = self.cache
        return PagedKVCache(list(cache.specs), self.max_slots,
                            self.page_tokens, self.max_blocks,
                            cache.pool_pages)

    def page_shapes(self) -> list[tuple[tuple, object]]:
        """(shape, dtype) of ONE page per pool array — the frame
        geometry of the host tier and staging rings."""
        cache = self.cache
        return [((cache.page_tokens,) + tuple(cache.specs[i][2]),
                 np.dtype(cache.arrays[i].dtype))
                for i in cache.pool_indices]

    def carry_shapes(self) -> list[tuple[tuple, object]]:
        """(shape, dtype) of one slot's carry rows (LSTM chains)."""
        cache = self.cache
        return [(tuple(cache.specs[i][2]),
                 np.dtype(cache.arrays[i].dtype))
                for i in cache.slot_indices]

    # ------------------------------------------------------------------
    # dispatch (ONE thread per cache — no locking needed on a cache;
    # ``cache=None`` means the model's own.  Pool replicas pass their
    # private same-geometry cache and reuse every compiled program.)
    # ------------------------------------------------------------------
    def run_prefill(self, tokens: np.ndarray, slot: int,
                    start: int = 0, cache: PagedKVCache | None = None
                    ) -> np.ndarray:
        """Prefill one prompt window into ``slot``; returns the last
        real position's logits (V,).  ``tokens`` are the positions
        ``start..start+len-1`` — the whole prompt for a fresh
        admission (``start=0``), the unshared tail after a
        prefix-cache hit (paged only)."""
        cache = cache if cache is not None else self.cache
        n = int(tokens.shape[0])
        if start + n > self.max_prompt:
            raise ValueError(f"prompt of {start + n} tokens exceeds "
                             f"max_prompt {self.max_prompt}")
        t_b = bucket_for(n, self.prompt_align)
        padded = np.zeros((1, t_b), np.int32)
        padded[0, :n] = tokens
        if not self.paged:
            if start:
                raise ValueError("flat cache cannot tail-prefill")
            prog = self.prefill_program(t_b)
            caches, logits = prog(cache.arrays, self._weights,
                                  padded, np.asarray(slot, np.int32),
                                  np.asarray(n, np.int32))
            cache.arrays = caches
            return np.asarray(logits, np.float32)[0]
        nb = self.nb_for(start + t_b - 1)
        prog = self.paged_prefill_program(t_b, nb)
        caches, logits = prog(
            cache.arrays, self._weights, padded,
            cache.table_operand(slot, nb),
            np.asarray(slot, np.int32), np.asarray(start, np.int32),
            np.asarray(n, np.int32))
        cache.arrays = caches
        return np.asarray(logits, np.float32)[0]

    def run_decode(self, tokens: np.ndarray, slots: np.ndarray,
                   positions: np.ndarray,
                   cache: PagedKVCache | None = None) -> np.ndarray:
        """One token step for ``len(tokens)`` live lanes; pads to the
        covering live-batch bucket (padded lanes ride the scratch
        slot/trash table).  Returns logits (n_live, V)."""
        cache = cache if cache is not None else self.cache
        n = int(tokens.shape[0])
        b_b = bucket_for(n)

        def padded(arr, fill):
            out = np.full((b_b,), fill, np.int32)
            out[:n] = arr
            return out

        if not self.paged:
            prog = self.decode_program(b_b)
            caches, logits = prog(
                cache.arrays, self._weights, padded(tokens, 0),
                padded(slots, cache.trash_slot),
                padded(positions, 0))
            cache.arrays = caches
            return np.asarray(logits, np.float32)[:n]
        nb = self.nb_for(int(positions.max()))
        tables = np.full((b_b, nb + 1), cache.trash_page,
                         np.int32)
        tables[:n, :nb] = cache.tables[slots, :nb]
        prog = self.paged_decode_program(b_b, nb)
        caches, logits = prog(
            cache.arrays, self._weights, padded(tokens, 0),
            tables, padded(slots, cache.trash_slot),
            padded(positions, 0))
        cache.arrays = caches
        return np.asarray(logits, np.float32)[:n]

    def run_window(self, windows: np.ndarray, slots: np.ndarray,
                   positions: np.ndarray, lengths: np.ndarray,
                   site: str = "serving-verify",
                   cache: PagedKVCache | None = None) -> np.ndarray:
        """Batched window dispatch: ``windows`` (n, W) token windows
        starting at per-lane ``positions`` with ``lengths`` real
        tokens each; ONE forward writes all live K/V through the page
        tables and returns logits (n, W, V)."""
        cache = cache if cache is not None else self.cache
        n, w_len = windows.shape
        b_b = bucket_for(n)
        nb = self.nb_for(int(positions.max()) + w_len - 1)
        win = np.zeros((b_b, w_len), np.int32)
        win[:n] = windows
        tables = np.full((b_b, nb + 1), cache.trash_page,
                         np.int32)
        tables[:n, :nb] = cache.tables[slots, :nb]
        pos = np.zeros((b_b,), np.int32)
        pos[:n] = positions
        lens = np.zeros((b_b,), np.int32)
        lens[:n] = lengths
        prog = self.window_program(b_b, int(w_len), nb, site=site)
        caches, logits = prog(cache.arrays, self._weights, win,
                              tables, pos, lens)
        cache.arrays = caches
        return np.asarray(logits, np.float32)[:n]

    def run_verify(self, windows: np.ndarray, slots: np.ndarray,
                   positions: np.ndarray,
                   cache: PagedKVCache | None = None) -> np.ndarray:
        """Speculative verification: ``windows`` (n, spec_k+1) token
        windows starting at per-lane ``positions``; logits at every
        window position (n, spec_k+1, V)."""
        if not self.spec_k:
            raise RuntimeError("spec_k=0 — no verify family planned")
        lengths = np.full((windows.shape[0],), self.spec_k + 1,
                          np.int32)
        return self.run_window(windows, slots, positions, lengths,
                               cache=cache)

    def copy_page(self, src: int, dst: int,
                  cache: PagedKVCache | None = None) -> None:
        """Device-copy one page across every attention pool — the COW
        a partial prefix match pays before its divergent tail."""
        cache = cache if cache is not None else self.cache
        prog = self.copy_program()
        cache.arrays = prog(cache.arrays,
                            np.asarray(src, np.int32),
                            np.asarray(dst, np.int32))

    # ------------------------------------------------------------------
    # page / carry I-O (round 22): the data plane of spill restores
    # and prefill→decode handoffs
    # ------------------------------------------------------------------
    def export_page(self, pid: int,
                    cache: PagedKVCache | None = None
                    ) -> list[np.ndarray]:
        """D2H-copy page ``pid`` out of every attention pool — one
        (page_tokens, H, Dh) host array per pool, the unit the host
        tier stores and a handoff ships."""
        cache = cache if cache is not None else self.cache
        return [np.asarray(cache.arrays[i][pid])
                for i in cache.pool_indices]

    def page_in(self, pages, dst: int,
                cache: PagedKVCache | None = None) -> None:
        """Scatter one page (device or host arrays, one per pool)
        into pool row ``dst`` — a spill restore or handoff landing."""
        cache = cache if cache is not None else self.cache
        cache.arrays = self.page_in_program()(
            cache.arrays, tuple(pages), np.asarray(dst, np.int32))

    def export_carry(self, slot: int,
                     cache: PagedKVCache | None = None
                     ) -> list[np.ndarray]:
        """D2H-copy slot ``slot``'s recurrent carry rows (LSTM h/c) —
        the non-paged half of a handoff."""
        cache = cache if cache is not None else self.cache
        return [np.asarray(cache.arrays[i][slot])
                for i in cache.slot_indices]

    def carry_in(self, rows, slot: int,
                 cache: PagedKVCache | None = None) -> None:
        """Scatter carry rows into slot ``slot``."""
        cache = cache if cache is not None else self.cache
        cache.arrays = self.carry_in_program()(
            cache.arrays, tuple(rows), np.asarray(slot, np.int32))

    # ------------------------------------------------------------------
    # weight hot-swap (round 13)
    # ------------------------------------------------------------------
    def check_compatible(self, manifest: dict | None,
                         params: dict) -> None:
        """Validate a candidate against the planned chain; raises
        :class:`~znicz_tpu.export.SwapIncompatible` with the incumbent
        untouched on any mismatch."""
        from znicz_tpu.export import SwapIncompatible
        if manifest is not None:
            mine = [layer["type"] for layer
                    in self.model.manifest["layers"]]
            theirs = [layer["type"] for layer
                      in manifest.get("layers", [])]
            if mine != theirs:
                raise SwapIncompatible(
                    f"candidate layer table {theirs} != decode chain "
                    f"{mine}")
        for op, ws in zip(self._plan, self._weights):
            for key, cur in zip(op.wkeys, ws):
                new = params.get(key)
                if cur is None:
                    if new is not None:
                        raise SwapIncompatible(
                            f"{key}: candidate carries a parameter "
                            f"the compiled programs have no operand "
                            f"for")
                    continue
                if new is None:
                    raise SwapIncompatible(
                        f"candidate is missing parameter '{key}'")
                shape = tuple((cur[0] if isinstance(cur, tuple)
                               else cur).shape)
                if tuple(np.shape(new)) != shape:
                    raise SwapIncompatible(
                        f"{key}: candidate shape "
                        f"{tuple(np.shape(new))} != compiled "
                        f"{shape}")

    def swap_weights(self, params: dict,
                     manifest: dict | None = None) -> int:
        """Replace the weight operand pytree without recompiling:
        validate → stage (device_put onto each leaf's existing
        placement, fenced) → publish the new immutable tuple in one
        assignment.  The caller (:meth:`DecodeEngine.swap_weights`)
        guarantees no decode step is mid-flight when the flip lands —
        slots carrying old-model generations drain first."""
        import jax
        from znicz_tpu.export import SwapIncompatible
        from znicz_tpu.serving import quantize as _quantize
        qkeys = getattr(self.model, "_qkeys", frozenset())
        cand_rec = _quantize.is_quantized(manifest)
        if qkeys:
            if cand_rec is None:
                raise SwapIncompatible(
                    "candidate is f32 but the decode chain compiled "
                    "int8 dequantize-on-load programs — republish "
                    "the candidate with quantize='int8' (or restart "
                    "the replica f32)")
            if frozenset(cand_rec.get("weights", [])) != qkeys:
                raise SwapIncompatible(
                    "candidate quantizes a different key set than "
                    "the compiled programs "
                    f"({sorted(cand_rec.get('weights', []))} != "
                    f"{sorted(qkeys)})")
        elif cand_rec is not None:
            # quantized candidate into an f32-compiled chain:
            # dequantize host-side and stage f32 — recompile-free
            params = _quantize.dequantize_params(manifest, params)
        self.check_compatible(manifest, params)
        staged = []
        for op, ws in zip(self._plan, self._weights):
            new_ws = []
            for key, cur in zip(op.wkeys, ws):
                if cur is None:
                    new_ws.append(None)
                    continue
                if isinstance(cur, tuple):  # int8 (q, scale) operand
                    skey = _quantize.scale_key(key)
                    q = np.asarray(params[key], np.int8)
                    s = np.asarray(params[skey], np.float32)
                    arr = (jax.device_put(q), jax.device_put(s))
                    self.model._params[key] = q
                    self.model._params[skey] = s
                else:
                    new = np.asarray(params[key], np.float32)
                    sharding = getattr(cur, "sharding", None)
                    arr = (jax.device_put(new, sharding)
                           if sharding is not None
                           else jax.device_put(new))
                    self.model._params[key] = new
                new_ws.append(arr)
            staged.append(tuple(new_ws))
        for ws in staged:  # fence before publishing
            for leaf in ws:
                if leaf is None:
                    continue
                for a in (leaf if isinstance(leaf, tuple)
                          else (leaf,)):
                    a.block_until_ready()
        self._weights = tuple(staged)
        self.weights_version += 1
        return self.weights_version


class _PromptReq:
    """One queued generation request.

    ``pause_s`` accumulates the admission-pause time (swap drains)
    that overlapped this request's queue wait: TTFT observations and
    the TTFT deadline both stamp from **admission-eligible** time
    (``t_submit + pause_s``), so a drain neither pollutes the serving
    SLO histograms nor expires a request the engine was forbidden to
    admit (round-13 documented noise band, fixed in round 15)."""

    __slots__ = ("tokens", "n", "max_new", "future", "t_submit",
                 "deadline", "pause_s", "charged", "tenant", "priority",
                 "trace")

    def __init__(self, tokens: np.ndarray, max_new: int,
                 deadline_ms: float | None,
                 tenant: str | None = None, priority: int = 0) -> None:
        self.tokens = tokens
        self.n = int(tokens.shape[0])
        self.max_new = int(max_new)
        self.future: Future = Future()
        self.t_submit = time.monotonic()
        self.pause_s = 0.0
        self.charged = 0  # tokens held against the admission budget
        self.tenant = tenant
        self.priority = int(priority)
        self.deadline = (None if deadline_ms is None
                         else self.t_submit + float(deadline_ms) / 1e3)
        # request-scoped trace context (round 24): minted HERE at
        # submit (or adopted from the fleet router, which stamped its
        # routing decision on it first) and riding the request object
        # through queue → prefill → [handoff →] decode
        self.trace = (_tracing.adopt_pending_trace()
                      or _tracing.new_request_trace(
                          "request", tokens=self.n,
                          tenant=tenant or "-"))
        self.trace.phase_begin("queue")

    def expired(self, now: float) -> bool:
        return self.deadline is not None \
            and now >= self.deadline + self.pause_s


class _Live:
    """Host-side state of one sequence mid-generation."""

    __slots__ = ("req", "slot", "pos", "generated", "t_last")

    def __init__(self, req: _PromptReq, slot: int, first_token: int
                 ) -> None:
        self.req = req
        self.slot = slot
        #: position the NEXT input token will occupy (= prompt length
        #: right after prefill; the sampled token is fed back there)
        self.pos = req.n
        self.generated = [int(first_token)]
        self.t_last = time.monotonic()


class _PageSetupMixin:
    """Paged admission shared by :class:`DecodeEngine` and the
    disaggregated prefill workers (serving/disagg.py): prefix match →
    share/COW/alloc → spill-tier room-making.  The host expects
    ``self.model`` (a :class:`DecodeModel`), ``self.prefix``
    (:class:`PrefixCache` or None), ``self._spill``
    (``memory.HostPageTier`` or None), ``self._obs_id`` and the
    prefix/migration metric children; :meth:`_kv_cache` names the
    cache the host schedules (a pool worker's private replica cache,
    the engine's own otherwise)."""

    def _kv_cache(self) -> PagedKVCache:
        return self.model.cache

    def _setup_pages(self, slot: int, tokens: np.ndarray,
                     max_new: int) -> int:
        """Map the request's blocks into ``slot``'s table: shared full
        blocks by reference, a partially-matched boundary block via
        copy-on-write, fresh pages for the rest — RESERVING the whole
        worst-case span (prompt + token budget, capped at max_t) up
        front, so an admitted request can never be page-starved
        mid-generation and pool pressure degrades as deterministic
        admission shedding, never as a truncated neighbor.  Returns
        the matched token count (the tail prefill starts there).
        Raises :class:`PoolExhausted` with the slot's table cleaned."""
        model = self.model
        cache = self._kv_cache()
        n = int(tokens.shape[0])
        nodes: list = []
        matched = 0
        cow = None
        if self.prefix is not None:
            nodes, matched, cow = self.prefix.match_nodes(tokens)
        span = min(n + int(max_new), model.max_t)
        nblocks = -(-span // model.page_tokens)
        # Two-phase pinning (round 22, generalizing the round-15
        # pin-before-evict rule to the spill tier).  Phase 1 pins
        # every HBM-resident matched block into the slot's table
        # BEFORE any room-making: a restore below may spill or evict
        # other trie pages, and a matched-but-unpinned HBM page must
        # never be a victim.  Phase 2 restores host-resident matched
        # blocks one at a time, pinning each the moment it lands
        # (ref 2 = trie + slot, so spill_candidate's ref==1 test
        # can't re-spill it while we restore the next).  Host-
        # resident blocks are safe to defer: evict() only takes
        # page-resident leaves, and the host tier frees nothing on
        # its own.
        donor_pinned = False
        try:
            for b, node in enumerate(nodes):
                if node.page is not None:
                    cache.share_block(slot, b, node.page)
            if cow is not None and cow[0].page is not None:
                cache.ref[cow[0].page] += 1  # donor pin till copy
                donor_pinned = True
            for b, node in enumerate(nodes):
                if node.page is None:
                    self._restore_node(node)
                    cache.share_block(slot, b, node.page)
            if cow is not None and not donor_pinned:
                self._restore_node(cow[0])
                cache.ref[cow[0].page] += 1
                donor_pinned = True
            need_new = nblocks - len(nodes)
            if cache.free_pages < need_new:
                self._make_room(need_new)
            base = len(nodes)
            if cow is not None:
                pid = cache.new_block(slot, base)
                # the divergence copy: shared positions of the
                # boundary block come along, the divergent tail
                # overwrites its own private copy
                model.copy_page(cow[0].page, pid, cache=cache)
                base += 1
            for b in range(base, nblocks):
                cache.new_block(slot, b)
        except PoolExhausted:
            cache.release_slot_pages(slot)
            raise
        finally:
            if donor_pinned:
                cache.ref_dec(cow[0].page)
        if self.prefix is not None:
            if matched > 0:
                self._m_prefix_hit.inc()
                self._m_tok_shared.inc(matched)
            else:
                self._m_prefix_miss.inc()
            self._m_tok_computed.inc(n - matched)
        return matched

    def _restore_node(self, node) -> None:
        """Bring one host-resident trie block back to an HBM page
        through the staging ring; the node's trie pin moves tiers
        with it (frame freed, fresh page ref 1)."""
        cache = self._kv_cache()
        if cache.free_pages < 1:
            self._make_room(1)
        pid = cache.alloc_page()  # ref 1 = the trie pin, now on HBM
        dev = self._spill.upload(node.host)
        self.model.page_in(dev, pid, cache=cache)
        self._spill.free(node.host)
        node.page, node.host = pid, None
        self._m_mig_restore.inc()

    def _make_room(self, pages_needed: int) -> None:
        """Free HBM pages for an admission: spill cold shareable
        blocks to the host tier while it has frames, then fall back
        to plain trie eviction.  No-op without a prefix cache —
        new_block raises PoolExhausted and admission requeues."""
        if self.prefix is None:
            return
        cache = self._kv_cache()
        while cache.free_pages < pages_needed:
            if self._spill is not None and not self._spill.full:
                victim = self.prefix.spill_candidate(cache)
                if victim is not None:
                    hid = self._spill.store(
                        self.model.export_page(victim.page,
                                               cache=cache))
                    # sole holder was the trie pin → page frees now
                    cache.ref_dec(victim.page)
                    victim.page, victim.host = None, hid
                    self._m_mig_spill.inc()
                    continue
            evicted = self.prefix.evict(cache, pages_needed)
            if evicted:
                _metrics.prefix_cache_events(
                    self._obs_id, "evicted").inc(evicted)
            return


class DecodeEngine(_PageSetupMixin, Logger):
    """Continuous-batching token server over a :class:`DecodeModel`.

    Lifecycle mirrors :class:`~znicz_tpu.serving.ServingEngine`::

        with DecodeEngine("lm.npz", max_slots=4, max_t=64) as eng:
            tokens = eng.generate(prompt)            # sync
            future = eng.submit(prompt)              # async
            tokens = future.result()                 # np.int32 ids

    ``temperature=0`` (default) decodes greedily — byte-for-byte
    reproducible against the numpy oracle; ``temperature>0`` samples
    from the softmax on the host with a seeded generator (the logits
    cross anyway: sampling adds no device work).

    Scheduling: ``admission="continuous"`` (default) admits queued
    prompts into the in-flight batch between token steps; ``"static"``
    admits only when the previous batch fully drained —
    run-to-completion, the serve_bench A/B baseline.

    Degradation: ``deadline_ms`` bounds **TTFT** (a prompt still
    queued past it fails fast with :class:`DeadlineExceeded` and never
    occupies a slot); the circuit breaker watches dispatch outcomes
    and, while open, sheds NEW prompts with :class:`Overloaded` while
    in-flight sequences keep decoding to completion (the drain
    contract — generation in progress is the last thing to abandon).
    """

    def __init__(self, model, *, max_slots: int = 4, max_t: int = 64,
                 max_prompt: int | None = None, prompt_align: int = 8,
                 max_new_tokens: int = 32,
                 eos_token: int | None = None,
                 temperature: float = 0.0, seed: int = 0,
                 max_queue: int = 256,
                 admission: str = "continuous",
                 retry_budget: int = 1,
                 breaker_failure_rate: float = 0.5,
                 breaker_window: int = 8,
                 breaker_min_samples: int = 4,
                 breaker_cooldown_ms: float = 1000.0,
                 paged: bool | None = None,
                 page_tokens: int | None = None,
                 pool_tokens: int | None = None,
                 prefix_cache: bool | None = None,
                 spec_draft_k: int | None = None,
                 drafter=None,
                 max_queue_tokens: int | None = None,
                 max_queue_age_ms: float = 10_000.0,
                 kv_quant: bool | None = None,
                 kv_dtype=None,
                 spill_pages: int | None = None,
                 device=None) -> None:
        super().__init__()
        from znicz_tpu.serving.batcher import TokenBudget
        from znicz_tpu.utils.config import root
        if not isinstance(model, DecodeModel):
            from znicz_tpu.export import ExportedModel
            if isinstance(model, (str, bytes)) \
                    or hasattr(model, "__fspath__"):
                model = ExportedModel.load(model, device=device)
            decode_meta = dict(model.manifest.get("decode", {}))
            explicit_k = spec_draft_k is not None
            if spec_draft_k is None:
                spec_draft_k = int(decode_meta.get(
                    "spec_draft_k",
                    root.common.engine.get("spec_draft_k", 0)))
            if drafter is None:
                drafter = decode_meta.get("drafter")
            if drafter is None:
                if explicit_k and spec_draft_k:
                    raise ValueError(
                        "spec_draft_k > 0 needs a drafter bundle "
                        "(path, ExportedModel or DecodeModel)")
                spec_draft_k = 0  # default-config engines: spec off
            model = DecodeModel(model, max_slots=max_slots,
                                max_t=max_t, max_prompt=max_prompt,
                                prompt_align=prompt_align,
                                device=device, paged=paged,
                                page_tokens=page_tokens,
                                pool_tokens=pool_tokens,
                                spec_k=int(spec_draft_k or 0),
                                kv_quant=kv_quant, kv_dtype=kv_dtype)
        self.model = model
        self.spec_k = int(model.spec_k)
        # the drafter: a SMALL published bundle (population-trained)
        # decoding through its own flat cache at the same geometry —
        # slot ids are shared with the big model, so the two caches
        # track the same sequences
        self.drafter: DecodeModel | None = None
        if self.spec_k:
            if drafter is None:
                raise ValueError(
                    "spec_draft_k > 0 needs a drafter bundle "
                    "(path, ExportedModel or DecodeModel)")
            if not isinstance(drafter, DecodeModel):
                drafter = DecodeModel(
                    drafter, max_slots=model.max_slots,
                    max_t=model.max_t, max_prompt=model.max_prompt,
                    prompt_align=model.prompt_align,
                    device=device, paged=False, spec_k=0)
            if drafter.vocab != model.vocab:
                raise ValueError(
                    f"drafter vocab {drafter.vocab} != model vocab "
                    f"{model.vocab} — the draft/verify token spaces "
                    f"must agree")
            self.drafter = drafter
        if prefix_cache is None:
            prefix_cache = bool(root.common.engine.get(
                "prefix_cache", True))
        # prefix sharing needs the page table and position-indexed
        # state only (LSTM carries summarize the WHOLE prefix in one
        # vector — nothing block-shaped to share)
        self.prefix_cache_enabled = bool(
            prefix_cache and model.paged and not model.has_lstm)
        self.prefix = (PrefixCache(model.page_tokens)
                       if self.prefix_cache_enabled else None)
        # round 22: host-DRAM spill tier behind the prefix trie —
        # cold pages leave HBM for preallocated pinned-style host
        # frames and restore through the staging-ring uploader, so
        # the shareable working set is pool_pages + spill_pages
        if spill_pages is None:
            spill_pages = int(root.common.engine.get(
                "kv_spill_pages", 0))
        self._spill = None
        if self.prefix_cache_enabled and int(spill_pages) > 0:
            from znicz_tpu.memory import HostPageTier
            self._spill = HostPageTier(model.page_shapes(),
                                       int(spill_pages))
        self._token_budget = None
        if model.paged:
            budget = (int(max_queue_tokens) if max_queue_tokens
                      else 16 * model.pool_tokens)
            self._token_budget = TokenBudget(budget)
        #: pool-exhaustion shed threshold: a full pool with a YOUNG
        #: queue is normal continuous-batching backlog (requeue and
        #: wait for a lane to drain); only a STALLED queue sheds —
        #: the same age semantics as the batcher's stall trip
        self.max_queue_age = float(max_queue_age_ms) / 1e3
        if admission not in ("continuous", "static"):
            raise ValueError(f"admission must be 'continuous' or "
                             f"'static', got {admission!r}")
        self.admission = admission
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token = eos_token
        self.temperature = float(temperature)
        self.max_queue = int(max_queue)
        self.retry_budget = max(0, int(retry_budget))
        self.breaker_failure_rate = float(breaker_failure_rate)
        self.breaker_min_samples = int(breaker_min_samples)
        self.breaker_cooldown = float(breaker_cooldown_ms) / 1e3
        self._rng = np.random.default_rng(seed)
        # telemetry: per-engine children of the canonical series
        wf_name = model.model.manifest.get("workflow", "model")
        self._obs_id = f"{wf_name}#decode{next(_DECODE_SEQ)}"
        self._m_submitted = _metrics.serving_requests(
            self._obs_id, "submitted")
        self._m_served = _metrics.serving_requests(self._obs_id,
                                                   "served")
        self._m_rejected = _metrics.serving_requests(self._obs_id,
                                                     "rejected")
        self._m_ttft = _metrics.serving_ttft_seconds(self._obs_id)
        self._m_token = _metrics.serving_token_seconds(self._obs_id)
        self._m_tok_prompt = _metrics.serving_tokens(self._obs_id,
                                                     "prompt")
        self._m_tok_gen = _metrics.serving_tokens(self._obs_id,
                                                  "generated")
        self._m_slots = _metrics.serving_decode_slots(self._obs_id)
        self._m_state = _metrics.serving_breaker_state(self._obs_id)
        self._m_state.set(_STATE_CODE[_CLOSED])
        # round 15: paged/prefix/speculation canonical series
        self._m_swap_pause = _metrics.swap_pause_seconds(self._obs_id)
        if model.paged:
            _metrics.kv_pages_total(self._obs_id).set(
                model.cache.pool_pages)
            _metrics.kv_pages_used(self._obs_id).set_function(
                model.cache.pages_used)
        # round 21: KV bytes amortized per concurrent lane — the
        # number int8 pages halve at fixed geometry (cache geometry
        # is fixed at construction, so one set() suffices)
        _metrics.kv_bytes_per_lane(self._obs_id).set(
            model.cache.nbytes() / max(1, model.max_slots))
        # round 22: migration traffic + tier occupancy + queue age
        self._m_mig_spill = _metrics.kv_page_migrations(
            self._obs_id, "spill")
        self._m_mig_restore = _metrics.kv_page_migrations(
            self._obs_id, "restore")
        if self._spill is not None:
            tier = self._spill
            _metrics.kv_spill_pages(self._obs_id).set_function(
                lambda: tier.used)
        _metrics.serving_queue_age_seconds(
            self._obs_id, pool="all").set_function(self._queue_age)
        self._m_prefix_hit = _metrics.prefix_cache_events(
            self._obs_id, "hit")
        self._m_prefix_miss = _metrics.prefix_cache_events(
            self._obs_id, "miss")
        self._m_tok_shared = _metrics.prefix_tokens(self._obs_id,
                                                    "shared")
        self._m_tok_computed = _metrics.prefix_tokens(self._obs_id,
                                                      "computed")
        self._m_spec_acc = _metrics.spec_tokens(self._obs_id,
                                                "accepted")
        self._m_spec_rej = _metrics.spec_tokens(self._obs_id,
                                                "rejected")
        self.page_truncations = 0
        #: breaker opened by pool pressure (not failures): it closes
        #: again the moment a requeued prompt admits — capacity
        #: recovery needs no cooldown, unlike a failing backend
        self._pool_tripped = False
        # exact-value windows for dashboard percentiles
        self._ttft_win: deque = deque(maxlen=4096)
        self._token_win: deque = deque(maxlen=4096)
        # round 24: per-phase latency windows fed by the request
        # traces, exported as znicz_phase_p99_seconds callback gauges
        # so SERVE_BENCH rows and /metrics read the SAME exact
        # windowed p99 (handoff only moves on the disagg subclass)
        self._phase_win: dict[str, deque] = {
            p: deque(maxlen=4096)
            for p in ("queue", "prefill", "handoff", "decode")}
        for _p, _win in self._phase_win.items():
            _metrics.phase_p99_seconds(self._obs_id, _p).set_function(
                lambda w=_win: _metrics.window_p99(w))
        _metrics.phase_p99_seconds(self._obs_id, "ttft").set_function(
            lambda w=self._ttft_win: _metrics.window_p99(w))
        _metrics.phase_p99_seconds(self._obs_id, "token").set_function(
            lambda w=self._token_win: _metrics.window_p99(w))
        #: queued prompts in priority classes (round 16): the fleet's
        #: high-priority tenants reach a KV slot before any flooded
        #: low class, FIFO within a class
        self._pending = PriorityQueue()
        self._live: list[_Live] = []
        self._cond = threading.Condition()
        self._stop = False
        self._state = _CLOSED
        self._opened_at = 0.0
        self._outcomes: deque[bool] = deque(maxlen=int(breaker_window))
        self.expired_total = 0
        self.shed_total = 0
        self.retries_total = 0
        self.warmup_compiles = 0
        self.warmup_seconds = 0.0
        self._thread: threading.Thread | None = None
        self._started = False
        # hot-swap bookkeeping (round 13): a pending swap request the
        # scheduler applies between token steps once old-model lanes
        # drained (or the engine.swap_drain_ms bound expires)
        self._swap_req: dict | None = None
        self.model_version = 0
        self._m_version = _metrics.model_version(self._obs_id)
        self._m_version.set(0)
        self._m_swap_dur = _metrics.swap_duration_seconds(self._obs_id)
        self.swap_counts = {"promoted": 0, "rejected": 0,
                            "rolled_back": 0}
        self._swap_pauses: list[float] = []

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> "DecodeEngine":
        if self._started:
            return self
        t0 = time.monotonic()
        self.warmup_compiles = self.model.warmup(
            prefix_cache=self.prefix_cache_enabled,
            page_io=self._spill is not None)
        if self.drafter is not None:
            self.warmup_compiles += self.drafter.warmup()
        self.warmup_seconds = time.monotonic() - t0
        self._thread = threading.Thread(target=self._loop,
                                        name="decode-scheduler",
                                        daemon=True)
        self._started = True
        self._thread.start()
        self.info(
            "decode '%s': %d AOT programs warmed in %.2fs (prompt "
            "buckets %s, batch buckets %s, block buckets %s, "
            "slots=%d, max_t=%d, paged=%s, prefix_cache=%s, "
            "spec_k=%d, cache=%.1f MB, donate=%s)",
            self.model.model.manifest.get("workflow", "?"),
            self.warmup_compiles, self.warmup_seconds,
            self.model.prompt_ladder(), self.model.batch_ladder(),
            self.model.block_ladder(), self.model.max_slots,
            self.model.max_t, self.model.paged,
            self.prefix_cache_enabled, self.spec_k,
            self.model.cache.nbytes() / 1e6, self.model.donating)
        return self

    def shutdown(self, timeout: float = 60.0) -> None:
        """Drain: everything admitted keeps generating to completion,
        queued prompts are served, then the scheduler exits."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        self._started = False
        if self._spill is not None:
            self._spill.shutdown()
        # a stopped engine is not shedding: clear the breaker so the
        # process-level /readyz (which scans EVERY engine child of the
        # breaker gauge) doesn't stay not-ready on a dead engine's
        # last state
        with self._cond:
            self._state = _CLOSED
            self._outcomes.clear()
            self._m_state.set(_STATE_CODE[_CLOSED])

    def __enter__(self) -> "DecodeEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # ------------------------------------------------------------------
    # request path
    # ------------------------------------------------------------------
    def submit(self, prompt, *, max_new_tokens: int | None = None,
               deadline_ms: float | None = None,
               tenant: str | None = None, priority: int = 0) -> Future:
        """Enqueue a prompt (1-D array of token ids); returns a future
        of the generated ids (np.int32, the first sampled token
        onward).  Raises :class:`QueueFull` under backpressure,
        :class:`Overloaded` while the breaker sheds, and the future
        fails with :class:`DeadlineExceeded` if ``deadline_ms`` passes
        before the first token (TTFT deadline).  ``tenant`` /
        ``priority`` (round 16): queued prompts admit to KV slots in
        strict priority order, and a token-budget-full queue sheds the
        NEWEST strictly lower-priority queued prompts to make room for
        a higher-priority arrival."""
        if not self._started:
            raise RuntimeError("engine not started — call start()")
        prompt = np.asarray(np.round(np.asarray(prompt, np.float64)),
                            np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if prompt.size > self.model.max_prompt:
            raise ValueError(
                f"prompt of {prompt.size} tokens exceeds max_prompt "
                f"{self.model.max_prompt} — truncate client-side")
        if deadline_ms is not None and deadline_ms <= 0:
            raise DeadlineExceeded(
                f"deadline_ms={deadline_ms} already expired at submit")
        req = _PromptReq(prompt,
                         max_new_tokens or self.max_new_tokens,
                         deadline_ms, tenant=tenant, priority=priority)
        preempted: list[_PromptReq] = []
        with self._cond:
            if self._stop:
                raise RuntimeError("engine is shut down")
            self._breaker_tick(time.monotonic())
            if self._state == _OPEN:
                self.shed_total += 1
                _metrics.serving_requests(self._obs_id, "shed").inc()
                self._m_rejected.inc()
                req.trace.event("breaker_shed", engine=self._obs_id)
                self._finish_trace(req, "shed")
                raise Overloaded(
                    "circuit breaker open — new prompts shed while "
                    "in-flight decodes drain (retry after "
                    f"{self.breaker_cooldown * 1e3:.0f}ms)")
            if len(self._pending) >= self.max_queue:
                self._m_rejected.inc()
                self._finish_trace(req, "shed")
                raise QueueFull(
                    f"decode queue full ({len(self._pending)} prompts "
                    f"pending, limit {self.max_queue})")
            if self._token_budget is not None:
                # token-denominated admission: the queue is bounded by
                # the WORK it holds (prompt + budget tokens), not the
                # request count — the bound that matches a pool whose
                # capacity is tokens
                want = req.n + req.max_new
                if not self._token_budget.try_acquire(want):
                    # preemptive admission (round 16): shed queued
                    # prompts of strictly LOWER priority, newest
                    # first, when that frees enough budget — the
                    # flooding class absorbs its own overload
                    preempted = self._make_budget_room(req, want)
                    if not self._token_budget.try_acquire(want):
                        self._m_rejected.inc()
                        self._finish_trace(req, "shed")
                        raise QueueFull(
                            f"decode token budget full "
                            f"({self._token_budget.used} of "
                            f"{self._token_budget.capacity} tokens "
                            f"held; request wants {want})")
                req.charged = want
            self._pending.append(req)
            self._cond.notify_all()
        for victim in preempted:  # fail outside the condition
            victim.trace.event("preempted", engine=self._obs_id)
            self._finish_trace(victim, "shed")
            if not victim.future.done():
                victim.future.set_exception(Overloaded(
                    "preempted by higher-priority traffic while the "
                    "decode token budget was full"))
        self._m_submitted.inc()
        return req.future

    def _make_budget_room(self, req: _PromptReq,
                          want: int) -> list[_PromptReq]:
        """Evict queued (never live) strictly lower-priority prompts,
        newest first, until ``want`` tokens could be acquired; returns
        the victims (their futures are failed by the caller outside
        the lock).  Call under ``_cond``."""
        victims: list[_PromptReq] = []
        if self._token_budget is None:
            return victims
        evictable = sorted(
            (r for r in self._pending
             if r.priority > req.priority and r.charged),
            key=lambda r: r.t_submit, reverse=True)
        if sum(r.charged for r in evictable) \
                + self._token_budget.available < want:
            return victims  # preemption cannot make room — shed req
        for victim in evictable:
            if self._token_budget.available >= want:
                break
            victims.append(victim)
            self._refund(victim)
            self.shed_total += 1
            _metrics.serving_requests(self._obs_id, "shed").inc()
        removed = set(map(id, victims))
        self._pending.sweep(lambda r: id(r) in removed)
        return victims

    def _refund(self, req: _PromptReq) -> None:
        if req.charged and self._token_budget is not None:
            self._token_budget.release(req.charged)
            req.charged = 0

    def generate(self, prompt, timeout: float | None = None,
                 **kwargs) -> np.ndarray:
        """Synchronous convenience: submit + wait."""
        return self.submit(prompt, **kwargs).result(timeout=timeout)

    # ------------------------------------------------------------------
    # weight hot-swap (round 13)
    # ------------------------------------------------------------------
    def current_bundle(self) -> tuple:
        """The live ``(manifest, params)`` — the rollback target a
        SwapController snapshots before promoting."""
        return (self.model.model.manifest,
                dict(self.model.model._params))

    def swap_weights(self, state, *, version: int | None = None,
                     drain_ms: float | None = None,
                     timeout: float | None = None,
                     outcome: str = "promoted") -> dict:
        """Hot-swap the decode weights without recompiling.

        In-flight generations belong to the OLD model: the scheduler
        stops admitting new prompts, lets live KV-cache slots decode
        to completion, and only then publishes the new weight pytree —
        so no sequence ever mixes two models' logits.  Lanes still
        live after ``drain_ms`` (default ``engine.swap_drain_ms``) are
        evicted with their tokens-so-far rather than holding the swap
        hostage.  Queued prompts are admitted AFTER the flip and
        prefill against the new model.

        Raises :class:`~znicz_tpu.export.SwapIncompatible` (validated
        before any drain starts — the incumbent keeps serving)."""
        from znicz_tpu.serving.engine import resolve_swap_state
        from znicz_tpu.utils.config import root
        manifest, params = resolve_swap_state(state)
        # fail BEFORE draining anything: an incompatible candidate
        # must not pause admission for even a millisecond
        self.model.check_compatible(manifest, params)
        if drain_ms is None:
            drain_ms = float(root.common.engine.get(
                "swap_drain_ms", 2000.0))
        t0 = time.monotonic()
        if not self._started:
            self.model.swap_weights(params, manifest=manifest)
            drain = {"drained": 0, "evicted": 0, "drain_ms": 0.0}
        else:
            fut: Future = Future()
            with self._cond:
                if self._swap_req is not None:
                    raise RuntimeError(
                        "a weight swap is already in progress")
                self._swap_req = {
                    "manifest": manifest, "params": params,
                    "deadline": t0 + float(drain_ms) / 1e3,
                    "future": fut, "t0": t0,
                    "live0": len(self._live)}
                self._cond.notify_all()
            drain = fut.result(
                timeout if timeout is not None
                else max(60.0, float(drain_ms) / 1e3 + 60.0))
        pause = time.monotonic() - t0
        if version is None:
            version = self.model_version + 1
        self.model_version = int(version)
        self._m_version.set(self.model_version)
        self._m_swap_dur.observe(pause)
        self._swap_pauses.append(pause)
        self.record_swap_outcome(outcome)
        self.info(
            "decode weights hot-swapped → version %d (%s, %.1f ms "
            "pause, %d lanes drained, %d evicted at the drain bound)",
            self.model_version, outcome, 1e3 * pause,
            drain.get("drained", 0), drain.get("evicted", 0))
        return {"version": self.model_version, "outcome": outcome,
                "pause_ms": round(1e3 * pause, 3),
                "weights_version": self.model.weights_version,
                **drain}

    def record_swap_outcome(self, outcome: str) -> None:
        self.swap_counts[outcome] = self.swap_counts.get(outcome, 0) + 1
        _metrics.swaps_total(self._obs_id, outcome).inc()
        _recorder.record("swap", engine=self._obs_id, outcome=outcome,
                         version=self.model_version)

    def set_model_version(self, version: int) -> None:
        """Label the CURRENTLY loaded bundle's published version."""
        self.model_version = int(version)
        self._m_version.set(self.model_version)

    def swap_pauses_ms(self) -> list[float]:
        return [1e3 * p for p in self._swap_pauses]

    def _maybe_apply_swap(self, force: bool = False) -> None:
        """Scheduler-thread half of the swap: once no old-model lane
        is live (or the drain deadline / shutdown forces it), evict
        stragglers with their tokens-so-far, flip the weight pytree,
        and resume admission."""
        req = self._swap_req
        if req is None:
            return
        now = time.monotonic()
        if self._live and not force and now < req["deadline"]:
            return  # still draining old-model generations
        evicted = 0
        for s in self._live:  # drain bound hit: return tokens-so-far
            self._finish(s)
            evicted += 1
        self._live = []
        self._m_slots.set(0)
        try:
            self.model.swap_weights(req["params"],
                                    manifest=req["manifest"])
        except Exception as exc:  # noqa: BLE001 — report to the caller
            req["future"].set_exception(exc)
        else:
            req["future"].set_result({
                "drained": req.get("live0", 0) - evicted,
                "evicted": evicted,
                "drain_ms": round(1e3 * (now - req["t0"]), 3)})
        if self.prefix is not None:
            # cached K/V are functions of the OLD weights: every
            # shared prefix page is stale the instant the flip lands
            dropped = self.prefix.clear(self.model.cache,
                                        tier=self._spill)
            if dropped:
                self.info("prefix cache invalidated by weight swap "
                          "(%d cached blocks dropped)", dropped)
        with self._cond:
            # admission-eligible TTFT (round 15): the drain pause is
            # a swap-policy cost, not serving latency — queued
            # requests' TTFT/deadline clocks shift past it, and the
            # pause itself lands on its own canonical counter
            pause_end = time.monotonic()
            self._m_swap_pause.inc(max(0.0, pause_end - req["t0"]))
            for r in self._pending:
                paused = max(0.0, pause_end
                             - max(r.t_submit, req["t0"]))
                r.pause_s += paused
                if paused > 0.0:
                    r.trace.event("swap_pause", engine=self._obs_id,
                                  pause_ms=round(1e3 * paused, 3))
            self._swap_req = None
            self._cond.notify_all()

    # ------------------------------------------------------------------
    # breaker (under _cond)
    # ------------------------------------------------------------------
    def _transition(self, state: str) -> None:
        if state == self._state:
            return
        self.warning("decode breaker %s → %s", self._state, state)
        _recorder.record("breaker", engine=self._obs_id,
                         src=self._state, to=state)
        self._state = state
        if state == _OPEN:
            self._opened_at = time.monotonic()
        self._m_state.set(_STATE_CODE[state])
        _metrics.serving_breaker_transitions(self._obs_id, state).inc()

    def _breaker_tick(self, now: float) -> None:
        if self._state == _OPEN \
                and now - self._opened_at >= self.breaker_cooldown:
            self._transition(_HALF_OPEN)

    def _record_outcome(self, ok: bool) -> None:
        with self._cond:
            if self._state == _HALF_OPEN:
                self._transition(_CLOSED if ok else _OPEN)
                self._outcomes.clear()
                return
            self._outcomes.append(ok)
            n = len(self._outcomes)
            if n >= self.breaker_min_samples:
                rate = self._outcomes.count(False) / n
                if rate >= self.breaker_failure_rate \
                        and self._state != _OPEN:
                    self.warning("decode breaker tripped: failure "
                                 "rate %.0f%% over %d dispatches",
                                 100 * rate, n)
                    self._transition(_OPEN)
                    self._outcomes.clear()

    # ------------------------------------------------------------------
    # scheduler
    # ------------------------------------------------------------------
    def _sample(self, logits: np.ndarray) -> int:
        if self.temperature <= 0:
            return int(np.argmax(logits))
        z = logits / self.temperature
        z = z - z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(self._rng.choice(len(p), p=p))

    def _sweep_expired(self, now: float) -> None:
        """TTFT deadline: fail-fast queued prompts whose deadline
        passed — they never reach prefill or occupy a slot.  Call
        under ``_cond``.  Deadlines stamp from admission-ELIGIBLE
        time: while a swap drain pauses admission the clock is
        stopped (the pause lands on each queued request's ``pause_s``
        when the flip completes)."""
        if self._swap_req is not None:
            return  # admission paused: nobody's clock is running
        if not any(r.deadline is not None for r in self._pending):
            return
        for req in self._pending.sweep(lambda r: r.expired(now)):
            self.expired_total += 1
            _metrics.serving_requests(self._obs_id,
                                      "expired").inc()
            self._refund(req)
            req.trace.event("deadline_evicted", engine=self._obs_id)
            self._finish_trace(req, "expired")
            req.future.set_exception(DeadlineExceeded(
                f"TTFT deadline passed after "
                f"{(now - req.t_submit - req.pause_s) * 1e3:.0f}ms "
                f"admission-eligible in queue"))

    def _chaos(self) -> None:
        spike = _faults.fire("serving.latency_spike")
        if spike is not None:
            time.sleep(float(spike.get("ms", 50.0)) / 1e3)
        if _faults.fire("serving.program_error") is not None:
            raise _faults.FaultInjected(
                "injected decode program failure")

    def _dispatch(self, fn, *args):
        """Run one program dispatch under the retry budget + breaker
        accounting.  Retries re-run against unchanged cache state —
        legal only when buffers are NOT donated (the host keeps valid
        references); under donation a failed dispatch is terminal."""
        attempts = 0
        budget = 0 if self.model.donating else self.retry_budget
        while True:
            try:
                self._chaos()
                out = fn(*args)
            except Exception:
                self._record_outcome(False)
                if attempts >= budget:
                    raise
                attempts += 1
                self.retries_total += 1
                _metrics.serving_requests(self._obs_id,
                                          "retried").inc()
                continue
            self._record_outcome(True)
            if attempts:
                _metrics.recoveries("serving_retry").inc()
            return out

    # -- request-trace plumbing (round 24) ------------------------------
    def _end_phase(self, req: _PromptReq, phase: str, **args) -> float:
        """Close one trace phase and feed the engine's windowed-p99
        gauge for it from the SAME measurement."""
        dur = req.trace.phase_end(phase, engine=self._obs_id, **args)
        if dur > 0.0:
            win = self._phase_win.get(phase)
            if win is not None:
                win.append(dur)
        return dur

    def _finish_trace(self, req: _PromptReq, outcome: str) -> None:
        _metrics.trace_requests(self._obs_id, outcome).inc()
        req.trace.finish(outcome)

    def _release_lane(self, live: _Live) -> None:
        if self.model.paged:
            self.model.cache.release_slot_pages(live.slot)
        self.model.cache.release(live.slot)
        self._refund(live.req)

    def _finish(self, live: _Live) -> None:
        self._release_lane(live)
        self._m_served.inc()
        self._end_phase(live.req, "decode",
                        tokens=len(live.generated))
        self._finish_trace(live.req, "ok")
        if not live.req.future.done():
            live.req.future.set_result(
                np.asarray(live.generated, np.int32))

    def _fail_lane(self, live: _Live, exc: Exception) -> None:
        self._release_lane(live)
        self._finish_trace(live.req, "failed")
        if not live.req.future.done():
            live.req.future.set_exception(exc)

    def _admit_cleanup(self, req: _PromptReq, slot: int,
                       exc: Exception) -> None:
        if self.model.paged:
            self.model.cache.release_slot_pages(slot)
        self.model.cache.release(slot)
        self._refund(req)
        self.warning("prefill failed: %s", exc)
        self._finish_trace(req, "failed")
        if not req.future.done():
            req.future.set_exception(exc)

    def _post_prefill(self, req: _PromptReq, slot: int,
                      logits: np.ndarray) -> None:
        """Shared admission bookkeeping once a prompt's first logits
        exist: trie registration, TTFT (admission-eligible clock),
        first sample, live-lane creation."""
        if self.prefix is not None:
            self.prefix.insert(req.tokens,
                               self.model.cache.tables[slot],
                               self.model.cache)
        token = self._sample(logits)
        self._end_phase(req, "prefill", tokens=req.n)
        req.trace.phase_begin("decode")
        ttft = time.monotonic() - req.t_submit - req.pause_s
        # stamp TTFT onto the future: the fleet's per-tenant latency
        # observes generation requests at TTFT (the admission-bound
        # SLO — completion time is work-proportional, round-12 split)
        req.future.ttft_s = ttft
        self._m_ttft.observe(ttft)
        self._ttft_win.append(ttft)
        self._m_tok_prompt.inc(req.n)
        self._m_tok_gen.inc()
        live = _Live(req, slot, token)
        if (self.eos_token is not None and token == self.eos_token) \
                or req.max_new <= 1:
            self._finish(live)
            return
        self._live.append(live)
        self._m_slots.set(len(self._live))

    def _admit_prefilled(self, req: _PromptReq, slot: int,
                         matched: int) -> None:
        """Single-prompt prefill dispatch for a slot whose pages are
        already set up (``matched`` tokens ride shared pages)."""
        self._end_phase(req, "queue")
        req.trace.phase_begin("prefill")
        try:
            with _tracing.TRACER.span("prefill", cat="serving",
                                      tokens=req.n, shared=matched):
                logits = self._dispatch(self.model.run_prefill,
                                        req.tokens[matched:], slot,
                                        matched)
                if self.drafter is not None:
                    # the drafter tracks the FULL prompt through its
                    # own flat cache (it is tiny — sharing buys
                    # nothing there)
                    self._dispatch(self.drafter.run_prefill,
                                   req.tokens, slot)
        except Exception as exc:  # noqa: BLE001 — isolate the prompt
            self._admit_cleanup(req, slot, exc)
            return
        self._post_prefill(req, slot, logits)

    def _admit_window(self, group: list[tuple]) -> None:
        """Admission coalescing (round 15): a burst of prompts whose
        unshared tails fit one ``prompt_align`` window — the
        steady-state shape of prefix-hit system-prompt traffic — pays
        ONE batched window dispatch instead of one prefill each."""
        w_len = self.model.prompt_align
        n = len(group)
        windows = np.zeros((n, w_len), np.int32)
        slots = np.empty((n,), np.int32)
        starts = np.empty((n,), np.int32)
        lengths = np.empty((n,), np.int32)
        for i, (req, slot, matched) in enumerate(group):
            tail = req.tokens[matched:]
            windows[i, :len(tail)] = tail
            slots[i] = slot
            starts[i] = matched
            lengths[i] = len(tail)
        for req, _slot, _m in group:
            self._end_phase(req, "queue")
            req.trace.phase_begin("prefill")
        try:
            with _tracing.TRACER.span("prefill_window", cat="serving",
                                      lanes=n, w=w_len):
                logits = self._dispatch(
                    self.model.run_window, windows, slots, starts,
                    lengths, "serving-prefill")
                if self.drafter is not None:
                    for req, slot, _m in group:
                        self._dispatch(self.drafter.run_prefill,
                                       req.tokens, slot)
        except Exception as exc:  # noqa: BLE001 — isolate the wave
            for req, slot, _m in group:
                self._admit_cleanup(req, slot, exc)
            return
        for i, (req, slot, _m) in enumerate(group):
            self._post_prefill(req, slot,
                               logits[i, int(lengths[i]) - 1])

    def _admit_many(self, reqs: list[_PromptReq]) -> list[_PromptReq]:
        """Admit a wave of prompts; returns the suffix to requeue
        when the page pool cannot hold one (order preserved — nothing
        is dropped or reordered past the blocked head).

        Prompts are matched against the trie IN ORDER, and a prefix
        MISS dispatches (and registers its blocks) immediately — so
        the second system-prompt request of a burst already shares
        the first one's pages, within one admission wave.  The
        prefix-hit tails then coalesce into one batched window
        dispatch."""
        model = self.model
        window: list[tuple] = []
        requeue: list[_PromptReq] = []
        for i, req in enumerate(reqs):
            slot = model.cache.acquire()
            matched = 0
            if model.paged:
                try:
                    matched = self._setup_pages(slot, req.tokens,
                                                req.max_new)
                except PoolExhausted:
                    model.cache.release(slot)
                    requeue = list(reqs[i:])
                    break
                if self._pool_tripped:
                    # capacity is back: resume taking traffic NOW
                    with self._cond:
                        self._pool_tripped = False
                        if self._state == _OPEN:
                            self._transition(_CLOSED)
            # the batched window path needs the paged window program
            # family (compiled when the prefix cache is on) and a
            # tail that fits the prompt_align window
            if (self.prefix is not None
                    and not model.has_lstm
                    and 0 < req.n - matched <= model.prompt_align):
                window.append((req, slot, matched))
            else:
                self._admit_prefilled(req, slot, matched)
        if len(window) == 1:
            self._admit_prefilled(*window[0])
        elif window:
            self._admit_window(window)
        return requeue

    def _emit_tokens(self, s: _Live, tokens: list[int],
                     now: float) -> bool:
        """Append emitted tokens to a lane (speculative steps emit
        several per dispatch); returns True when the lane finished
        (EOS / budget / max-T)."""
        dt = (now - s.t_last) / max(1, len(tokens))
        done = False
        for tok in tokens:
            s.pos += 1
            s.generated.append(int(tok))
            self._m_token.observe(dt)
            self._token_win.append(dt)
            self._m_tok_gen.inc()
            if ((self.eos_token is not None
                 and int(tok) == self.eos_token)
                    or len(s.generated) >= s.req.max_new
                    or s.pos >= self.model.max_t):
                done = True
                break
        s.t_last = now
        return done

    def _step(self) -> None:
        """One continuous-batching token step over every live lane.
        No page bookkeeping here: admission reserved every block a
        real token can land in, so the hot loop is pure dispatch."""
        live = self._live
        if not live:
            return
        tokens = np.asarray([s.generated[-1] for s in live], np.int32)
        slots = np.asarray([s.slot for s in live], np.int32)
        positions = np.asarray([s.pos for s in live], np.int32)
        try:
            with _tracing.TRACER.span("decode_step", cat="serving",
                                      lanes=len(live)):
                logits = self._dispatch(self.model.run_decode,
                                        tokens, slots, positions)
        except Exception as exc:  # noqa: BLE001 — the step is shared
            self.warning("decode step failed for %d lanes: %s",
                         len(live), exc)
            for s in live:
                self._fail_lane(s, exc)
            self._live = []
            self._m_slots.set(0)
            return
        now = time.monotonic()
        still: list[_Live] = []
        for i, s in enumerate(live):
            token = self._sample(logits[i])
            if self._emit_tokens(s, [token], now):
                self._finish(s)
            else:
                still.append(s)
        self._live = still
        self._m_slots.set(len(still))

    # ------------------------------------------------------------------
    # speculative decoding (round 15): draft k with the population
    # drafter, verify the window in ONE batched big-model forward
    # ------------------------------------------------------------------
    def _softmax(self, logits: np.ndarray) -> np.ndarray:
        z = logits / max(self.temperature, 1e-9)
        z = z - z.max(axis=-1, keepdims=True)
        p = np.exp(z)
        return p / p.sum(axis=-1, keepdims=True)

    def _accept_lane(self, vlogits: np.ndarray, drafts: np.ndarray,
                     qrow: np.ndarray | None) -> tuple[list[int], int]:
        """Leviathan accept/reject for one lane.  ``vlogits``
        (k+1, V) verifier logits, ``drafts`` (k,) drafted ids,
        ``qrow`` (k, V) drafter probabilities (sampled mode only).
        Returns ``(emitted_tokens, accepted_draft_count)``.  Greedy:
        accept while the verifier's argmax equals the draft, emit the
        verifier's token at the first mismatch — byte-identical to
        non-speculative greedy by construction.  No bonus token is
        emitted on a full accept: the drafter never consumed the last
        draft, so the next round feeds it instead (state stays exact
        with zero catch-up dispatches)."""
        emitted: list[int] = []
        accepted = 0
        for i in range(self.spec_k):
            d = int(drafts[i])
            if qrow is None:  # greedy
                g = int(np.argmax(vlogits[i]))
                emitted.append(g)
                if g != d:
                    break
                accepted += 1
            else:  # temperature: exact rejection sampling
                p = self._softmax(vlogits[i])
                q = qrow[i]
                if self._rng.random() < min(
                        1.0, float(p[d]) / max(float(q[d]), 1e-12)):
                    emitted.append(d)
                    accepted += 1
                    continue
                resid = np.maximum(p - q, 0.0)
                total = resid.sum()
                probs = resid / total if total > 0 else p
                emitted.append(int(self._rng.choice(len(p), p=probs)))
                break
        return emitted, accepted

    def _step_spec(self) -> None:
        """One speculative step: k drafter tokens per lane, one
        batched verification forward, 1..k tokens emitted per lane."""
        k = self.spec_k
        # no page bookkeeping: admission reserved every block a REAL
        # token can land in; the verify window's overhang past the
        # reservation holds only discardable draft overflow, and the
        # table routes those writes to the trash page by construction
        live = self._live
        if not live:
            return
        n = len(live)
        slots = np.asarray([s.slot for s in live], np.int32)
        base_pos = np.asarray([s.pos for s in live], np.int32)
        cur = np.asarray([s.generated[-1] for s in live], np.int32)
        drafts = np.empty((n, k), np.int32)
        qprobs = (np.empty((n, k, self.model.vocab), np.float64)
                  if self.temperature > 0 else None)
        try:
            with _tracing.TRACER.span("spec_draft", cat="serving",
                                      lanes=n, k=k):
                for j in range(k):
                    dlogits = self._dispatch(self.drafter.run_decode,
                                             cur, slots, base_pos + j)
                    if qprobs is None:
                        nxt = np.argmax(dlogits, axis=1)
                    else:
                        q = self._softmax(dlogits)
                        qprobs[:, j] = q
                        nxt = np.asarray(
                            [self._rng.choice(q.shape[1], p=q[i])
                             for i in range(n)])
                    drafts[:, j] = nxt
                    cur = nxt.astype(np.int32)
            windows = np.concatenate(
                [np.asarray([[s.generated[-1]] for s in live],
                            np.int32), drafts], axis=1)
            with _tracing.TRACER.span("spec_verify", cat="serving",
                                      lanes=n, k=k):
                vlogits = self._dispatch(self.model.run_verify,
                                         windows, slots, base_pos)
        except Exception as exc:  # noqa: BLE001 — the step is shared
            self.warning("speculative step failed for %d lanes: %s",
                         n, exc)
            for s in live:
                self._fail_lane(s, exc)
            self._live = []
            self._m_slots.set(0)
            return
        now = time.monotonic()
        still: list[_Live] = []
        for i, s in enumerate(live):
            emitted, accepted = self._accept_lane(
                vlogits[i], drafts[i],
                None if qprobs is None else qprobs[i])
            self._m_spec_acc.inc(accepted)
            self._m_spec_rej.inc(k - accepted)
            if self._emit_tokens(s, emitted, now):
                self._finish(s)
            else:
                still.append(s)
        self._live = still
        self._m_slots.set(len(still))

    def _loop(self) -> None:
        while True:
            admit: list[_PromptReq] = []
            with self._cond:
                while (not self._pending and not self._live
                       and not self._stop and self._swap_req is None):
                    self._cond.wait(timeout=0.25)
                    self._sweep_expired(time.monotonic())
                if self._stop and not self._pending and not self._live:
                    # a swap still pending at shutdown applies now —
                    # its caller is blocked on the future
                    self._maybe_apply_swap(force=True)
                    return
                now = time.monotonic()
                self._sweep_expired(now)
                self._breaker_tick(now)
                # during a swap drain NOTHING is admitted: queued
                # prompts wait for the flip and prefill against the
                # NEW model — a slot freed by an old-model eviction
                # never admits a new-model prompt early
                may_admit = (self._swap_req is None
                             and (self.admission == "continuous"
                                  or not self._live))
                # bound by the free-slot count HERE — slots are only
                # acquired inside _admit, so the live count cannot
                # gate this loop
                free = self.model.cache.free_slots
                while (may_admit and self._pending
                       and len(admit) < free):
                    admit.append(self._pending.popleft())
            # admissions coalesce: prefix-hit tails share one batched
            # window dispatch; pool exhaustion returns the blocked
            # suffix in order — nothing is dropped silently
            requeue = self._admit_many(admit)
            if requeue:
                with self._cond:
                    self._pending.requeue_front(requeue)
                    if self._live or self._swap_req is not None:
                        # token-capacity overload: a young backlog
                        # just waits for draining lanes to release
                        # pages; a STALLED one (head older than
                        # max_queue_age) sheds new prompts through
                        # the breaker until capacity returns
                        blocked = self._pending.peek()
                        head_age = (time.monotonic()
                                    - blocked.t_submit
                                    - blocked.pause_s)
                        if self._state == _CLOSED \
                                and head_age > self.max_queue_age:
                            self.warning(
                                "page pool exhausted (%d/%d pages "
                                "free, head queued %.1fs): shedding "
                                "new prompts while %d lanes drain",
                                self.model.cache.free_pages,
                                self.model.cache.pool_pages, head_age,
                                len(self._live))
                            self._transition(_OPEN)
                            self._pool_tripped = True
                        head = None
                    else:
                        # no lane will ever free a page — the prompt
                        # cannot fit this pool, period
                        head = self._pending.popleft()
                if head is not None:
                    self._refund(head)
                    self._m_rejected.inc()
                    if not head.future.done():
                        head.future.set_exception(PoolExhausted(
                            f"prompt of {head.n} tokens cannot fit "
                            f"the {self.model.cache.pool_pages}-page "
                            f"pool even with every lane drained and "
                            f"the prefix cache evicted"))
            if self._live:
                if self.spec_k and all(
                        s.pos + self.spec_k < self.model.max_t
                        for s in self._live):
                    self._step_spec()
                else:
                    self._step()
            self._maybe_apply_swap()

    # ------------------------------------------------------------------
    # telemetry
    # ------------------------------------------------------------------
    def _queue_age(self) -> float:
        """Age of the oldest queued prompt (seconds) — the gauge's
        read callback.  Racy peek without the lock is fine: the
        scrape tolerates one-request staleness."""
        try:
            head = self._pending.peek()
        except RuntimeError:  # dict mutated mid-iteration
            return 0.0
        if head is None:
            return 0.0
        return max(0.0, time.monotonic() - head.t_submit
                   - head.pause_s)

    def stats(self) -> dict:
        from znicz_tpu.serving.engine import _percentile

        def window(win):
            vals = sorted(win)
            if not vals:
                return {}
            return {"p50": round(1e3 * _percentile(vals, 50), 3),
                    "p95": round(1e3 * _percentile(vals, 95), 3),
                    "p99": round(1e3 * _percentile(vals, 99), 3),
                    "mean": round(1e3 * sum(vals) / len(vals), 3),
                    "window": len(vals)}

        spec_acc = int(self._m_spec_acc.value)
        spec_rej = int(self._m_spec_rej.value)
        out = {
            "engine": ("decode-paged-aot" if self.model.paged
                       else "decode-bucketed-aot"),
            "admission": self.admission,
            "max_slots": self.model.max_slots,
            "max_t": self.model.max_t,
            "paged": self.model.paged,
            "page_tokens": (self.model.page_tokens
                            if self.model.paged else None),
            "pages": ({
                "total": self.model.cache.pool_pages,
                "used": self.model.cache.pages_used(),
                "pool_tokens": self.model.pool_tokens,
                "page_truncations": self.page_truncations,
            } if self.model.paged else None),
            "prefix_cache": ({
                "nodes": self.prefix.nodes,
                "hits": int(self._m_prefix_hit.value),
                "misses": int(self._m_prefix_miss.value),
                "shared_tokens": int(self._m_tok_shared.value),
                "computed_tokens": int(self._m_tok_computed.value),
                "spilled_nodes": self.prefix.spilled_nodes(),
                "spill_pages_used": (self._spill.used
                                     if self._spill else 0),
                "spill_capacity": (self._spill.capacity
                                   if self._spill else 0),
                "migrations": {
                    "spill": int(self._m_mig_spill.value),
                    "restore": int(self._m_mig_restore.value),
                },
            } if self.prefix is not None else None),
            "speculative": ({
                "draft_k": self.spec_k,
                "drafter": self.drafter.model.manifest.get(
                    "workflow", "?"),
                "accepted": spec_acc,
                "rejected": spec_rej,
                "accept_rate": round(
                    spec_acc / max(1, spec_acc + spec_rej), 3),
            } if self.spec_k else None),
            "prompt_buckets": self.model.prompt_ladder(),
            "batch_buckets": self.model.batch_ladder(),
            "block_buckets": self.model.block_ladder(),
            "programs_compiled": self.model.compile_count
            + (self.drafter.compile_count if self.drafter else 0),
            "programs_loaded": getattr(self.model, "load_count", 0)
            + (getattr(self.drafter, "load_count", 0)
               if self.drafter else 0),
            "programs_live": self.model.programs_live
            + (self.drafter.programs_live if self.drafter else 0),
            "warmup_seconds": round(self.warmup_seconds, 3),
            "cache_bytes": self.model.cache.nbytes(),
            "kv_bytes_per_lane": self.model.cache.nbytes()
            // max(1, self.model.max_slots),
            "quant": ({
                "weights": ("int8" if getattr(self.model.model,
                                              "_qkeys", None)
                            else "f32"),
                "kv_pages": ("int8" if self.model.kv_quant
                             else str(self.model.kv_dtype)),
            } if (self.model.kv_quant
                  or getattr(self.model.model, "_qkeys", None))
                else None),
            "submitted": int(self._m_submitted.value),
            "served": int(self._m_served.value),
            "rejected": int(self._m_rejected.value),
            "model_version": self.model_version,
            "weights_version": self.model.weights_version,
            "swaps": dict(self.swap_counts),
            "tokens_prompt": int(self._m_tok_prompt.value),
            "tokens_generated": int(self._m_tok_gen.value),
            "live_slots": len(self._live),
            "queued_prompts": len(self._pending),
            "ttft_ms": window(self._ttft_win),
            "token_ms": window(self._token_win),
            "resilience": {
                "breaker": self._state,
                "retry_budget": self.retry_budget,
                "retried": self.retries_total,
                "expired": self.expired_total,
                "shed": self.shed_total,
            },
            "token_budget": ({
                "capacity": self._token_budget.capacity,
                "used": self._token_budget.used,
                "over_released": self._token_budget.over_released,
            } if self._token_budget is not None else None),
        }
        from . import aot_cache as _aot
        out["aot_cache"] = _aot.status()
        return out

    @property
    def breaker_state(self) -> str:
        return self._state

    def ready(self) -> bool:
        return bool(self._started and self._state != _OPEN)

    def serving_status(self) -> dict:
        """``web_status.gather_status`` hook."""
        out = {"name": f"decode:{self.model.model.manifest.get('workflow', '?')}",
               "initialized": self._started,
               "stopped": not self._started}
        out.update(self.stats())
        return out
