"""Autoregressive decode serving: KV-cache + prefill/decode AOT split
+ continuous token batching.

The round-8 engine scores fixed-shape one-shot requests; this module
is the *generation* half of the serving story (ROADMAP item 2 — the
heaviest-traffic scenario a millions-of-users deployment runs).  It
converts any exported causal LM bundle (``manifest["kind"] == "lm"``:
token-first chain of embedding / pos_encoding / causal attention /
LSTM, a position-independent head) into a continuous-batching token
server, built from three pieces:

1. **KV cache** (:class:`KVCache`) — per-replica device buffers
   preallocated at :meth:`DecodeModel.warmup`: one (S+1, maxT, H, Dh)
   K and V page array per attention layer and one (S+1, H) carry pair
   per LSTM layer, where S is ``max_slots`` sequence slots (+1 scratch
   row that absorbs padded decode lanes).  Pages are *functionally*
   updated by the decode program and donated back, so on
   donation-capable platforms a warmed decode loop mutates HBM in
   place and allocates nothing per token.

2. **Prefill / decode AOT split** (:class:`DecodeModel`) — two
   separate program families, both real ``jit().lower().compile()``
   AOT like the round-8 ladder:

   - *prefill*, bucketed on **prompt length** via the same
     ``serving/buckets.py`` ladder math applied to the T axis
     (``prompt_align·2^k``): runs the full causal forward over the
     padded prompt, writes every position's K/V (or the masked LSTM
     carry) into the request's slot, and returns the last real
     position's logits — the first token;
   - *decode*, bucketed on **live-batch size**: one token for every
     in-flight sequence per dispatch — embedding gather → positional
     offset add → per-layer cached step
     (``MultiHeadAttention.xla_decode_step`` /
     ``LSTM.xla_decode_step``) → head logits — with ragged per-lane
     position indices, so sequences at different depths share one
     program.

   Warmed, the token loop performs ZERO XLA compiles
   (``znicz_xla_compiles_total{site=serving-prefill|serving-decode}``
   stays flat — pinned by tests/test_retrace_guard.py).

3. **Continuous token batching** (:class:`DecodeEngine`) — the Orca
   iteration-level insight applied to generation: the scheduler
   admits queued prompts into the *in-flight* decode batch between
   token steps (``admission="continuous"``; ``"static"`` keeps the
   run-to-completion behavior as the measured A/B arm in
   serve_bench), and evicts slots the moment a sequence finishes
   (EOS, token budget, or the bucketed max-T page boundary) so a
   long straggler never holds the batch hostage.

Telemetry splits decode latency into its two canonical halves —
``znicz_serving_ttft_seconds`` (queue + prefill + first sample) and
``znicz_serving_token_seconds`` (steady-state cadence) — because the
two move independently: admission policy moves TTFT, cache residency
moves per-token.  Resilience (round 11 carried forward):
``deadline_ms`` applies to **TTFT** — a prompt still queued past its
deadline is evicted before prefill and never occupies a slot — and
the circuit breaker sheds *new prompts* with fast
:class:`Overloaded` replies while in-flight decodes drain to
completion.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from concurrent.futures import Future

import numpy as np

from znicz_tpu.observe import metrics as _metrics
from znicz_tpu.observe import tracing as _tracing
from znicz_tpu.resilience import faults as _faults
from znicz_tpu.serving.batcher import (_CLOSED, _HALF_OPEN, _OPEN,
                                       _STATE_CODE, DeadlineExceeded,
                                       Overloaded, QueueFull)
from znicz_tpu.serving.buckets import bucket_for, ladder, next_pow2
from znicz_tpu.utils.logger import Logger

__all__ = ["DecodeModel", "DecodeEngine", "KVCache"]

#: distinguishes same-named engines in the registry's labels
_DECODE_SEQ = itertools.count()

#: layer kinds the decode planner knows how to step incrementally
_SEQ_KINDS = ("embedding", "pos_encoding", "attention", "lstm")
_HEAD_KINDS = ("all2all", "all2all_tanh", "all2all_relu",
               "all2all_str", "all2all_sigmoid", "softmax")


class _Op:
    """One planned chain step: the unit (config carrier), the export
    KEYS of its weight leaves, and — for stateful layers — its cache
    array indices.  Weights themselves are NOT baked into the op: the
    traced programs take them as a call-time operand pytree, which is
    what lets :meth:`DecodeModel.swap_weights` replace them without a
    single recompile."""

    __slots__ = ("kind", "unit", "wkeys", "aux", "table")

    def __init__(self, kind, unit, wkeys=(), aux=None, table=None):
        self.kind = kind
        self.unit = unit
        self.wkeys = tuple(wkeys)  # export keys (layer<i>_<attr>)
        self.aux = aux or {}       # cache indices etc.
        self.table = table         # pos_encoding: baked (maxT, D) table


class KVCache:
    """The preallocated decode state for one replica: the page/carry
    arrays (functionally threaded through every program call) plus the
    host-side slot free list.

    Slot reuse needs no zeroing: prefill overwrites ``[0, t_bucket)``
    of a reused slot, and every attention step masks positions
    ``> pos``, so a prior tenant's rows beyond the new sequence's live
    prefix are unreachable by construction (pinned by
    tests/test_decode.py's eviction-reuse case).
    """

    def __init__(self, specs: list[tuple[str, tuple]], max_slots: int,
                 dtype=np.float32) -> None:
        import jax.numpy as jnp
        self.max_slots = int(max_slots)
        #: scratch row absorbing padded decode lanes (their scattered
        #: writes must land somewhere that is never a live sequence)
        self.trash_slot = self.max_slots
        self.specs = list(specs)
        self.arrays: tuple = tuple(
            jnp.zeros((self.max_slots + 1,) + tuple(shape), dtype)
            for _name, shape in specs)
        self._free = list(range(self.max_slots))

    @property
    def free_slots(self) -> int:
        return len(self._free)

    def acquire(self) -> int:
        return self._free.pop()

    def release(self, slot: int) -> None:
        self._free.append(slot)

    def nbytes(self) -> int:
        return int(sum(a.size * a.dtype.itemsize for a in self.arrays))


class DecodeModel(Logger):
    """Prefill/decode program families + KV cache over an exported LM.

    ``model`` is an :class:`~znicz_tpu.export.ExportedModel` (or a
    bundle path); its manifest must describe a causal LM
    (``kind == "lm"`` — legacy pre-round-12 bundles re-derive the
    kind from their layer table, so any previously exported LM
    decodes without re-export).

    Geometry knobs:

    - ``max_slots`` — concurrent sequences (KV pages preallocated);
    - ``max_t`` — cache page length, rounded up to a power of two
      (a sequence reaching it is force-finished);
    - ``max_prompt`` / ``prompt_align`` — the prompt-length ladder:
      prefill programs exist for ``prompt_align·2^k ≤ max_prompt``.
    """

    def __init__(self, model, *, max_slots: int = 4,
                 max_t: int = 64, max_prompt: int | None = None,
                 prompt_align: int = 8, device=None) -> None:
        super().__init__()
        from znicz_tpu.export import ExportedModel
        if isinstance(model, (str, bytes)) or hasattr(model,
                                                      "__fspath__"):
            model = ExportedModel.load(model, device=device)
        self.model = model
        if model.kind != "lm":
            raise ValueError(
                f"bundle '{model.manifest.get('workflow', '?')}' is a "
                f"'{model.kind}' — decode needs an LM (token-first "
                f"causal chain); re-export a generation model or use "
                f"ServingEngine for one-shot scoring")
        self.seq_meta = dict(model.sequence)
        self.vocab = int(self.seq_meta["vocab"])
        self.dim = int(self.seq_meta["dim"])
        self.max_slots = int(max_slots)
        if self.max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        self.max_t = next_pow2(int(max_t))
        self.prompt_align = int(prompt_align)
        self.max_prompt = int(max_prompt if max_prompt is not None
                              else min(self.max_t // 2,
                                       bucket_for(
                                           self.seq_meta["train_t"],
                                           self.prompt_align)))
        if self.max_prompt >= self.max_t:
            raise ValueError(
                f"max_prompt ({self.max_prompt}) must leave room to "
                f"generate below max_t ({self.max_t})")
        if bucket_for(self.max_prompt, self.prompt_align) > self.max_t:
            raise ValueError(
                f"prompt ladder top "
                f"{bucket_for(self.max_prompt, self.prompt_align)} "
                f"(max_prompt {self.max_prompt} rounded up to the "
                f"prompt_align·2^k ladder) exceeds the max_t "
                f"{self.max_t} cache page — raise max_t or lower "
                f"max_prompt")
        self.device = model.device
        self._plan, cache_specs = self._build_plan()
        self.cache = KVCache(cache_specs, self.max_slots)
        self._prefill_programs: dict[int, "callable"] = {}
        self._decode_programs: dict[int, "callable"] = {}
        self.compile_count = 0
        self.donating = model._donate_choice()
        # the published weight pytree: one immutable tuple-of-tuples
        # (one entry per plan op, None for absent leaves) every
        # prefill/decode dispatch reads exactly once — hot-swap
        # replaces the tuple between dispatches
        self._weights = self._gather_weights(self.model._params)
        self.weights_version = 0

    # ------------------------------------------------------------------
    # chain planning
    # ------------------------------------------------------------------
    def _gather_weights(self, params: dict) -> tuple:
        """Build the weight operand pytree from a bundle's param dict
        (absent leaves — e.g. a bias the export never carried — stay
        ``None``, a legal empty pytree node)."""
        import jax.numpy as jnp
        out = []
        for op in self._plan:
            out.append(tuple(
                None if key not in params
                else jnp.asarray(params[key], jnp.float32)
                for key in op.wkeys))
        return tuple(out)

    def _build_plan(self) -> tuple[list[_Op], list]:
        """Walk the manifest layers into decode ops + cache specs.

        Chain grammar: a *sequence* phase (embedding first, then
        pos_encoding / causal attention / LSTM), a bridge to
        position-independence (``last_token``, or a final
        ``return_sequence=False`` LSTM), then a *head* phase of
        per-sample FC layers ending in the vocabulary softmax."""
        units = self.model.forwards
        layers = self.model.manifest["layers"]
        plan: list[_Op] = []
        cache_specs: list[tuple[str, tuple]] = []
        phase = "seq"
        d = self.dim
        if not layers or layers[0]["type"] != "embedding":
            raise ValueError("decode chain must start with an "
                             "embedding layer (token-first)")
        for i, (spec, unit) in enumerate(zip(layers, units)):
            kind = spec["type"]
            if phase == "head" and kind not in _HEAD_KINDS:
                raise ValueError(
                    f"layer {i} ({kind}) after the sequence→sample "
                    f"bridge — only head layers {_HEAD_KINDS} may "
                    f"follow")
            if kind == "embedding":
                plan.append(_Op(kind, unit, (f"layer{i}_weights",)))
            elif kind == "pos_encoding":
                import jax.numpy as jnp
                table = jnp.asarray(
                    unit.table_to(self.max_t, d), jnp.float32)
                plan.append(_Op(kind, unit, table=table))
            elif kind == "attention":
                if not spec.get("config", {}).get("causal"):
                    raise ValueError(
                        f"layer {i}: attention must be causal=True to "
                        f"decode (a bidirectional layer has no valid "
                        f"incremental step)")
                heads = unit.n_heads
                dh = d // heads
                k_idx = len(cache_specs)
                cache_specs.append(
                    (f"l{i}.k", (self.max_t, heads, dh)))
                cache_specs.append(
                    (f"l{i}.v", (self.max_t, heads, dh)))
                plan.append(_Op(kind, unit, (
                    f"layer{i}_weights", f"layer{i}_bias",
                    f"layer{i}_weights_out", f"layer{i}_bias_out"),
                    aux={"k": k_idx, "v": k_idx + 1}))
            elif kind == "lstm":
                h_idx = len(cache_specs)
                cache_specs.append((f"l{i}.h", (unit.units,)))
                cache_specs.append((f"l{i}.c", (unit.units,)))
                plan.append(_Op(kind, unit, (
                    f"layer{i}_weights", f"layer{i}_bias"),
                    aux={"h": h_idx, "c": h_idx + 1}))
                d = unit.units
                if not unit.return_sequence:
                    phase = "head"  # the carry IS the sample bridge
            elif kind == "last_token":
                plan.append(_Op(kind, unit))
                phase = "head"
            elif kind in _HEAD_KINDS:
                if phase != "head":
                    raise ValueError(
                        f"layer {i} ({kind}) inside the sequence "
                        f"phase — FC layers flatten the time axis "
                        f"and cannot decode; bridge with last_token "
                        f"first")
                plan.append(_Op(kind, unit, (
                    f"layer{i}_weights", f"layer{i}_bias")))
            else:
                raise ValueError(
                    f"layer {i} ({kind}): no incremental decode step "
                    f"(supported: {_SEQ_KINDS + _HEAD_KINDS} + "
                    f"last_token)")
        if phase != "head":
            raise ValueError("chain never bridges to per-sample "
                             "features (last_token or a final "
                             "return_sequence=False LSTM)")
        if layers[-1]["type"] != "softmax":
            raise ValueError("decode chain must end in the softmax "
                             "vocabulary head")
        if not cache_specs:
            raise ValueError("stateless chain — nothing to cache, "
                             "nothing to decode")
        return plan, cache_specs

    # ------------------------------------------------------------------
    # traced bodies
    # ------------------------------------------------------------------
    def _head(self, op: _Op, w, x, final: bool):
        """One head layer on (B, D) features; the final softmax layer
        returns raw logits (softmax is monotone — greedy unchanged,
        and sampling normalizes on the host)."""
        import jax.numpy as jnp
        weights, b = w
        if final:
            return op.unit._logits(jnp, x, weights, b)
        return op.unit._forward(jnp, x, weights, b)

    def _prefill_fn(self, t_bucket: int):
        """The traced prefill body for one prompt-length bucket.
        ``weights`` is the per-op operand pytree — an argument, not a
        baked constant, so a hot-swap never invalidates the program."""
        import jax
        import jax.numpy as jnp
        plan = self._plan

        def fn(caches, weights, tokens, slot, length):
            # tokens (1, t_bucket) int32; slot, length () int32
            caches = list(caches)
            feat = None
            logits = None
            for j, op in enumerate(plan):
                w = weights[j]
                if op.kind == "embedding":
                    feat = op.unit.xla_embed(w[0], tokens)
                elif op.kind == "pos_encoding":
                    feat = (feat.astype(jnp.float32)
                            + op.table[:t_bucket][None])
                elif op.kind == "attention":
                    feat, k, v = op.unit.xla_prefill(feat, *w)
                    zero = jnp.int32(0)
                    caches[op.aux["k"]] = jax.lax.dynamic_update_slice(
                        caches[op.aux["k"]], k, (slot, zero, zero, zero))
                    caches[op.aux["v"]] = jax.lax.dynamic_update_slice(
                        caches[op.aux["v"]], v, (slot, zero, zero, zero))
                elif op.kind == "lstm":
                    feat, h, c = op.unit.xla_prefill(
                        feat, *w, length=jnp.reshape(length, (1,)))
                    caches[op.aux["h"]] = \
                        caches[op.aux["h"]].at[slot].set(h[0])
                    caches[op.aux["c"]] = \
                        caches[op.aux["c"]].at[slot].set(c[0])
                elif op.kind == "last_token":
                    # the last REAL position, not the padded tail
                    feat = jax.lax.dynamic_index_in_dim(
                        feat, length - 1, axis=1, keepdims=False)
                else:  # head layer
                    logits = self._head(op, w, feat, op is plan[-1])
                    feat = logits
            return tuple(caches), logits
        return fn

    def _decode_fn(self, b_bucket: int):
        """The traced single-token body for one live-batch bucket."""
        plan = self._plan

        def fn(caches, weights, tokens, slots, positions):
            # tokens/slots/positions: (b_bucket,) int32
            import jax.numpy as jnp
            caches = list(caches)
            rows = jnp.arange(b_bucket)
            feat = None
            logits = None
            for j, op in enumerate(plan):
                w = weights[j]
                if op.kind == "embedding":
                    feat = op.unit.xla_embed(w[0], tokens)[:, None, :]
                elif op.kind == "pos_encoding":
                    feat = op.unit.xla_decode_step(feat, positions,
                                                   op.table)
                elif op.kind == "attention":
                    k_rows = caches[op.aux["k"]][slots]
                    v_rows = caches[op.aux["v"]][slots]
                    feat, k_rows, v_rows = op.unit.xla_decode_step(
                        feat, k_rows, v_rows, positions, *w)
                    # only position `pos` changed per lane: scatter the
                    # new row back, padded lanes land in the scratch
                    # slot (duplicate-index writes there are garbage
                    # by design)
                    caches[op.aux["k"]] = caches[op.aux["k"]].at[
                        slots, positions].set(k_rows[rows, positions])
                    caches[op.aux["v"]] = caches[op.aux["v"]].at[
                        slots, positions].set(v_rows[rows, positions])
                elif op.kind == "lstm":
                    h = caches[op.aux["h"]][slots]
                    c = caches[op.aux["c"]][slots]
                    feat, h, c = op.unit.xla_decode_step(
                        feat, h, c, *w)
                    caches[op.aux["h"]] = \
                        caches[op.aux["h"]].at[slots].set(h)
                    caches[op.aux["c"]] = \
                        caches[op.aux["c"]].at[slots].set(c)
                    if op.unit.return_sequence:
                        feat = feat[:, None, :]
                elif op.kind == "last_token":
                    feat = feat[:, 0]
                else:
                    if feat.ndim == 3:  # head after a seq-phase bridge
                        feat = feat[:, 0]
                    logits = self._head(op, w, feat, op is plan[-1])
                    feat = logits
            return tuple(caches), logits
        return fn

    # ------------------------------------------------------------------
    # AOT compilation
    # ------------------------------------------------------------------
    def _compile(self, fn, in_structs: tuple, site: str):
        import jax
        donate = (0,) if self.donating else ()
        with _tracing.TRACER.span(f"aot_compile:{site}",
                                  cat="compile"):
            compiled = jax.jit(fn, donate_argnums=donate).lower(
                *in_structs).compile()
        _metrics.xla_compiles(site).inc()
        self.compile_count += 1
        return compiled

    def _cache_structs(self) -> tuple:
        import jax
        return tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                     for a in self.cache.arrays)

    def _weight_structs(self) -> tuple:
        import jax
        return tuple(tuple(
            None if a is None
            else jax.ShapeDtypeStruct(a.shape, a.dtype,
                                      sharding=getattr(a, "sharding",
                                                       None))
            for a in ws) for ws in self._weights)

    def prefill_program(self, t_bucket: int):
        """The AOT prefill program for one prompt-length bucket
        (compiled on first use; :meth:`warmup` front-loads the whole
        ladder)."""
        prog = self._prefill_programs.get(t_bucket)
        if prog is None:
            import jax
            i32 = np.dtype(np.int32)
            prog = self._compile(
                self._prefill_fn(t_bucket),
                (self._cache_structs(), self._weight_structs(),
                 jax.ShapeDtypeStruct((1, t_bucket), i32),
                 jax.ShapeDtypeStruct((), i32),
                 jax.ShapeDtypeStruct((), i32)),
                "serving-prefill")
            self._prefill_programs[t_bucket] = prog
        return prog

    def decode_program(self, b_bucket: int):
        """The AOT single-token program for one live-batch bucket."""
        prog = self._decode_programs.get(b_bucket)
        if prog is None:
            import jax
            vec = jax.ShapeDtypeStruct((b_bucket,), np.dtype(np.int32))
            prog = self._compile(
                self._decode_fn(b_bucket),
                (self._cache_structs(), self._weight_structs(),
                 vec, vec, vec),
                "serving-decode")
            self._decode_programs[b_bucket] = prog
        return prog

    def prompt_ladder(self) -> list[int]:
        return ladder(self.max_prompt, self.prompt_align)

    def batch_ladder(self) -> list[int]:
        return ladder(self.max_slots)

    def warmup(self) -> int:
        """Compile BOTH program families up front — after this, a
        decode loop at any live-batch size over any legal prompt mix
        performs zero compiles.  Returns programs compiled."""
        before = self.compile_count
        for t_b in self.prompt_ladder():
            self.prefill_program(t_b)
        for b_b in self.batch_ladder():
            self.decode_program(b_b)
        return self.compile_count - before

    @property
    def programs_live(self) -> int:
        return len(self._prefill_programs) + len(self._decode_programs)

    # ------------------------------------------------------------------
    # dispatch (scheduler thread only — no locking needed on cache)
    # ------------------------------------------------------------------
    def run_prefill(self, tokens: np.ndarray, slot: int
                    ) -> np.ndarray:
        """Prefill one prompt into ``slot``; returns the last real
        position's logits (V,)."""
        n = int(tokens.shape[0])
        if n > self.max_prompt:
            raise ValueError(f"prompt of {n} tokens exceeds "
                             f"max_prompt {self.max_prompt}")
        t_b = bucket_for(n, self.prompt_align)
        padded = np.zeros((1, t_b), np.int32)
        padded[0, :n] = tokens
        prog = self.prefill_program(t_b)
        caches, logits = prog(self.cache.arrays, self._weights, padded,
                              np.asarray(slot, np.int32),
                              np.asarray(n, np.int32))
        self.cache.arrays = caches
        return np.asarray(logits, np.float32)[0]

    def run_decode(self, tokens: np.ndarray, slots: np.ndarray,
                   positions: np.ndarray) -> np.ndarray:
        """One token step for ``len(tokens)`` live lanes; pads to the
        covering live-batch bucket (padded lanes ride the scratch
        slot).  Returns logits (n_live, V)."""
        n = int(tokens.shape[0])
        b_b = bucket_for(n)
        pad = b_b - n

        def padded(arr, fill):
            out = np.full((b_b,), fill, np.int32)
            out[:n] = arr
            return out

        prog = self.decode_program(b_b)
        caches, logits = prog(
            self.cache.arrays, self._weights, padded(tokens, 0),
            padded(slots, self.cache.trash_slot), padded(positions, 0))
        self.cache.arrays = caches
        return np.asarray(logits, np.float32)[:n]

    # ------------------------------------------------------------------
    # weight hot-swap (round 13)
    # ------------------------------------------------------------------
    def check_compatible(self, manifest: dict | None,
                         params: dict) -> None:
        """Validate a candidate against the planned chain; raises
        :class:`~znicz_tpu.export.SwapIncompatible` with the incumbent
        untouched on any mismatch."""
        from znicz_tpu.export import SwapIncompatible
        if manifest is not None:
            mine = [layer["type"] for layer
                    in self.model.manifest["layers"]]
            theirs = [layer["type"] for layer
                      in manifest.get("layers", [])]
            if mine != theirs:
                raise SwapIncompatible(
                    f"candidate layer table {theirs} != decode chain "
                    f"{mine}")
        for op, ws in zip(self._plan, self._weights):
            for key, cur in zip(op.wkeys, ws):
                new = params.get(key)
                if cur is None:
                    if new is not None:
                        raise SwapIncompatible(
                            f"{key}: candidate carries a parameter "
                            f"the compiled programs have no operand "
                            f"for")
                    continue
                if new is None:
                    raise SwapIncompatible(
                        f"candidate is missing parameter '{key}'")
                if tuple(np.shape(new)) != tuple(cur.shape):
                    raise SwapIncompatible(
                        f"{key}: candidate shape "
                        f"{tuple(np.shape(new))} != compiled "
                        f"{tuple(cur.shape)}")

    def swap_weights(self, params: dict,
                     manifest: dict | None = None) -> int:
        """Replace the weight operand pytree without recompiling:
        validate → stage (device_put onto each leaf's existing
        placement, fenced) → publish the new immutable tuple in one
        assignment.  The caller (:meth:`DecodeEngine.swap_weights`)
        guarantees no decode step is mid-flight when the flip lands —
        slots carrying old-model generations drain first."""
        import jax
        self.check_compatible(manifest, params)
        staged = []
        for op, ws in zip(self._plan, self._weights):
            new_ws = []
            for key, cur in zip(op.wkeys, ws):
                if cur is None:
                    new_ws.append(None)
                    continue
                new = np.asarray(params[key], np.float32)
                sharding = getattr(cur, "sharding", None)
                arr = (jax.device_put(new, sharding)
                       if sharding is not None else jax.device_put(new))
                new_ws.append(arr)
                self.model._params[key] = new
            staged.append(tuple(new_ws))
        for ws in staged:  # fence before publishing
            for a in ws:
                if a is not None:
                    a.block_until_ready()
        self._weights = tuple(staged)
        self.weights_version += 1
        return self.weights_version


class _PromptReq:
    """One queued generation request."""

    __slots__ = ("tokens", "n", "max_new", "future", "t_submit",
                 "deadline")

    def __init__(self, tokens: np.ndarray, max_new: int,
                 deadline_ms: float | None) -> None:
        self.tokens = tokens
        self.n = int(tokens.shape[0])
        self.max_new = int(max_new)
        self.future: Future = Future()
        self.t_submit = time.monotonic()
        self.deadline = (None if deadline_ms is None
                         else self.t_submit + float(deadline_ms) / 1e3)

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now >= self.deadline


class _Live:
    """Host-side state of one sequence mid-generation."""

    __slots__ = ("req", "slot", "pos", "generated", "t_last")

    def __init__(self, req: _PromptReq, slot: int, first_token: int
                 ) -> None:
        self.req = req
        self.slot = slot
        #: position the NEXT input token will occupy (= prompt length
        #: right after prefill; the sampled token is fed back there)
        self.pos = req.n
        self.generated = [int(first_token)]
        self.t_last = time.monotonic()


class DecodeEngine(Logger):
    """Continuous-batching token server over a :class:`DecodeModel`.

    Lifecycle mirrors :class:`~znicz_tpu.serving.ServingEngine`::

        with DecodeEngine("lm.npz", max_slots=4, max_t=64) as eng:
            tokens = eng.generate(prompt)            # sync
            future = eng.submit(prompt)              # async
            tokens = future.result()                 # np.int32 ids

    ``temperature=0`` (default) decodes greedily — byte-for-byte
    reproducible against the numpy oracle; ``temperature>0`` samples
    from the softmax on the host with a seeded generator (the logits
    cross anyway: sampling adds no device work).

    Scheduling: ``admission="continuous"`` (default) admits queued
    prompts into the in-flight batch between token steps; ``"static"``
    admits only when the previous batch fully drained —
    run-to-completion, the serve_bench A/B baseline.

    Degradation: ``deadline_ms`` bounds **TTFT** (a prompt still
    queued past it fails fast with :class:`DeadlineExceeded` and never
    occupies a slot); the circuit breaker watches dispatch outcomes
    and, while open, sheds NEW prompts with :class:`Overloaded` while
    in-flight sequences keep decoding to completion (the drain
    contract — generation in progress is the last thing to abandon).
    """

    def __init__(self, model, *, max_slots: int = 4, max_t: int = 64,
                 max_prompt: int | None = None, prompt_align: int = 8,
                 max_new_tokens: int = 32,
                 eos_token: int | None = None,
                 temperature: float = 0.0, seed: int = 0,
                 max_queue: int = 256,
                 admission: str = "continuous",
                 retry_budget: int = 1,
                 breaker_failure_rate: float = 0.5,
                 breaker_window: int = 8,
                 breaker_min_samples: int = 4,
                 breaker_cooldown_ms: float = 1000.0,
                 device=None) -> None:
        super().__init__()
        if not isinstance(model, DecodeModel):
            model = DecodeModel(model, max_slots=max_slots,
                                max_t=max_t, max_prompt=max_prompt,
                                prompt_align=prompt_align,
                                device=device)
        self.model = model
        if admission not in ("continuous", "static"):
            raise ValueError(f"admission must be 'continuous' or "
                             f"'static', got {admission!r}")
        self.admission = admission
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token = eos_token
        self.temperature = float(temperature)
        self.max_queue = int(max_queue)
        self.retry_budget = max(0, int(retry_budget))
        self.breaker_failure_rate = float(breaker_failure_rate)
        self.breaker_min_samples = int(breaker_min_samples)
        self.breaker_cooldown = float(breaker_cooldown_ms) / 1e3
        self._rng = np.random.default_rng(seed)
        # telemetry: per-engine children of the canonical series
        wf_name = model.model.manifest.get("workflow", "model")
        self._obs_id = f"{wf_name}#decode{next(_DECODE_SEQ)}"
        self._m_submitted = _metrics.serving_requests(
            self._obs_id, "submitted")
        self._m_served = _metrics.serving_requests(self._obs_id,
                                                   "served")
        self._m_rejected = _metrics.serving_requests(self._obs_id,
                                                     "rejected")
        self._m_ttft = _metrics.serving_ttft_seconds(self._obs_id)
        self._m_token = _metrics.serving_token_seconds(self._obs_id)
        self._m_tok_prompt = _metrics.serving_tokens(self._obs_id,
                                                     "prompt")
        self._m_tok_gen = _metrics.serving_tokens(self._obs_id,
                                                  "generated")
        self._m_slots = _metrics.serving_decode_slots(self._obs_id)
        self._m_state = _metrics.serving_breaker_state(self._obs_id)
        self._m_state.set(_STATE_CODE[_CLOSED])
        # exact-value windows for dashboard percentiles
        self._ttft_win: deque = deque(maxlen=4096)
        self._token_win: deque = deque(maxlen=4096)
        self._pending: deque[_PromptReq] = deque()
        self._live: list[_Live] = []
        self._cond = threading.Condition()
        self._stop = False
        self._state = _CLOSED
        self._opened_at = 0.0
        self._outcomes: deque[bool] = deque(maxlen=int(breaker_window))
        self.expired_total = 0
        self.shed_total = 0
        self.retries_total = 0
        self.warmup_compiles = 0
        self.warmup_seconds = 0.0
        self._thread: threading.Thread | None = None
        self._started = False
        # hot-swap bookkeeping (round 13): a pending swap request the
        # scheduler applies between token steps once old-model lanes
        # drained (or the engine.swap_drain_ms bound expires)
        self._swap_req: dict | None = None
        self.model_version = 0
        self._m_version = _metrics.model_version(self._obs_id)
        self._m_version.set(0)
        self._m_swap_dur = _metrics.swap_duration_seconds(self._obs_id)
        self.swap_counts = {"promoted": 0, "rejected": 0,
                            "rolled_back": 0}
        self._swap_pauses: list[float] = []

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> "DecodeEngine":
        if self._started:
            return self
        t0 = time.monotonic()
        self.warmup_compiles = self.model.warmup()
        self.warmup_seconds = time.monotonic() - t0
        self._thread = threading.Thread(target=self._loop,
                                        name="decode-scheduler",
                                        daemon=True)
        self._started = True
        self._thread.start()
        self.info(
            "decode '%s': %d AOT programs warmed in %.2fs (prompt "
            "buckets %s, batch buckets %s, slots=%d, max_t=%d, "
            "cache=%.1f MB, donate=%s)",
            self.model.model.manifest.get("workflow", "?"),
            self.warmup_compiles, self.warmup_seconds,
            self.model.prompt_ladder(), self.model.batch_ladder(),
            self.model.max_slots, self.model.max_t,
            self.model.cache.nbytes() / 1e6, self.model.donating)
        return self

    def shutdown(self, timeout: float = 60.0) -> None:
        """Drain: everything admitted keeps generating to completion,
        queued prompts are served, then the scheduler exits."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        self._started = False
        # a stopped engine is not shedding: clear the breaker so the
        # process-level /readyz (which scans EVERY engine child of the
        # breaker gauge) doesn't stay not-ready on a dead engine's
        # last state
        with self._cond:
            self._state = _CLOSED
            self._outcomes.clear()
            self._m_state.set(_STATE_CODE[_CLOSED])

    def __enter__(self) -> "DecodeEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # ------------------------------------------------------------------
    # request path
    # ------------------------------------------------------------------
    def submit(self, prompt, *, max_new_tokens: int | None = None,
               deadline_ms: float | None = None) -> Future:
        """Enqueue a prompt (1-D array of token ids); returns a future
        of the generated ids (np.int32, the first sampled token
        onward).  Raises :class:`QueueFull` under backpressure,
        :class:`Overloaded` while the breaker sheds, and the future
        fails with :class:`DeadlineExceeded` if ``deadline_ms`` passes
        before the first token (TTFT deadline)."""
        if not self._started:
            raise RuntimeError("engine not started — call start()")
        prompt = np.asarray(np.round(np.asarray(prompt, np.float64)),
                            np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if prompt.size > self.model.max_prompt:
            raise ValueError(
                f"prompt of {prompt.size} tokens exceeds max_prompt "
                f"{self.model.max_prompt} — truncate client-side")
        if deadline_ms is not None and deadline_ms <= 0:
            raise DeadlineExceeded(
                f"deadline_ms={deadline_ms} already expired at submit")
        req = _PromptReq(prompt,
                         max_new_tokens or self.max_new_tokens,
                         deadline_ms)
        with self._cond:
            if self._stop:
                raise RuntimeError("engine is shut down")
            self._breaker_tick(time.monotonic())
            if self._state == _OPEN:
                self.shed_total += 1
                _metrics.serving_requests(self._obs_id, "shed").inc()
                self._m_rejected.inc()
                raise Overloaded(
                    "circuit breaker open — new prompts shed while "
                    "in-flight decodes drain (retry after "
                    f"{self.breaker_cooldown * 1e3:.0f}ms)")
            if len(self._pending) >= self.max_queue:
                self._m_rejected.inc()
                raise QueueFull(
                    f"decode queue full ({len(self._pending)} prompts "
                    f"pending, limit {self.max_queue})")
            self._pending.append(req)
            self._cond.notify_all()
        self._m_submitted.inc()
        return req.future

    def generate(self, prompt, timeout: float | None = None,
                 **kwargs) -> np.ndarray:
        """Synchronous convenience: submit + wait."""
        return self.submit(prompt, **kwargs).result(timeout=timeout)

    # ------------------------------------------------------------------
    # weight hot-swap (round 13)
    # ------------------------------------------------------------------
    def current_bundle(self) -> tuple:
        """The live ``(manifest, params)`` — the rollback target a
        SwapController snapshots before promoting."""
        return (self.model.model.manifest,
                dict(self.model.model._params))

    def swap_weights(self, state, *, version: int | None = None,
                     drain_ms: float | None = None,
                     timeout: float | None = None,
                     outcome: str = "promoted") -> dict:
        """Hot-swap the decode weights without recompiling.

        In-flight generations belong to the OLD model: the scheduler
        stops admitting new prompts, lets live KV-cache slots decode
        to completion, and only then publishes the new weight pytree —
        so no sequence ever mixes two models' logits.  Lanes still
        live after ``drain_ms`` (default ``engine.swap_drain_ms``) are
        evicted with their tokens-so-far rather than holding the swap
        hostage.  Queued prompts are admitted AFTER the flip and
        prefill against the new model.

        Raises :class:`~znicz_tpu.export.SwapIncompatible` (validated
        before any drain starts — the incumbent keeps serving)."""
        from znicz_tpu.serving.engine import resolve_swap_state
        from znicz_tpu.utils.config import root
        manifest, params = resolve_swap_state(state)
        # fail BEFORE draining anything: an incompatible candidate
        # must not pause admission for even a millisecond
        self.model.check_compatible(manifest, params)
        if drain_ms is None:
            drain_ms = float(root.common.engine.get(
                "swap_drain_ms", 2000.0))
        t0 = time.monotonic()
        if not self._started:
            self.model.swap_weights(params, manifest=manifest)
            drain = {"drained": 0, "evicted": 0, "drain_ms": 0.0}
        else:
            fut: Future = Future()
            with self._cond:
                if self._swap_req is not None:
                    raise RuntimeError(
                        "a weight swap is already in progress")
                self._swap_req = {
                    "manifest": manifest, "params": params,
                    "deadline": t0 + float(drain_ms) / 1e3,
                    "future": fut, "t0": t0,
                    "live0": len(self._live)}
                self._cond.notify_all()
            drain = fut.result(
                timeout if timeout is not None
                else max(60.0, float(drain_ms) / 1e3 + 60.0))
        pause = time.monotonic() - t0
        if version is None:
            version = self.model_version + 1
        self.model_version = int(version)
        self._m_version.set(self.model_version)
        self._m_swap_dur.observe(pause)
        self._swap_pauses.append(pause)
        self.record_swap_outcome(outcome)
        self.info(
            "decode weights hot-swapped → version %d (%s, %.1f ms "
            "pause, %d lanes drained, %d evicted at the drain bound)",
            self.model_version, outcome, 1e3 * pause,
            drain.get("drained", 0), drain.get("evicted", 0))
        return {"version": self.model_version, "outcome": outcome,
                "pause_ms": round(1e3 * pause, 3),
                "weights_version": self.model.weights_version,
                **drain}

    def record_swap_outcome(self, outcome: str) -> None:
        self.swap_counts[outcome] = self.swap_counts.get(outcome, 0) + 1
        _metrics.swaps_total(self._obs_id, outcome).inc()

    def set_model_version(self, version: int) -> None:
        """Label the CURRENTLY loaded bundle's published version."""
        self.model_version = int(version)
        self._m_version.set(self.model_version)

    def swap_pauses_ms(self) -> list[float]:
        return [1e3 * p for p in self._swap_pauses]

    def _maybe_apply_swap(self, force: bool = False) -> None:
        """Scheduler-thread half of the swap: once no old-model lane
        is live (or the drain deadline / shutdown forces it), evict
        stragglers with their tokens-so-far, flip the weight pytree,
        and resume admission."""
        req = self._swap_req
        if req is None:
            return
        now = time.monotonic()
        if self._live and not force and now < req["deadline"]:
            return  # still draining old-model generations
        evicted = 0
        for s in self._live:  # drain bound hit: return tokens-so-far
            self.model.cache.release(s.slot)
            self._m_served.inc()
            if not s.req.future.done():
                s.req.future.set_result(
                    np.asarray(s.generated, np.int32))
            evicted += 1
        self._live = []
        self._m_slots.set(0)
        try:
            self.model.swap_weights(req["params"],
                                    manifest=req["manifest"])
        except Exception as exc:  # noqa: BLE001 — report to the caller
            req["future"].set_exception(exc)
        else:
            req["future"].set_result({
                "drained": req.get("live0", 0) - evicted,
                "evicted": evicted,
                "drain_ms": round(1e3 * (now - req["t0"]), 3)})
        with self._cond:
            self._swap_req = None
            self._cond.notify_all()

    # ------------------------------------------------------------------
    # breaker (under _cond)
    # ------------------------------------------------------------------
    def _transition(self, state: str) -> None:
        if state == self._state:
            return
        self.warning("decode breaker %s → %s", self._state, state)
        self._state = state
        if state == _OPEN:
            self._opened_at = time.monotonic()
        self._m_state.set(_STATE_CODE[state])
        _metrics.serving_breaker_transitions(self._obs_id, state).inc()

    def _breaker_tick(self, now: float) -> None:
        if self._state == _OPEN \
                and now - self._opened_at >= self.breaker_cooldown:
            self._transition(_HALF_OPEN)

    def _record_outcome(self, ok: bool) -> None:
        with self._cond:
            if self._state == _HALF_OPEN:
                self._transition(_CLOSED if ok else _OPEN)
                self._outcomes.clear()
                return
            self._outcomes.append(ok)
            n = len(self._outcomes)
            if n >= self.breaker_min_samples:
                rate = self._outcomes.count(False) / n
                if rate >= self.breaker_failure_rate \
                        and self._state != _OPEN:
                    self.warning("decode breaker tripped: failure "
                                 "rate %.0f%% over %d dispatches",
                                 100 * rate, n)
                    self._transition(_OPEN)
                    self._outcomes.clear()

    # ------------------------------------------------------------------
    # scheduler
    # ------------------------------------------------------------------
    def _sample(self, logits: np.ndarray) -> int:
        if self.temperature <= 0:
            return int(np.argmax(logits))
        z = logits / self.temperature
        z = z - z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(self._rng.choice(len(p), p=p))

    def _sweep_expired(self, now: float) -> None:
        """TTFT deadline: fail-fast queued prompts whose deadline
        passed — they never reach prefill or occupy a slot.  Call
        under ``_cond``."""
        if not any(r.deadline is not None for r in self._pending):
            return
        keep: deque[_PromptReq] = deque()
        for req in self._pending:
            if req.expired(now):
                self.expired_total += 1
                _metrics.serving_requests(self._obs_id,
                                          "expired").inc()
                req.future.set_exception(DeadlineExceeded(
                    f"TTFT deadline passed after "
                    f"{(now - req.t_submit) * 1e3:.0f}ms in queue"))
            else:
                keep.append(req)
        self._pending = keep

    def _chaos(self) -> None:
        spike = _faults.fire("serving.latency_spike")
        if spike is not None:
            time.sleep(float(spike.get("ms", 50.0)) / 1e3)
        if _faults.fire("serving.program_error") is not None:
            raise _faults.FaultInjected(
                "injected decode program failure")

    def _dispatch(self, fn, *args):
        """Run one program dispatch under the retry budget + breaker
        accounting.  Retries re-run against unchanged cache state —
        legal only when buffers are NOT donated (the host keeps valid
        references); under donation a failed dispatch is terminal."""
        attempts = 0
        budget = 0 if self.model.donating else self.retry_budget
        while True:
            try:
                self._chaos()
                out = fn(*args)
            except Exception:
                self._record_outcome(False)
                if attempts >= budget:
                    raise
                attempts += 1
                self.retries_total += 1
                _metrics.serving_requests(self._obs_id,
                                          "retried").inc()
                continue
            self._record_outcome(True)
            if attempts:
                _metrics.recoveries("serving_retry").inc()
            return out

    def _finish(self, live: _Live) -> None:
        self.model.cache.release(live.slot)
        self._m_served.inc()
        if not live.req.future.done():
            live.req.future.set_result(
                np.asarray(live.generated, np.int32))

    def _admit(self, req: _PromptReq) -> None:
        """Prefill one prompt into a free slot; samples (and times)
        the first token."""
        slot = self.model.cache.acquire()
        try:
            with _tracing.TRACER.span("prefill", cat="serving",
                                      tokens=req.n):
                logits = self._dispatch(self.model.run_prefill,
                                        req.tokens, slot)
        except Exception as exc:  # noqa: BLE001 — isolate the prompt
            self.model.cache.release(slot)
            self.warning("prefill failed: %s", exc)
            if not req.future.done():
                req.future.set_exception(exc)
            return
        token = self._sample(logits)
        ttft = time.monotonic() - req.t_submit
        self._m_ttft.observe(ttft)
        self._ttft_win.append(ttft)
        self._m_tok_prompt.inc(req.n)
        self._m_tok_gen.inc()
        live = _Live(req, slot, token)
        if (self.eos_token is not None and token == self.eos_token) \
                or req.max_new <= 1:
            self._finish(live)
            return
        self._live.append(live)
        self._m_slots.set(len(self._live))

    def _step(self) -> None:
        """One continuous-batching token step over every live lane."""
        live = self._live
        tokens = np.asarray([s.generated[-1] for s in live], np.int32)
        slots = np.asarray([s.slot for s in live], np.int32)
        positions = np.asarray([s.pos for s in live], np.int32)
        try:
            with _tracing.TRACER.span("decode_step", cat="serving",
                                      lanes=len(live)):
                logits = self._dispatch(self.model.run_decode,
                                        tokens, slots, positions)
        except Exception as exc:  # noqa: BLE001 — the step is shared
            self.warning("decode step failed for %d lanes: %s",
                         len(live), exc)
            for s in live:
                self.model.cache.release(s.slot)
                if not s.req.future.done():
                    s.req.future.set_exception(exc)
            self._live = []
            self._m_slots.set(0)
            return
        now = time.monotonic()
        still: list[_Live] = []
        for i, s in enumerate(live):
            token = self._sample(logits[i])
            s.pos += 1
            s.generated.append(token)
            self._m_token.observe(now - s.t_last)
            self._token_win.append(now - s.t_last)
            s.t_last = now
            self._m_tok_gen.inc()
            done = ((self.eos_token is not None
                     and token == self.eos_token)
                    or len(s.generated) >= s.req.max_new
                    # page boundary: the next input position would
                    # fall off the bucketed max-T cache
                    or s.pos >= self.model.max_t)
            if done:
                self._finish(s)
            else:
                still.append(s)
        self._live = still
        self._m_slots.set(len(still))

    def _loop(self) -> None:
        while True:
            admit: list[_PromptReq] = []
            with self._cond:
                while (not self._pending and not self._live
                       and not self._stop and self._swap_req is None):
                    self._cond.wait(timeout=0.25)
                    self._sweep_expired(time.monotonic())
                if self._stop and not self._pending and not self._live:
                    # a swap still pending at shutdown applies now —
                    # its caller is blocked on the future
                    self._maybe_apply_swap(force=True)
                    return
                now = time.monotonic()
                self._sweep_expired(now)
                self._breaker_tick(now)
                # during a swap drain NOTHING is admitted: queued
                # prompts wait for the flip and prefill against the
                # NEW model — a slot freed by an old-model eviction
                # never admits a new-model prompt early
                may_admit = (self._swap_req is None
                             and (self.admission == "continuous"
                                  or not self._live))
                # bound by the free-slot count HERE — slots are only
                # acquired inside _admit, so the live count cannot
                # gate this loop
                free = self.model.cache.free_slots
                while (may_admit and self._pending
                       and len(admit) < free):
                    admit.append(self._pending.popleft())
            for req in admit:
                self._admit(req)
            if self._live:
                self._step()
            self._maybe_apply_swap()

    # ------------------------------------------------------------------
    # telemetry
    # ------------------------------------------------------------------
    def stats(self) -> dict:
        from znicz_tpu.serving.engine import _percentile

        def window(win):
            vals = sorted(win)
            if not vals:
                return {}
            return {"p50": round(1e3 * _percentile(vals, 50), 3),
                    "p95": round(1e3 * _percentile(vals, 95), 3),
                    "p99": round(1e3 * _percentile(vals, 99), 3),
                    "mean": round(1e3 * sum(vals) / len(vals), 3),
                    "window": len(vals)}

        out = {
            "engine": "decode-bucketed-aot",
            "admission": self.admission,
            "max_slots": self.model.max_slots,
            "max_t": self.model.max_t,
            "prompt_buckets": self.model.prompt_ladder(),
            "batch_buckets": self.model.batch_ladder(),
            "programs_compiled": self.model.compile_count,
            "programs_live": self.model.programs_live,
            "warmup_seconds": round(self.warmup_seconds, 3),
            "cache_bytes": self.model.cache.nbytes(),
            "submitted": int(self._m_submitted.value),
            "served": int(self._m_served.value),
            "rejected": int(self._m_rejected.value),
            "model_version": self.model_version,
            "weights_version": self.model.weights_version,
            "swaps": dict(self.swap_counts),
            "tokens_prompt": int(self._m_tok_prompt.value),
            "tokens_generated": int(self._m_tok_gen.value),
            "live_slots": len(self._live),
            "queued_prompts": len(self._pending),
            "ttft_ms": window(self._ttft_win),
            "token_ms": window(self._token_win),
            "resilience": {
                "breaker": self._state,
                "retry_budget": self.retry_budget,
                "retried": self.retries_total,
                "expired": self.expired_total,
                "shed": self.shed_total,
            },
        }
        return out

    @property
    def breaker_state(self) -> str:
        return self._state

    def ready(self) -> bool:
        return bool(self._started and self._state != _OPEN)

    def serving_status(self) -> dict:
        """``web_status.gather_status`` hook."""
        out = {"name": f"decode:{self.model.model.manifest.get('workflow', '?')}",
               "initialized": self._started,
               "stopped": not self._started}
        out.update(self.stats())
        return out
