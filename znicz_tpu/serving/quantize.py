"""Post-training int8 weight quantization (round 21).

Weight-only, per-output-channel symmetric absmax scheme (Dettmers et
al., *LLM.int8()*, arXiv:2208.07339): for a 2-D weight ``W`` stored
``(in, out)`` — this repo's layout — each output channel ``o`` gets one
scale ``s[o] = max|W[:, o]| / 127`` and the stored tensor becomes
``q = round(W / s)`` in int8.  Dequantization ``q.astype(f32) * s`` is
exact arithmetic on representable values, so the in-program
dequantize-on-load path and the host-side dequantized numpy oracle are
bitwise identical — the repo's oracle discipline survives quantization
unchanged.

Only 2-D float arrays whose key contains ``weights`` are quantized
(dense/attention projections and embeddings); biases, conv kernels,
and norm gains stay f32 — they are a rounding error of the bundle
bytes and per-channel semantics are ill-defined for them.  The chosen
keys are stamped into the manifest as ``manifest["quant"]`` next to
the existing ``dtype`` record, so every consumer (:class:`~znicz_tpu.
export.ExportedModel`, the decode plane, the swap validator) discovers
quantization from the bundle alone.

Calibration rides the round-13 publish pipeline: the publisher's
canary/shadow stream supplies ``(x, y)`` and the numpy f32 oracle is
the accuracy gate — a quantization whose calibration accuracy regresses
past the swap guard margin is never published (the f32 bundle ships
instead).  The ``quant.calib_corrupt`` fault site corrupts the scales
AFTER the gate, modeling a calibration bug that slips publication: the
SwapController's canary must then reject the bundle downstream.
"""

from __future__ import annotations

import numpy as np

from znicz_tpu.resilience import faults as _faults

QUANT_DTYPE = "int8"
SCHEME = "symmetric-per-channel"

#: absmax floor — an all-zero channel quantizes to zeros with a scale
#: that never divides by zero
_EPS = 1e-12


def scale_key(key: str) -> str:
    """The params key carrying a quantized tensor's per-channel
    scales."""
    return f"{key}_scale"


def is_quantized(manifest: dict | None) -> dict | None:
    """The bundle's quant record (``{"dtype", "scheme", "weights"}``)
    or ``None`` for f32 bundles."""
    if not manifest:
        return None
    return manifest.get("quant") or None


def quantizable_keys(params: dict) -> list[str]:
    """Keys this scheme quantizes: 2-D float ``*weights*`` arrays —
    per-output-channel scales need a well-defined output axis (last,
    in the ``(in, out)`` layout).  Everything else ships f32."""
    out = []
    for key, arr in params.items():
        a = np.asarray(arr)
        if ("weights" in key and not key.endswith("_scale")
                and a.ndim == 2 and a.dtype.kind == "f"):
            out.append(key)
    return sorted(out)


def quantize_array(w) -> tuple[np.ndarray, np.ndarray]:
    """``(in, out)`` f32 → ``(q int8, scale f32 (out,))``."""
    w = np.asarray(w, dtype=np.float32)
    scale = np.maximum(np.abs(w).max(axis=0), _EPS) / 127.0
    q = np.clip(np.rint(w / scale), -127, 127).astype(np.int8)
    return q, scale.astype(np.float32)


def dequantize_array(q, scale) -> np.ndarray:
    """int8 + per-channel scales → f32 (broadcast over the last
    axis)."""
    return np.asarray(q, dtype=np.float32) * np.asarray(
        scale, dtype=np.float32)


def quantize_params(params: dict,
                    keys: list[str] | None = None
                    ) -> tuple[dict, list[str]]:
    """Quantize ``keys`` (default: every quantizable key) of a bundle
    param dict; returns ``(new_params, keys)`` with int8 tensors under
    the original keys plus ``<key>_scale`` f32 leaves."""
    if keys is None:
        keys = quantizable_keys(params)
    out = {}
    for key, arr in params.items():
        if key in keys:
            q, s = quantize_array(arr)
            out[key] = q
            out[scale_key(key)] = s
        else:
            out[key] = arr
    return out, list(keys)


def dequantize_params(manifest: dict | None, params: dict) -> dict:
    """Expand a quantized bundle's params back to f32 (scale keys
    dropped).  No-op passthrough for f32 bundles — safe to call on
    anything the watcher hands over."""
    rec = is_quantized(manifest)
    if rec is None:
        return params
    keys = set(rec.get("weights", []))
    out = {}
    for key, arr in params.items():
        if key in keys:
            out[key] = dequantize_array(arr, params[scale_key(key)])
        elif not (key.endswith("_scale") and key[:-6] in keys):
            out[key] = arr
    return out


def weight_nbytes(params: dict) -> int:
    """Total parameter bytes of a bundle's array dict (manifest buffer
    excluded by construction — it is not in the dict)."""
    return int(sum(np.asarray(v).nbytes for v in params.values()))


def _oracle_accuracy(manifest: dict, params: dict, x, y) -> float:
    """Top-1 accuracy of the bundle on the calibration stream through
    the compile-free numpy oracle (the same scorer the canary uses)."""
    from znicz_tpu.backends import NumpyDevice
    from znicz_tpu.export import ExportedModel
    model = ExportedModel(dict(manifest), dict(params),
                          device=NumpyDevice())
    pred = model.predict_classes(np.asarray(x))
    return float(np.mean(pred == np.asarray(y).reshape(-1)))


def quantize_bundle(manifest: dict, params: dict,
                    calib: tuple | None = None) -> tuple:
    """Quantize an exported bundle: ``(manifest, params)`` →
    ``(new_manifest, new_params, info)``.

    When ``calib=(x, y)`` is given (the canary/shadow stream), both
    arms are scored through the numpy f32 oracle and the accuracies
    ride the quant record — the publisher compares ``acc_delta``
    against the guard margin and falls back to f32 on a regression.
    The ``quant.calib_corrupt`` fault fires AFTER the gate (payload
    ``factor``, default 64), mis-scaling the published tensors the way
    a calibration bug would: downstream canary rejection is the only
    line of defense left, which is exactly what the chaos drill
    proves.
    """
    keys = quantizable_keys(params)
    info = {"keys": keys, "bytes_f32": weight_nbytes(params)}
    if not keys:
        info.update(bytes_quant=info["bytes_f32"], bytes_ratio=1.0,
                    quantized=False)
        return manifest, params, info
    qparams, keys = quantize_params(params, keys)
    record = {"dtype": QUANT_DTYPE, "scheme": SCHEME, "weights": keys}
    if calib is not None:
        x, y = calib
        new_manifest = dict(manifest)
        new_manifest["quant"] = record
        acc_f32 = _oracle_accuracy(manifest, params, x, y)
        acc_q = _oracle_accuracy(new_manifest, qparams, x, y)
        record["calib_acc_f32"] = acc_f32
        record["calib_acc_int8"] = acc_q
        record["calib_acc_delta"] = acc_f32 - acc_q
    payload = _faults.fire("quant.calib_corrupt")
    if payload is not None:
        # mis-scale AND sign-scramble alternating channels — a pure
        # uniform blow-up can survive saturating activations with its
        # argmax intact, which would let a broken calibration pass the
        # canary this drill exists to trip
        factor = float(payload.get("factor", 64.0))
        for key in keys:
            sk = scale_key(key)
            s = np.asarray(qparams[sk], np.float32) * factor
            s[::2] *= -1.0
            qparams[sk] = s
        info["corrupted"] = True
    new_manifest = dict(manifest)
    new_manifest["quant"] = record
    info.update(bytes_quant=weight_nbytes(qparams),
                quantized=True,
                acc_f32=record.get("calib_acc_f32"),
                acc_int8=record.get("calib_acc_int8"),
                acc_delta=record.get("calib_acc_delta"))
    info["bytes_ratio"] = info["bytes_quant"] / max(
        1, info["bytes_f32"])
    return new_manifest, qparams, info
