"""FleetEngine: many models, one mesh, SLO-aware multi-tenant serving.

Veles shipped VelesForge — a model *store*.  This module is the
serving side a real fleet needs on top of it (ROADMAP item 4): N
exported bundles (one-shot scorers and decode LMs mixed) resident in
ONE process, requests routed by model id + version, scheduled under
explicit per-tenant SLOs, and provably isolated — a misbehaving or
flooded tenant cannot move another tenant's p99.

The layers, bottom-up:

- **routing** — each model holds versions with *weighted A/B traffic
  fractions* (:meth:`FleetEngine.set_traffic`; smooth weighted
  round-robin, so fractions are exact over any window — the round-13
  two-version canary generalized to arbitrary splits), each version a
  :class:`ReplicaGroup` of engines round-robined per request, skipping
  replicas whose breaker is open;
- **priority admission** — every tenant belongs to a
  :class:`TenantClass` (priority, token-bucket rate, default
  deadline, retry budget, queue-row bound).  The priority rides into
  the engines' batchers (round-16
  :class:`~znicz_tpu.serving.batcher.PriorityQueue`): pending work is
  dispatched in strict priority order, and a full queue *preempts*
  the newest strictly-lower-priority rows instead of bouncing
  high-priority traffic — the flooding class absorbs its own
  overload;
- **per-tenant degradation state** — token-bucket shedding, a
  per-tenant circuit breaker (sustained shed/failure opens it; while
  open that tenant — and only that tenant — gets an instant
  :class:`~znicz_tpu.serving.batcher.Overloaded`; cooldown →
  half-open → one probe request decides), per-tenant deadline
  defaults and retry budgets threaded through to the dispatch layer;
- **shared memory budget** — every one-shot model's bucket-ladder
  programs charge ONE :class:`SharedLadderBudget`; pressure evicts
  the lowest-priority model's LRU bucket first
  (``znicz_fleet_ladder_evictions_total``), so co-residency degrades
  the cheapest ladder instead of failing allocation;
- **autoscaling** — :class:`FleetAutoscaler` grows/shrinks each
  model's replica group from the existing canonical queue-age and
  bucket-occupancy series, and *repairs* groups after a replica loss.
  One-shot replicas share their version's
  :class:`~znicz_tpu.export.ExportedModel` — the warmed AOT ladder
  and the weights are resident once — so scale-up and repair are
  compile-free by construction (a replica adds a continuous batcher
  + staging buffers + failure isolation; each dispatch already spans
  the mesh's data axis).

Chaos sites (:mod:`znicz_tpu.resilience.faults`):
``fleet.tenant_flood`` (a synthetic burst on one tenant at
:meth:`FleetEngine.tick`), ``fleet.model_corrupt`` (digest failure in
:class:`~znicz_tpu.forge.ForgeRegistry.fetch` — quarantine +
fallback), ``fleet.replica_loss`` (one live replica killed
mid-traffic; routing steers around it, the autoscaler repairs).

Telemetry: everything the isolation proof needs is a canonical
``/metrics`` series — ``znicz_fleet_requests_total{tenant,event}``
(shed attribution), ``znicz_fleet_latency_seconds`` +
``znicz_fleet_latency_p99_seconds`` (exact windowed per-tenant p99),
``znicz_fleet_breaker_state{tenant}``, ``znicz_fleet_models`` /
``znicz_fleet_replicas`` / ``znicz_fleet_scale_events_total``,
``znicz_fleet_traffic_weight{model,version}``,
``znicz_fleet_tenant_tokens`` and
``znicz_fleet_ladder_evictions_total``.

Locking discipline: the fleet lock guards tenant/breaker state and
the model table only, and is NEVER held across a call into an engine
(whose schedulers run future done-callbacks back into the fleet) —
outcome callbacks are lock-light by construction.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import Future

import numpy as np

from znicz_tpu.observe import metrics as _metrics
from znicz_tpu.observe import recorder as _recorder
from znicz_tpu.observe import tracing as _tracing
from znicz_tpu.resilience import faults as _faults
from znicz_tpu.serving.batcher import (_CLOSED, _HALF_OPEN, _OPEN,
                                       _STATE_CODE, DeadlineExceeded,
                                       Overloaded, QueueFull,
                                       TokenBucketLimiter)
from znicz_tpu.utils.logger import Logger

__all__ = ["FleetEngine", "TenantClass", "ReplicaGroup",
           "SharedLadderBudget", "FleetAutoscaler", "PoolAutoscaler"]

#: distinguishes same-process fleets in the registry's labels
_FLEET_SEQ = itertools.count()


class TenantClass:
    """One tenant's SLO class.

    ``priority``: 0 is the most important class; dispatch, preemption
    and KV-slot admission all order by it.  ``rate``/``burst``: the
    admission token bucket in rows (one-shot) / prompts (decode) per
    second — ``None`` disables rate limiting.  ``deadline_ms`` /
    ``retry_budget``: per-tenant defaults threaded into every
    dispatch.  ``max_queue_rows`` caps this tenant's share of any one
    engine's queue."""

    __slots__ = ("name", "priority", "rate", "burst", "deadline_ms",
                 "retry_budget", "max_queue_rows")

    def __init__(self, name: str, *, priority: int = 1,
                 rate: float | None = None, burst: float | None = None,
                 deadline_ms: float | None = None,
                 retry_budget: int | None = None,
                 max_queue_rows: int | None = None) -> None:
        self.name = str(name)
        self.priority = int(priority)
        self.rate = rate
        self.burst = burst
        self.deadline_ms = deadline_ms
        self.retry_budget = retry_budget
        self.max_queue_rows = max_queue_rows


class _TenantState:
    """Live admission state for one tenant on one fleet: token
    bucket, per-tenant circuit breaker, exact latency window, and the
    registry children everything exports through."""

    def __init__(self, fleet_id: str, cls: TenantClass,
                 breaker_failure_rate: float, breaker_window: int,
                 breaker_min_samples: int,
                 breaker_cooldown_ms: float) -> None:
        self.cls = cls
        self.bucket = TokenBucketLimiter(cls.rate, cls.burst)
        self.state = _CLOSED
        self.opened_at = 0.0
        self.probe_inflight = False
        self.failure_rate = float(breaker_failure_rate)
        self.min_samples = int(breaker_min_samples)
        self.cooldown = float(breaker_cooldown_ms) / 1e3
        self.outcomes: deque[bool] = deque(maxlen=int(breaker_window))
        self.latency_win: deque[float] = deque(maxlen=4096)
        self.counts = {"submitted": 0, "served": 0, "shed": 0,
                       "expired": 0, "failed": 0}
        self._m = {event: _metrics.fleet_requests(fleet_id, cls.name,
                                                  event)
                   for event in self.counts}
        self._m_lat = _metrics.fleet_latency_seconds(fleet_id, cls.name)
        self._m_state = _metrics.fleet_breaker_state(fleet_id, cls.name)
        self._m_state.set(_STATE_CODE[_CLOSED])
        _metrics.fleet_latency_p99_seconds(
            fleet_id, cls.name).set_function(self.p99)
        _metrics.fleet_tenant_tokens(fleet_id, cls.name).set_function(
            lambda b=self.bucket: b.level)

    # -- called under the fleet lock ------------------------------------
    def count(self, event: str) -> None:
        self.counts[event] += 1
        self._m[event].inc()

    def observe_latency(self, seconds: float) -> None:
        self.latency_win.append(seconds)
        self._m_lat.observe(seconds)

    def p99(self) -> float:
        win = sorted(self.latency_win)
        if not win:
            return 0.0
        idx = min(len(win) - 1,
                  max(0, int(round(0.99 * (len(win) - 1)))))
        return win[idx]

    def transition(self, state: str) -> None:
        if state == self.state:
            return
        self.state = state
        if state == _OPEN:
            self.opened_at = time.monotonic()
        self._m_state.set(_STATE_CODE[state])

    def breaker_tick(self, now: float) -> None:
        if self.state == _OPEN \
                and now - self.opened_at >= self.cooldown:
            self.transition(_HALF_OPEN)
            self.probe_inflight = False

    def record_outcome(self, ok: bool, probe: bool) -> None:
        if probe:
            self.probe_inflight = False
            # mixed one-shot + decode traffic shares ONE tenant
            # breaker: whichever path carried the probe decides
            self.transition(_CLOSED if ok else _OPEN)
            self.outcomes.clear()
            return
        if self.state != _CLOSED:
            return
        self.outcomes.append(ok)
        n = len(self.outcomes)
        if n >= self.min_samples:
            rate = self.outcomes.count(False) / n
            if rate >= self.failure_rate:
                self.transition(_OPEN)
                self.outcomes.clear()


class SharedLadderBudget:
    """One LRU accountant over EVERY attached model's bucket-ladder
    programs (round 16).

    Each :class:`~znicz_tpu.export.ExportedModel` joins via
    ``attach_program_budget(budget, key, priority)``; compiles charge
    bytes/program slots here, hits refresh recency.  When either cap
    (``max_programs`` / ``max_bytes``) is exceeded, the victim is the
    least-recently-used program of the LOWEST-priority attached model
    (largest priority number) — never the program just charged — so
    HBM pressure degrades the cheapest tenant's ladder first instead
    of failing allocation or touching a premium ladder.

    Registration also charges each model's RESIDENT WEIGHT bytes
    (round 21: :meth:`~znicz_tpu.export.ExportedModel.weights_nbytes`)
    against ``max_bytes`` as a protected, never-evictable entry —
    an int8-quantized bundle at ~0.5× the f32 bytes visibly raises
    how many ladder programs fit in the same budget."""

    def __init__(self, max_programs: int | None = None,
                 max_bytes: int | None = None,
                 fleet: str | None = None) -> None:
        if max_programs is None and max_bytes is None:
            raise ValueError("give max_programs and/or max_bytes")
        self.max_programs = max_programs
        self.max_bytes = max_bytes
        self.fleet = fleet or "fleet"
        self._lock = threading.RLock()
        #: key -> (model, priority)
        self._models: dict[str, tuple] = {}
        #: (key, size) -> nbytes, LRU order (oldest first)
        self._entries: "OrderedDict[tuple, int]" = OrderedDict()
        #: key -> resident weight bytes (protected — never a victim)
        self._weights: dict[str, int] = {}
        self.evictions = 0

    def register(self, key: str, model, priority: int) -> None:
        with self._lock:
            self._models[str(key)] = (model, int(priority))
            nbytes = getattr(model, "weights_nbytes", None)
            self._weights[str(key)] = (int(nbytes())
                                       if callable(nbytes) else 0)

    def touch(self, key: str, size: int) -> None:
        with self._lock:
            if (key, size) in self._entries:
                self._entries.move_to_end((key, size))

    def forget(self, key: str, size: int) -> None:
        with self._lock:
            self._entries.pop((key, size), None)

    @property
    def bytes_used(self) -> int:
        with self._lock:
            return (sum(self._entries.values())
                    + sum(self._weights.values()))

    @property
    def programs(self) -> int:
        return len(self._entries)

    def _over(self) -> bool:
        if self.max_programs is not None \
                and len(self._entries) > self.max_programs:
            return True
        return (self.max_bytes is not None
                and sum(self._entries.values())
                + sum(self._weights.values()) > self.max_bytes)

    def _pick_victim(self, protect: tuple) -> tuple | None:
        """LRU entry of the lowest-priority model, skipping the entry
        being charged."""
        worst_prio = None
        victim = None
        for entry in self._entries:  # oldest → newest
            if entry == protect:
                continue
            prio = self._models.get(entry[0], (None, 0))[1]
            if worst_prio is None or prio > worst_prio:
                worst_prio, victim = prio, entry
        return victim

    def charge(self, key: str, size: int, nbytes: int) -> None:
        victims = []
        with self._lock:
            self._entries[(key, size)] = int(nbytes)
            self._entries.move_to_end((key, size))
            while self._over():
                victim = self._pick_victim((key, size))
                if victim is None:
                    break  # only the protected entry remains
                self._entries.pop(victim)
                victims.append(victim)
                self.evictions += 1
        # drop programs OUTSIDE the budget lock (each drop takes the
        # victim model's swap lock)
        for vkey, vsize in victims:
            model = self._models.get(vkey, (None, 0))[0]
            if model is not None:
                model.drop_program(vsize)
            _metrics.fleet_ladder_evictions(self.fleet, vkey).inc()

    def stats(self) -> dict:
        with self._lock:
            per_model: dict[str, int] = {}
            for key, _size in self._entries:
                per_model[key] = per_model.get(key, 0) + 1
            return {"programs": len(self._entries),
                    "bytes": (sum(self._entries.values())
                              + sum(self._weights.values())),
                    "program_bytes": sum(self._entries.values()),
                    "weight_bytes": dict(self._weights),
                    "max_programs": self.max_programs,
                    "max_bytes": self.max_bytes,
                    "evictions": self.evictions,
                    "per_model": per_model}


class ReplicaGroup(Logger):
    """N dispatch replicas for one (model, version).

    A replica is a full engine (ServingEngine or DecodeEngine) with
    its own scheduler thread, batcher, breaker and staging buffers.
    One-shot replicas share the version's ``ExportedModel``: the AOT
    ladder and the published weight tuple are resident ONCE, so
    spawning (scale-up, repair after ``fleet.replica_loss``) compiles
    nothing once the first replica warmed.  Requests round-robin over
    live replicas, skipping any whose breaker is open."""

    def __init__(self, fleet_id: str, model_id: str, version: str,
                 factory, *, target: int = 1,
                 max_replicas: int = 4) -> None:
        super().__init__()
        self.fleet_id = fleet_id
        self.model_id = model_id
        self.version = version
        self._factory = factory
        self.target = int(target)
        self.max_replicas = int(max_replicas)
        self._replicas: list = []
        self._rr = itertools.count()
        self._replica_seq = itertools.count()
        self._lock = threading.Lock()
        self._m_replicas = _metrics.fleet_replicas(
            fleet_id, f"{model_id}@{version}")
        #: replica ids removed by the round-19 SDC shadow audit (the
        #: "corrupt-chip quarantine", serving side)
        self.sdc_quarantined: list[str] = []

    def live(self) -> int:
        return len(self._replicas)

    def engines(self) -> list:
        with self._lock:
            return list(self._replicas)

    def scale_to(self, n: int, reason: str = "manual") -> int:
        """Grow/shrink to ``n`` live replicas (clamped to
        [0, max_replicas]); returns the delta.  Shrink drains: the
        removed engine's shutdown serves everything it admitted."""
        n = max(0, min(int(n), self.max_replicas))
        started, stopped = [], []
        with self._lock:
            while len(self._replicas) < n:
                eng = self._factory()
                # round 19: replica identity + quarantine hook for the
                # SDC shadow audit (no-ops on engines without it)
                eng.sdc_replica = (f"{self.model_id}@{self.version}"
                                   f"#r{next(self._replica_seq)}")
                eng.on_sdc_suspect = self.quarantine_replica
                self._replicas.append(eng)
                started.append(eng)
            while len(self._replicas) > n:
                stopped.append(self._replicas.pop())
        for eng in started:
            eng.start()
        for eng in stopped:  # outside the lock: shutdown drains
            eng.shutdown()
        delta = len(started) - len(stopped)
        if delta:
            self.target = n if reason != "repair" else self.target
            self._m_replicas.set(self.live())
            _recorder.record("scale",
                             group=f"{self.model_id}@{self.version}",
                             reason=reason, delta=delta,
                             live=self.live())
            self.info("replica group %s@%s scaled to %d (%s)",
                      self.model_id, self.version, self.live(), reason)
        return delta

    def kill_one(self) -> bool:
        """Chaos: drop one live replica WITHOUT draining bookkeeping
        (``fleet.replica_loss``) — the autoscaler's repair path must
        bring the group back to target."""
        with self._lock:
            if not self._replicas:
                return False
            eng = self._replicas.pop(0)
        self._m_replicas.set(self.live())
        _recorder.record("replica_loss",
                         group=f"{self.model_id}@{self.version}",
                         live=self.live())
        eng.shutdown(timeout=30.0)
        self.warning("replica of %s@%s lost (chaos) — %d live",
                     self.model_id, self.version, self.live())
        return True

    def quarantine_replica(self, eng) -> bool:
        """Round 19: remove a shadow-audit-confirmed corrupt replica
        from the routing set (``znicz_sdc_quarantined_total{kind=
        replica}``) — the serving-side corrupt-chip quarantine.
        Shutdown drains on a helper thread because this is invoked
        from the suspect engine's OWN scheduler thread (its remaining
        queued batches serve oracle-corrected replies — zero wrong
        answers after detection); the autoscaler's existing
        live-below-target repair path (or an explicit
        ``scale_to(target, reason="repair")``) restores capacity
        compile-free."""
        with self._lock:
            if eng not in self._replicas:
                return False
            self._replicas.remove(eng)
            self.sdc_quarantined.append(
                getattr(eng, "sdc_replica", "?"))
        self._m_replicas.set(self.live())
        _metrics.sdc_quarantined("replica").inc()
        _recorder.record("sdc_quarantine",
                         group=f"{self.model_id}@{self.version}",
                         replica=getattr(eng, "sdc_replica", "?"),
                         live=self.live())
        self.warning(
            "replica %s of %s@%s QUARANTINED by the SDC shadow audit "
            "— %d live", getattr(eng, "sdc_replica", "?"),
            self.model_id, self.version, self.live())
        threading.Thread(target=eng.shutdown, name="sdc-quarantine",
                         daemon=True).start()
        return True

    def pick(self):
        """Next live replica (round-robin), skipping breaker-open and
        SDC-suspect replicas; None when the group is empty or fully
        shedding."""
        with self._lock:
            replicas = list(self._replicas)
        if not replicas:
            return None
        start = next(self._rr)
        for i in range(len(replicas)):
            eng = replicas[(start + i) % len(replicas)]
            if getattr(eng, "breaker_state", "closed") != "open" \
                    and not getattr(eng, "sdc_suspect", False):
                return eng
        return None


class _Version:
    """One traffic-weighted version of a fleet model."""

    __slots__ = ("label", "weight", "current", "group", "model",
                 "source", "quant")

    def __init__(self, label: str, weight: float, group: ReplicaGroup,
                 model, source, quant: bool = False) -> None:
        self.label = label
        self.weight = float(weight)
        self.current = 0.0  # smooth weighted round-robin credit
        self.group = group
        self.model = model  # shared ExportedModel (one-shot) or None
        self.source = source
        self.quant = bool(quant)  # bundle carries an int8 quant record


class _FleetModel:
    """A registered model: kind, SLO priority, versions + weights."""

    __slots__ = ("model_id", "kind", "priority", "versions",
                 "input_shape")

    def __init__(self, model_id: str, kind: str, priority: int,
                 input_shape: tuple | None) -> None:
        self.model_id = model_id
        self.kind = kind  # "oneshot" | "lm"
        self.priority = int(priority)
        self.versions: "OrderedDict[str, _Version]" = OrderedDict()
        self.input_shape = input_shape

    def pick_version(self) -> _Version:
        """Smooth weighted round-robin: exact fractions over any
        window, deterministic (no RNG in the request path)."""
        versions = [v for v in self.versions.values() if v.weight > 0]
        if not versions:
            raise RuntimeError(
                f"model '{self.model_id}' has no version with "
                f"traffic weight > 0")
        total = sum(v.weight for v in versions)
        best = None
        for v in versions:
            v.current += v.weight
            if best is None or v.current > best.current:
                best = v
        best.current -= total
        return best


class FleetEngine(Logger):
    """N models, one process, per-tenant SLOs (see module docstring).

    Usage::

        fleet = FleetEngine(tenants=[
            TenantClass("hi", priority=0),
            TenantClass("lo", priority=2, rate=200, burst=50,
                        deadline_ms=250, max_queue_rows=64),
        ])
        fleet.add_model("scorer", "scorer.npz", max_batch=16)
        fleet.add_model("lm", "lm.npz", kind="lm", max_slots=6)
        fleet.start()
        probs  = fleet.submit("scorer", x, tenant="hi").result()
        tokens = fleet.submit("lm", prompt, tenant="lo").result()
        fleet.tick()        # autoscaler + chaos sites
        fleet.shutdown()
    """

    def __init__(self, *, tenants: list[TenantClass] | None = None,
                 default_tenant: str = "default",
                 name: str | None = None,
                 max_programs: int | None = None,
                 max_program_bytes: int | None = None,
                 breaker_failure_rate: float = 0.5,
                 breaker_window: int = 16,
                 breaker_min_samples: int = 4,
                 breaker_cooldown_ms: float = 500.0,
                 autoscale: bool = True,
                 max_replicas: int = 4,
                 replicate: bool | None = None) -> None:
        super().__init__()
        self._obs_id = name or f"fleet#{next(_FLEET_SEQ)}"
        self._lock = threading.RLock()
        self._breaker_cfg = (breaker_failure_rate, breaker_window,
                             breaker_min_samples, breaker_cooldown_ms)
        self._tenants: dict[str, _TenantState] = {}
        self.default_tenant = default_tenant
        for cls in (tenants or []):
            self.add_tenant(cls)
        if default_tenant not in self._tenants:
            self.add_tenant(TenantClass(default_tenant, priority=1))
        self.budget = None
        if max_programs is not None or max_program_bytes is not None:
            self.budget = SharedLadderBudget(
                max_programs=max_programs, max_bytes=max_program_bytes,
                fleet=self._obs_id)
        self._models: "OrderedDict[str, _FleetModel]" = OrderedDict()
        self._m_models = _metrics.fleet_models(self._obs_id)
        self.max_replicas = int(max_replicas)
        self._replicate = replicate
        self._device = None  # resolved once, shared by one-shot models
        self.autoscaler = (FleetAutoscaler(self) if autoscale else None)
        self._federator = None
        self._started = False

    # ------------------------------------------------------------------
    # registration
    # ------------------------------------------------------------------
    def add_tenant(self, cls: TenantClass | str, **kwargs
                   ) -> TenantClass:
        if isinstance(cls, str):
            cls = TenantClass(cls, **kwargs)
        with self._lock:
            if cls.name in self._tenants:
                raise ValueError(f"tenant '{cls.name}' already exists")
            self._tenants[cls.name] = _TenantState(
                self._obs_id, cls, *self._breaker_cfg)
        return cls

    def tenant(self, name: str) -> TenantClass:
        return self._tenant_state(name).cls

    def _tenant_state(self, name: str) -> _TenantState:
        state = self._tenants.get(name)
        if state is None:
            raise KeyError(
                f"unknown tenant '{name}' — add_tenant() it first "
                f"(known: {sorted(self._tenants)})")
        return state

    def _resolve_device(self):
        if self._device is None:
            from znicz_tpu.serving.engine import ServingEngine
            self._device = ServingEngine.resolve_device(self._replicate)
        return self._device

    def add_model(self, model_id: str, source, *, kind: str | None = None,
                  version: str = "v1", weight: float = 1.0,
                  priority: int | None = None, replicas: int = 1,
                  max_replicas: int | None = None,
                  **engine_kwargs) -> None:
        """Register a model (its first version).  ``source`` is a
        bundle path or an :class:`~znicz_tpu.export.ExportedModel`;
        ``kind`` defaults to the bundle manifest's (``lm`` bundles
        serve through a :class:`~znicz_tpu.serving.DecodeEngine`,
        scorers through a :class:`~znicz_tpu.serving.ServingEngine`).
        ``priority`` is the model's SLO class for SHARED-LADDER
        eviction order (defaults to the lowest — largest — registered
        tenant priority).  ``engine_kwargs`` pass through to every
        replica engine (``max_batch``, ``max_slots``, …)."""
        with self._lock:
            if model_id in self._models:
                raise ValueError(f"model '{model_id}' already "
                                 f"registered — use add_version()")
        if priority is None:
            priority = max((s.cls.priority
                            for s in self._tenants.values()),
                           default=1)
        entry = self._build_version(model_id, source, kind, version,
                                    weight, int(priority), replicas,
                                    max_replicas, engine_kwargs)
        kind = entry[0]
        with self._lock:
            model = _FleetModel(model_id, kind, int(priority),
                                entry[2])
            model.versions[version] = entry[1]
            self._models[model_id] = model
            self._m_models.set(len(self._models))
        _metrics.fleet_traffic_weight(self._obs_id, model_id,
                                      version).set(weight)
        self._refresh_quant_gauge()
        if self._started:
            entry[1].group.scale_to(replicas, reason="up")

    def add_version(self, model_id: str, source, *,
                    version: str, weight: float = 0.0,
                    replicas: int = 1, max_replicas: int | None = None,
                    **engine_kwargs) -> None:
        """Add another traffic-weighted version of a registered model
        (A/B / canary generalization: any number of versions, any
        fractions)."""
        model = self._models[model_id]
        if version in model.versions:
            raise ValueError(f"{model_id}@{version} already exists")
        entry = self._build_version(model_id, source, model.kind,
                                    version, weight, model.priority,
                                    replicas, max_replicas,
                                    engine_kwargs)
        with self._lock:
            model.versions[version] = entry[1]
        _metrics.fleet_traffic_weight(self._obs_id, model_id,
                                      version).set(weight)
        self._refresh_quant_gauge()
        if self._started:
            entry[1].group.scale_to(replicas, reason="up")

    def _refresh_quant_gauge(self) -> None:
        """``znicz_quantized_models``: int8-quantized model versions
        currently registered (round 21)."""
        with self._lock:
            n = sum(1 for m in self._models.values()
                    for v in m.versions.values() if v.quant)
        _metrics.quantized_models(self._obs_id).set(n)

    def _build_version(self, model_id: str, source, kind: str | None,
                       version: str, weight: float, priority: int,
                       replicas: int, max_replicas: int | None,
                       engine_kwargs: dict) -> tuple:
        """Resolve (kind, _Version, input_shape) for one source."""
        from znicz_tpu.export import ExportedModel, read_bundle
        from znicz_tpu.serving.decode import DecodeEngine
        from znicz_tpu.serving.engine import ServingEngine
        shared_model = None
        input_shape = None
        if isinstance(source, ExportedModel):
            shared_model = source
            manifest = source.manifest
        elif isinstance(source, (str, bytes)) \
                or hasattr(source, "__fspath__"):
            manifest, _params = read_bundle(source)
        else:
            raise TypeError(f"cannot serve {type(source).__name__}: "
                            f"pass a bundle path or an ExportedModel")
        if kind is None:
            kind = "lm" if manifest.get("kind") == "lm" else "oneshot"
        if kind not in ("oneshot", "lm"):
            raise ValueError(f"kind must be 'oneshot' or 'lm', "
                             f"got {kind!r}")
        cap = (max_replicas if max_replicas is not None
               else self.max_replicas)
        if kind == "oneshot":
            max_batch = int(engine_kwargs.pop("max_batch", 16))
            if shared_model is None:
                shared_model = ExportedModel.load(
                    source, device=self._resolve_device(),
                    max_batch=max_batch)
            input_shape = shared_model.input_shape
            if self.budget is not None:
                shared_model.attach_program_budget(
                    self.budget, key=f"{model_id}@{version}",
                    priority=priority)
            kwargs = dict(engine_kwargs)

            def factory(model=shared_model, kwargs=kwargs,
                        max_batch=max_batch):
                return ServingEngine(model, max_batch=max_batch,
                                     **kwargs)
        else:
            if shared_model is not None:
                raise TypeError(
                    "decode models are registered by bundle PATH — "
                    "each replica builds its own KV-cache state")
            kwargs = dict(engine_kwargs)

            def factory(source=source, kwargs=kwargs):
                return DecodeEngine(source, **kwargs)
        group = ReplicaGroup(self._obs_id, model_id, version, factory,
                             target=replicas, max_replicas=cap)
        return kind, _Version(version, weight, group, shared_model,
                              source,
                              quant=bool(manifest.get("quant"))
                              ), input_shape

    def set_traffic(self, model_id: str,
                    weights: dict[str, float]) -> None:
        """Set the A/B traffic split across a model's versions —
        arbitrary fractions (they need not sum to 1; routing
        normalizes).  A version absent from ``weights`` keeps its
        current weight; weight 0 drains a version out of the split
        without tearing its replicas down."""
        model = self._models[model_id]
        with self._lock:
            for label, weight in weights.items():
                if label not in model.versions:
                    raise KeyError(f"{model_id}@{label} not registered")
                if weight < 0:
                    raise ValueError(f"weight must be >= 0, "
                                     f"got {weight}")
                model.versions[label].weight = float(weight)
                model.versions[label].current = 0.0
        for label, weight in weights.items():
            _metrics.fleet_traffic_weight(self._obs_id, model_id,
                                          label).set(weight)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> "FleetEngine":
        if self._started:
            return self
        for model in self._models.values():
            for v in model.versions.values():
                v.group.scale_to(max(1, v.group.target), reason="up")
        if _metrics.enabled() and self._federator is None:
            # tick() doubles as the fleet's federation cadence: one
            # in-process source re-labels every replica engine's
            # series under its model@version "pool"
            from znicz_tpu.observe.federation import Federator
            self._federator = Federator(self._obs_id)
            self._federator.add_registry("self",
                                         pool_of=self._fed_pool_of)
        self._started = True
        self.info("fleet '%s': %d models resident, tenants=%s",
                  self._obs_id, len(self._models),
                  sorted(self._tenants))
        return self

    def shutdown(self, timeout: float = 60.0) -> None:
        for model in self._models.values():
            for v in model.versions.values():
                for eng in v.group.engines():
                    eng.shutdown(timeout=timeout)
                v.group.scale_to(0, reason="down")
        if self._federator is not None:
            self._federator.close()
            self._federator = None
        self._started = False

    def _fed_pool_of(self, eng_label: str):
        """Map a replica engine's label to its ``model@version`` fed
        pool (None: not one of this fleet's replicas)."""
        for model in self._models.values():
            for v in model.versions.values():
                for e in v.group.engines():
                    if getattr(e, "_obs_id", None) == eng_label:
                        return f"{model.model_id}@{v.label}"
        return None

    def __enter__(self) -> "FleetEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # ------------------------------------------------------------------
    # request path
    # ------------------------------------------------------------------
    def submit(self, model_id: str, x, *, tenant: str | None = None,
               version: str | None = None,
               deadline_ms: float | None = None,
               max_new_tokens: int | None = None,
               retry_budget: int | None = None) -> Future:
        """Route one request: tenant admission (breaker → token
        bucket) → version pick (weighted A/B, or pinned via
        ``version``) → replica pick (round-robin, breaker-open
        skipped) → engine submit carrying the tenant's priority,
        deadline and retry budget.  Sheds raise
        :class:`Overloaded`/:class:`QueueFull`; every outcome lands on
        this tenant's counters, latency window and breaker."""
        if not self._started:
            raise RuntimeError("fleet not started — call start()")
        t0 = time.monotonic()
        tname = tenant or self.default_tenant
        state = self._tenant_state(tname)
        cls = state.cls
        model = self._models.get(model_id)
        if model is None:
            raise KeyError(f"unknown model '{model_id}' "
                           f"(known: {sorted(self._models)})")
        # the trace is minted HERE — the fleet's routing decision is
        # the request's first hop — and handed to the engine's submit
        # via the pending-trace channel (round 24)
        trace = _tracing.new_request_trace("request", model=model_id,
                                           tenant=tname)

        def _shed(event: str) -> None:
            _metrics.trace_requests(self._obs_id, "shed").inc()
            trace.event(event, fleet=self._obs_id, tenant=tname)
            trace.finish("shed")
        probe = False
        with self._lock:
            state.breaker_tick(t0)
            if state.state == _OPEN:
                state.count("shed")
                _shed("breaker_shed")
                raise Overloaded(
                    f"tenant '{tname}' breaker open — load shed "
                    f"(retry after {state.cooldown * 1e3:.0f}ms)")
            if state.state == _HALF_OPEN:
                if state.probe_inflight:
                    state.count("shed")
                    _shed("breaker_shed")
                    raise Overloaded(
                        f"tenant '{tname}' breaker half-open — probe "
                        f"in flight")
                state.probe_inflight = True
                probe = True
        cost = (int(np.shape(x)[0])
                if model.kind == "oneshot" and np.ndim(x) > 1 else 1)
        if not state.bucket.try_acquire(cost):
            with self._lock:
                state.count("shed")
                # sustained rate-limit shedding IS the flood signal:
                # it feeds the tenant breaker so a flooding tenant
                # degrades to instant rejection
                state.record_outcome(False, probe)
            _shed("rate_limit_shed")
            raise Overloaded(
                f"tenant '{tname}' rate limit — token bucket empty "
                f"(rate={cls.rate}/s, burst={cls.burst})")
        if deadline_ms is None:
            deadline_ms = cls.deadline_ms
        if retry_budget is None:
            retry_budget = cls.retry_budget
        with self._lock:
            v = (model.versions[version] if version is not None
                 else model.pick_version())
        engine = v.group.pick()
        if engine is None:
            with self._lock:
                state.count("shed")
                state.record_outcome(False, probe)
            _shed("no_replica_shed")
            raise Overloaded(
                f"no live replica for {model_id}@{v.label}")
        # the A/B choice + replica pick land on the trace, then the
        # trace parks on this thread for the engine's request
        # constructor to adopt (same-thread synchronous submit)
        trace.event("fleet_route", fleet=self._obs_id,
                    model=model_id, version=v.label,
                    replica=getattr(engine, "sdc_replica", "?"))
        _tracing.set_pending_trace(trace)
        try:
            if model.kind == "lm":
                future = engine.submit(
                    x, max_new_tokens=max_new_tokens,
                    deadline_ms=deadline_ms, tenant=tname,
                    priority=cls.priority)
            else:
                future = engine.submit(
                    x, deadline_ms=deadline_ms, tenant=tname,
                    priority=cls.priority, retry_budget=retry_budget,
                    tenant_max_rows=cls.max_queue_rows)
        except Exception as exc:  # noqa: BLE001 — probe must not leak
            # an engine that raised before constructing its request
            # never adopted the parked trace — clear it so the NEXT
            # request on this thread cannot inherit it
            leftover = _tracing.adopt_pending_trace()
            if leftover is not None:
                _metrics.trace_requests(self._obs_id, "shed").inc()
                leftover.finish("shed")
            with self._lock:
                state.count("shed" if isinstance(
                    exc, (QueueFull, DeadlineExceeded)) else "failed")
                state.record_outcome(False, probe)
            raise
        _tracing.adopt_pending_trace()  # engine took it; clear if not
        with self._lock:
            state.count("submitted")
        future.add_done_callback(
            lambda f, s=state, t=t0, p=probe: self._on_done(s, t, f, p))
        return future

    def __call__(self, model_id: str, x, timeout: float | None = None,
                 **kwargs):
        """Synchronous convenience: submit + wait."""
        return self.submit(model_id, x, **kwargs).result(
            timeout=timeout)

    def _on_done(self, state: _TenantState, t0: float, future: Future,
                 probe: bool) -> None:
        """Outcome accounting (runs on engine scheduler threads —
        keep it lock-light, never call back into an engine).

        Latency semantics per request kind: one-shot scoring observes
        submit→reply; GENERATION observes submit→first-token (the
        decode engine stamps ``ttft_s`` on the future) — TTFT is the
        scheduling-bound SLO the fleet controls, while completion
        time is proportional to the tokens requested (round-12
        TTFT/cadence split), so an SLO on it would conflate work size
        with admission latency."""
        exc = future.exception()
        with self._lock:
            if exc is None:
                state.count("served")
                ttft = getattr(future, "ttft_s", None)
                state.observe_latency(ttft if ttft is not None
                                      else time.monotonic() - t0)
                state.record_outcome(True, probe)
            elif isinstance(exc, DeadlineExceeded):
                state.count("expired")
                state.record_outcome(False, probe)
            elif isinstance(exc, QueueFull):  # preempted / shed late
                state.count("shed")
                state.record_outcome(False, probe)
            else:
                state.count("failed")
                state.record_outcome(False, probe)

    # ------------------------------------------------------------------
    # maintenance: chaos sites + autoscaler
    # ------------------------------------------------------------------
    def tick(self) -> list[str]:
        """One control-plane step (drive from any host loop): fires
        the fleet chaos sites when a plan says so, then runs one
        autoscaler pass.  Returns human-readable events."""
        events: list[str] = []
        payload = _faults.fire("fleet.tenant_flood")
        if payload is not None:
            self._inject_flood(payload, events)
        payload = _faults.fire("fleet.replica_loss")
        if payload is not None:
            self._kill_replica(payload, events)
        if self.autoscaler is not None:
            events.extend(self.autoscaler.tick())
        if self._federator is not None:
            self._federator.scrape()
        return events

    def _flood_tenant(self) -> str:
        """The lowest-priority tenant (chaos default)."""
        return max(self._tenants.values(),
                   key=lambda s: s.cls.priority).cls.name

    def _inject_flood(self, payload: dict, events: list[str]) -> None:
        tname = payload.get("tenant") or self._flood_tenant()
        n = int(payload.get("n", 32))
        model_id = payload.get("model")
        if model_id is None:
            candidates = [m for m in self._models.values()
                          if m.kind == "oneshot"] \
                or list(self._models.values())
            if not candidates:
                return
            model_id = candidates[0].model_id
        model = self._models[model_id]
        shed = served = 0
        for _i in range(n):
            try:
                if model.kind == "lm":
                    self.submit(model_id, np.zeros(1, np.int32),
                                tenant=tname, max_new_tokens=1)
                else:
                    self.submit(
                        model_id,
                        np.zeros((1,) + tuple(model.input_shape),
                                 np.float32), tenant=tname)
                served += 1
            except QueueFull:  # Overloaded included — the flood sheds
                shed += 1
        _metrics.recoveries("tenant_flood_absorbed").inc()
        msg = (f"injected flood: {n} requests on tenant '{tname}' → "
               f"{served} admitted, {shed} shed inside the class")
        self.warning(msg)
        events.append(msg)

    def _kill_replica(self, payload: dict, events: list[str]) -> None:
        model_id = payload.get("model") \
            or next(iter(self._models), None)
        if model_id is None:
            return
        model = self._models[model_id]
        for v in model.versions.values():
            if v.group.live() > 0:
                v.group.kill_one()
                msg = (f"injected replica loss on "
                       f"{model_id}@{v.label} — {v.group.live()} live,"
                       f" awaiting autoscaler repair")
                events.append(msg)
                return

    # ------------------------------------------------------------------
    # telemetry
    # ------------------------------------------------------------------
    def tenant_stats(self, name: str) -> dict:
        state = self._tenant_state(name)
        with self._lock:
            win = sorted(state.latency_win)
            out = {"priority": state.cls.priority,
                   "rate": state.cls.rate,
                   "breaker": state.state,
                   "tokens": round(state.bucket.level, 1),
                   **dict(state.counts)}
        if win:
            def pct(q):
                idx = min(len(win) - 1,
                          max(0, int(round(q / 100 * (len(win) - 1)))))
                return round(1e3 * win[idx], 3)
            out["latency_ms"] = {"p50": pct(50), "p95": pct(95),
                                 "p99": pct(99), "window": len(win)}
        return out

    def stats(self) -> dict:
        models: dict = {}
        for model in self._models.values():
            versions = {}
            for v in model.versions.values():
                versions[v.label] = {
                    "weight": v.weight,
                    "replicas": v.group.live(),
                    "target": v.group.target,
                    "quant": v.quant,
                    "served": sum(
                        int(e.stats().get("served", 0))
                        for e in v.group.engines()),
                }
            models[model.model_id] = {
                "kind": model.kind, "priority": model.priority,
                "versions": versions}
        out = {
            "engine": "fleet",
            "fleet": self._obs_id,
            "models": models,
            "tenants": {name: self.tenant_stats(name)
                        for name in sorted(self._tenants)},
        }
        if self.budget is not None:
            out["ladder_budget"] = self.budget.stats()
        return out

    def ready(self) -> bool:
        """Every model has at least one live replica (a single
        tenant's open breaker does NOT make the process unready — it
        sheds exactly that tenant)."""
        return bool(self._started and all(
            any(v.group.live() > 0 for v in m.versions.values())
            for m in self._models.values()))

    def serving_status(self) -> dict:
        """``web_status.gather_status`` hook."""
        out = {"name": f"fleet:{self._obs_id}",
               "initialized": self._started,
               "stopped": not self._started}
        out.update(self.stats())
        return out


class FleetAutoscaler:
    """Replica autoscaling from the existing canonical series.

    Per (model, version) group each :meth:`tick`:

    - **repair** — live < target (a ``fleet.replica_loss`` or a died
      engine): scale back to target immediately
      (``znicz_fleet_scale_events_total{op=repair}`` +
      ``znicz_recoveries_total{kind=replica_respawn}``);
    - **up** — the group's worst replica queue age
      (``znicz_serving_queue_age_seconds``) exceeds
      ``queue_age_up_s``, or its cumulative bucket occupancy
      (``znicz_serving_bucket_rows_total`` /
      ``znicz_serving_bucket_batches_total`` × bucket) exceeds
      ``occupancy_up`` while queue rows are pending — and live <
      max_replicas;
    - **down** — the group has been idle (zero queue age and no new
      served work) for ``idle_down_s`` and live > min_replicas.

    Decode groups participate in repair only: a FUSED decode engine's
    slot occupancy is already the KV-pool's admission currency and a
    full extra engine re-plans its own programs.  The round-22
    disaggregated engine lifts that limit — its pool replicas share
    ONE warmed :class:`~znicz_tpu.serving.decode.DecodeModel` and
    scale compile-free; :class:`PoolAutoscaler` below is the
    per-pool (prefill/decode) scaler that exploits it."""

    def __init__(self, fleet: FleetEngine, *,
                 queue_age_up_s: float = 0.25,
                 occupancy_up: float = 0.9,
                 idle_down_s: float = 5.0,
                 min_replicas: int = 1,
                 cooldown_s: float = 0.5) -> None:
        self.fleet = fleet
        self.queue_age_up_s = float(queue_age_up_s)
        self.occupancy_up = float(occupancy_up)
        self.idle_down_s = float(idle_down_s)
        self.min_replicas = int(min_replicas)
        self.cooldown_s = float(cooldown_s)
        self._last_scale: dict[tuple, float] = {}
        self._last_busy: dict[tuple, float] = {}
        self._last_served: dict[tuple, int] = {}

    # -- canonical-series readers --------------------------------------
    @staticmethod
    def _gauge_for(series: str, engine_id: str) -> float:
        fam = _metrics.REGISTRY.get(series)
        if fam is None:
            return 0.0
        for key, child in fam.items():
            if key[0] == engine_id:
                return float(child.value)
        return 0.0

    @staticmethod
    def _occupancy_for(engine_id: str) -> float:
        rows_fam = _metrics.REGISTRY.get(
            "znicz_serving_bucket_rows_total")
        batches_fam = _metrics.REGISTRY.get(
            "znicz_serving_bucket_batches_total")
        if rows_fam is None or batches_fam is None:
            return 0.0
        rows = sum(child.value for key, child in rows_fam.items()
                   if key[0] == engine_id)
        capacity = sum(child.value * float(key[1])
                       for key, child in batches_fam.items()
                       if key[0] == engine_id)
        return rows / capacity if capacity else 0.0

    def tick(self) -> list[str]:
        events: list[str] = []
        now = time.monotonic()
        for model in list(self.fleet._models.values()):
            for v in model.versions.values():
                events.extend(self._tick_group(model, v, now))
        return events

    def _tick_group(self, model: _FleetModel, v: _Version,
                    now: float) -> list[str]:
        events: list[str] = []
        group = v.group
        gkey = (model.model_id, v.label)
        live = group.live()
        if live < group.target and self.fleet._started:
            group.scale_to(group.target, reason="repair")
            _metrics.fleet_scale_events(self.fleet._obs_id,
                                        f"{model.model_id}@{v.label}",
                                        "repair").inc()
            _metrics.recoveries("replica_respawn").inc()
            events.append(f"repaired {model.model_id}@{v.label} → "
                          f"{group.live()} replicas")
            self._last_scale[gkey] = now
            return events
        if model.kind != "oneshot":
            return events  # decode groups: repair-only (see class doc)
        engines = group.engines()
        if not engines:
            return events
        ages = [self._gauge_for("znicz_serving_queue_age_seconds",
                                e._obs_id) for e in engines]
        queue_rows = [self._gauge_for("znicz_serving_queue_rows",
                                      e._obs_id) for e in engines]
        occ = max((self._occupancy_for(e._obs_id) for e in engines),
                  default=0.0)
        served = sum(int(e.stats().get("served", 0)) for e in engines)
        busy = (max(ages, default=0.0) > 0.0
                or sum(queue_rows) > 0
                or served != self._last_served.get(gkey, -1))
        self._last_served[gkey] = served
        if busy:
            self._last_busy[gkey] = now
        if now - self._last_scale.get(gkey, 0.0) < self.cooldown_s:
            return events
        if (max(ages, default=0.0) > self.queue_age_up_s
            or (occ > self.occupancy_up and sum(queue_rows) > 0)) \
                and live < group.max_replicas:
            group.scale_to(live + 1, reason="up")
            _metrics.fleet_scale_events(self.fleet._obs_id,
                                        f"{model.model_id}@{v.label}",
                                        "up").inc()
            events.append(
                f"scaled {model.model_id}@{v.label} up → "
                f"{group.live()} (queue_age={max(ages):.2f}s, "
                f"occupancy={occ:.2f})")
            self._last_scale[gkey] = now
        elif (live > self.min_replicas
              and now - self._last_busy.get(gkey, now)
              > self.idle_down_s):
            group.scale_to(live - 1, reason="down")
            _metrics.fleet_scale_events(self.fleet._obs_id,
                                        f"{model.model_id}@{v.label}",
                                        "down").inc()
            events.append(f"scaled {model.model_id}@{v.label} down → "
                          f"{group.live()} (idle)")
            self._last_scale[gkey] = now
        return events


class PoolAutoscaler:
    """Per-pool replica autoscaling for a disaggregated serving
    engine (round 22).

    ``pools`` maps a pool name (``"prefill"`` / ``"decode"``) to its
    :class:`ReplicaGroup`; the scaling signal is that pool's child of
    ``znicz_serving_queue_age_seconds{engine=<engine_id>,
    pool=<name>}`` — prefill reads the shared prompt queue's head
    age, decode the oldest unaccepted handoff — so a prompt burst
    grows the prefill pool without touching decode residency, and a
    handoff backlog grows decode without spending prefill compute.

    Unlike :class:`FleetAutoscaler`'s decode caveat, these replicas
    ARE compile-free: every pool worker shares one warmed
    :class:`~znicz_tpu.serving.decode.DecodeModel` and owns only a
    private same-geometry cache (:meth:`DecodeModel.make_cache`), so
    a scale-up costs cache allocation, not XLA compiles.

    Per pool each :meth:`tick`: **repair** when live < target;
    **up** when the pool's queue age exceeds ``queue_age_up_s`` and
    live < max_replicas; **down** after ``idle_down_s`` of zero queue
    age and no new served work, to ``min_replicas``."""

    def __init__(self, pools: dict[str, ReplicaGroup],
                 engine_id: str, *,
                 queue_age_up_s: float = 0.25,
                 idle_down_s: float = 5.0,
                 min_replicas: int = 1,
                 cooldown_s: float = 0.5) -> None:
        self.pools = dict(pools)
        self.engine_id = engine_id
        self.queue_age_up_s = float(queue_age_up_s)
        self.idle_down_s = float(idle_down_s)
        self.min_replicas = int(min_replicas)
        self.cooldown_s = float(cooldown_s)
        self._last_scale: dict[str, float] = {}
        self._last_busy: dict[str, float] = {}
        self._last_served: dict[str, int] = {}

    def _pool_age(self, pool: str) -> float:
        fam = _metrics.REGISTRY.get("znicz_serving_queue_age_seconds")
        if fam is None:
            return 0.0
        for key, child in fam.items():
            if key[0] == self.engine_id and key[1] == pool:
                return float(child.value)
        return 0.0

    def tick(self) -> list[str]:
        events: list[str] = []
        now = time.monotonic()
        for name, group in self.pools.items():
            events.extend(self._tick_pool(name, group, now))
        return events

    def _tick_pool(self, name: str, group: ReplicaGroup,
                   now: float) -> list[str]:
        events: list[str] = []
        live = group.live()
        if live < group.target:
            group.scale_to(group.target, reason="repair")
            _metrics.fleet_scale_events(
                self.engine_id, f"{self.engine_id}@{name}",
                "repair").inc()
            _metrics.recoveries("replica_respawn").inc()
            events.append(f"repaired pool {name} → "
                          f"{group.live()} replicas")
            self._last_scale[name] = now
            return events
        age = self._pool_age(name)
        served = sum(int(getattr(e, "served", 0))
                     for e in group.engines())
        busy = age > 0.0 or served != self._last_served.get(name, -1)
        self._last_served[name] = served
        if busy:
            self._last_busy[name] = now
        if now - self._last_scale.get(name, 0.0) < self.cooldown_s:
            return events
        if age > self.queue_age_up_s and live < group.max_replicas:
            group.scale_to(live + 1, reason="up")
            _metrics.fleet_scale_events(
                self.engine_id, f"{self.engine_id}@{name}",
                "up").inc()
            events.append(f"scaled pool {name} up → {group.live()} "
                          f"(queue_age={age:.2f}s)")
            self._last_scale[name] = now
        elif (live > self.min_replicas
              and now - self._last_busy.get(name, now)
              > self.idle_down_s):
            group.scale_to(live - 1, reason="down")
            _metrics.fleet_scale_events(
                self.engine_id, f"{self.engine_id}@{name}",
                "down").inc()
            events.append(f"scaled pool {name} down → "
                          f"{group.live()} (idle)")
            self._last_scale[name] = now
        return events
