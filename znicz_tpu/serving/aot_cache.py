"""Persisted AOT executable cache: compile-free cold starts (round 23).

Every warmed path in the framework is zero-compile, but every cold
*process* still pays the full trace+compile ladder — an elastic
restart recompiles the training step on the surviving mesh, a
fleet/pool scale-out onto a fresh host recompiles every bucket program
before it can absorb the burst it was spawned for.  This module turns
restart-to-first-token and resume-to-first-step from compile-bound
into I/O-bound: compiled XLA executables are serialized
(``jax.experimental.serialize_executable``) into a content-addressed
store next to the weights and deserialized before tracing on the next
cold start.

Safety model — a wrong program can NEVER load:

- entries are **content-addressed**: the key is a sha256 over every
  input that shapes the compiled program — program family, the
  bundle's architecture digest (manifest layer table + geometry +
  dtype, weight VALUES excluded: since round 13 weights are call-time
  operands, so a v2 weight refresh of the same architecture reuses v1
  programs), bucket/geometry, operand shapes + dtypes + shardings
  (which carry the mesh shape and axis names), donation, platform +
  device kind + device count, jax version, a digest of the znicz
  package sources, and a digest of the program-relevant config tree.
  Any mismatch is a plain cache miss → trace as before;
- jit-region programs additionally key on the **jaxpr hash** of the
  exact function being compiled (region bodies bake unit hyperparams
  into the trace as constants — no structural key can enumerate them,
  the jaxpr is the ground truth of what would be compiled);
- every entry carries a ``.sha256`` sidecar; a payload that fails
  digest verification (or fails to unpickle/deserialize) is
  **quarantined** (renamed aside, never retried) and the site falls
  back to tracing — counted as
  ``znicz_aot_cache_total{outcome="corrupt"}`` +
  ``znicz_recoveries_total{kind="aotcache_fallback"}``.  The
  ``aotcache.corrupt`` chaos site rots the payload bytes on read to
  drill exactly this path.

Enablement: ``root.common.engine.aot_cache`` — a directory path, or
``True`` (default directory under the snapshots dir), or ``False``
(hard opt-out, beats the environment).  When the config tree carries
no decision, the ``ZNICZ_AOT_CACHE`` environment variable supplies the
directory (the test suite's session fixture and fresh subprocesses use
this: the config tree is reset per test / empty at process start, the
environment survives both).  Unset everywhere = disabled, and every
compile site behaves exactly as it did before this round.

Publication (the fleet path): :func:`publish_programs` packs the
active cache's entries for one bundle architecture into
``<prefix>_v<version>.programs.npz`` (+ ``.sha256``) beside the
published weights; ``PublicationWatcher.poll`` imports a verified pack
into the local cache before surfacing the bundle — a scale-out replica
or hot-swap candidate comes up compile-free.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import threading

import numpy as np

from znicz_tpu.observe import metrics as _metrics
from znicz_tpu.utils.config import root
from znicz_tpu.utils.logger import Logger

__all__ = ["AotCache", "active_cache", "entry_key", "jaxpr_key",
           "program_digest", "build_digest", "config_digest",
           "guard_donated", "publish_programs", "import_programs",
           "status"]

#: default size bound for the store (evicts oldest entries past this)
DEFAULT_MAX_BYTES = 2 << 30

_lock = threading.Lock()
_build_digest: str | None = None
_caches: dict[str, "AotCache"] = {}


# ----------------------------------------------------------------------
# key material
# ----------------------------------------------------------------------
def build_digest() -> str:
    """sha256 over the znicz_tpu package sources, computed once per
    process — two processes agree on a key only when they run the same
    code, so a stale-code hit is impossible."""
    global _build_digest
    if _build_digest is None:
        import znicz_tpu
        pkg = os.path.dirname(os.path.abspath(znicz_tpu.__file__))
        h = hashlib.sha256()
        for base, dirs, files in sorted(os.walk(pkg)):
            dirs.sort()
            for name in sorted(files):
                if not name.endswith(".py"):
                    continue
                path = os.path.join(base, name)
                h.update(os.path.relpath(path, pkg).encode())
                with open(path, "rb") as f:
                    h.update(f.read())
        _build_digest = h.hexdigest()[:16]
    return _build_digest


def platform_fingerprint() -> tuple:
    """(jax version, platform, device kind, device count) — the
    executable's hardware/runtime identity."""
    import jax
    devs = jax.devices()
    return (jax.__version__, devs[0].platform,
            getattr(devs[0], "device_kind", "?"), len(devs))


#: engine keys that never shape a compiled program (control-plane,
#: injection and cache knobs) — excluded so flipping them cannot fork
#: the key space.  Everything else IS included: an unknown new knob
#: then forks the cache (a false miss — safe), never a false hit.
_NONPROGRAM_ENGINE_KEYS = frozenset({
    "aot_cache", "aot_cache_bytes", "faults",
    "publish_fence_timeout_s", "swap_guard_margin",
    "swap_probation_steps", "read_backoff_s",
})


def config_digest() -> str:
    """Digest of the program-relevant config: global knobs (precision
    mode, bf16 activations, fp8 matmul, partition rules, serving
    donation, …) alter what a trace produces, and the test suite
    resets the tree per test — the digest keeps differently-configured
    programs in different entries."""
    def snap(node):
        as_dict = getattr(node, "as_dict", None)
        d = as_dict() if callable(as_dict) else dict(node or {})
        return {str(k): v for k, v in d.items()}

    common = root.common
    payload = {
        "precision": str(common.get("precision_type", "float32")),
        "engine": {k: v for k, v in snap(common.engine).items()
                   if k not in _NONPROGRAM_ENGINE_KEYS},
        "serving": snap(common.serving),
    }
    text = json.dumps(payload, sort_keys=True, default=repr)
    return hashlib.sha256(text.encode()).hexdigest()[:16]


def program_digest(manifest: dict) -> str:
    """Architecture digest of an exported bundle: every
    program-shaping manifest field (layer table + configs, input
    geometry, dtype, kind/sequence/decode metadata, quant key set) —
    but NOT the weight values, which are call-time operands, and NOT
    the volatile quant calibration record, so a recalibrated republish
    of the same architecture still hits."""
    m = json.loads(json.dumps(manifest, default=str, sort_keys=True))
    quant = m.get("quant")
    if isinstance(quant, dict):
        m["quant"] = {k: v for k, v in sorted(quant.items())
                      if not str(k).startswith("calib")}
    text = json.dumps(m, sort_keys=True)
    return hashlib.sha256(text.encode()).hexdigest()[:16]


def _leaf_token(leaf) -> str:
    dtype = getattr(leaf, "dtype", None)
    return (f"{tuple(np.shape(leaf))}:"
            f"{np.dtype(dtype) if dtype is not None else '?'}:"
            f"{getattr(leaf, 'sharding', None)!r}")


def struct_token(structs) -> str:
    """Fingerprint of an operand pytree: tree structure + per-leaf
    shape/dtype/sharding (a NamedSharding's repr carries the mesh
    shape and axis names — the executable is pinned to them)."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(structs)
    return f"{treedef}|" + ";".join(_leaf_token(leaf) for leaf in leaves)


def entry_key(family: str, *, digest: str = "", geometry=(),
              structs=None, donate=False, extra=()) -> str:
    """The content address of one executable: sha256 over every input
    that shapes the compiled program."""
    fields = {
        "family": str(family),
        "digest": str(digest),
        "geometry": [str(g) for g in geometry],
        "structs": "" if structs is None else struct_token(structs),
        "donate": bool(donate),
        "extra": [str(e) for e in extra],
        "platform": [str(p) for p in platform_fingerprint()],
        "build": build_digest(),
        "config": config_digest(),
    }
    text = json.dumps(fields, sort_keys=True)
    return hashlib.sha256(text.encode()).hexdigest()


def jaxpr_key(fn, leaves, extra=()) -> str | None:
    """Key a jit-region program by the hash of its jaxpr.

    Region bodies bake unit hyperparameters (learning rate, momentum,
    dropout ratio, …) into the traced program as literals and closure
    constants — no enumerable structural key can cover them, so the
    key IS the trace: jaxpr text + closure-constant bytes + operand
    avals + the variant/donation tags in ``extra``.  Identical jaxpr
    ⇒ identical compiled program; the hit path therefore still traces
    (to compute the key) but skips the XLA compile — which is where
    nearly all the wall-clock lives.  Returns ``None`` when the
    function cannot be traced or hashed safely (caching is then simply
    skipped for this program)."""
    try:
        import jax
        closed = jax.make_jaxpr(fn)(*leaves)
        h = hashlib.sha256()
        h.update(str(closed.jaxpr).encode())
        for const in closed.consts:
            arr = np.asarray(const)
            h.update(str(arr.dtype).encode())
            h.update(str(arr.shape).encode())
            h.update(arr.tobytes())
        for leaf in leaves:
            h.update(_leaf_token(leaf).encode())
        for e in extra:
            h.update(str(e).encode())
        for p in platform_fingerprint():
            h.update(str(p).encode())
        h.update(build_digest().encode())
        h.update(config_digest().encode())
        return h.hexdigest()
    except Exception:  # noqa: BLE001 — any doubt disables caching
        return None


# ----------------------------------------------------------------------
# the store
# ----------------------------------------------------------------------
class AotCache(Logger):
    """Content-addressed executable store: ``<key>.bin`` (pickled
    ``serialize_executable`` triple) + ``<key>.sha256`` sidecar +
    ``<key>.json`` metadata per entry, plus an advisory
    ``manifest.json`` rollup.  Thread-safe; writes are atomic
    (tmp + rename) so concurrent processes sharing one directory never
    observe a torn entry."""

    def __init__(self, directory: str,
                 max_bytes: int | None = None) -> None:
        super().__init__()
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.max_bytes = int(
            root.common.engine.get("aot_cache_bytes", DEFAULT_MAX_BYTES)
            if max_bytes is None else max_bytes)
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.corrupt = 0
        self.puts = 0

    # -- paths ----------------------------------------------------------
    def _bin(self, key: str) -> str:
        return os.path.join(self.directory, f"{key}.bin")

    def _side(self, key: str) -> str:
        return os.path.join(self.directory, f"{key}.sha256")

    def _meta(self, key: str) -> str:
        return os.path.join(self.directory, f"{key}.json")

    @staticmethod
    def _digest(payload: bytes) -> str:
        return hashlib.sha256(payload).hexdigest()

    @staticmethod
    def _atomic_write(path: str, data: bytes) -> None:
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    # -- observability --------------------------------------------------
    def total_bytes(self) -> int:
        total = 0
        try:
            for name in os.listdir(self.directory):
                if name.endswith(".bin"):
                    total += os.path.getsize(
                        os.path.join(self.directory, name))
        except OSError:
            pass
        return total

    def entries(self) -> list[tuple[str, dict]]:
        """``(key, meta)`` for every complete entry, oldest first."""
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in sorted(names):
            if not name.endswith(".json") or name == "manifest.json":
                continue
            key = name[:-len(".json")]
            path = self._bin(key)
            if not os.path.exists(path):
                continue
            try:
                with open(self._meta(key)) as f:
                    meta = json.load(f)
            except (OSError, ValueError):
                meta = {}
            out.append((key, meta))
        out.sort(key=lambda kv: kv[1].get("seq", 0))
        return out

    def _set_bytes_gauge(self) -> None:
        _metrics.aot_cache_bytes().set(float(self.total_bytes()))

    def _write_manifest(self) -> None:
        rollup = {key: meta for key, meta in self.entries()}
        try:
            self._atomic_write(
                os.path.join(self.directory, "manifest.json"),
                json.dumps(rollup, indent=1, sort_keys=True).encode())
        except OSError:
            pass  # advisory only — entries are self-describing

    # -- the hot paths --------------------------------------------------
    def get(self, key: str, site: str):
        """The deserialized executable for ``key``, or ``None`` (miss
        or quarantined-corrupt — either way the caller traces)."""
        from znicz_tpu.resilience import faults as _faults
        path = self._bin(key)
        try:
            with open(path, "rb") as f:
                payload = f.read()
            with open(self._side(key)) as f:
                want = f.read().strip()
        except OSError:
            _metrics.aot_cache_events(site, "miss").inc()
            with self._lock:
                self.misses += 1
            return None
        if _faults.fire("aotcache.corrupt", at_site=site) is not None:
            # rot the bytes AFTER the sidecar was written — exactly
            # the on-disk corruption digest verification must catch
            mid = len(payload) // 2
            payload = payload[:mid] + b"\xde\xad\xbe\xef" \
                + payload[mid + 4:]
        if self._digest(payload) != want:
            self._quarantine(key, site, "sha256 mismatch")
            return None
        try:
            from jax.experimental import serialize_executable as _se
            ser, in_tree, out_tree = pickle.loads(payload)
            loaded = _se.deserialize_and_load(ser, in_tree, out_tree)
        except Exception as exc:  # noqa: BLE001 — corrupt pickle/exe
            self._quarantine(key, site, f"deserialize failed: {exc}")
            return None
        _metrics.aot_cache_events(site, "hit").inc()
        with self._lock:
            self.hits += 1
        return loaded

    def _quarantine(self, key: str, site: str, reason: str) -> None:
        """A corrupt entry is moved aside (never retried, evidence
        kept) and the site falls back to tracing."""
        self.warning("AOT cache entry %s… quarantined (%s) — falling "
                     "back to tracing", key[:12], reason)
        for path in (self._bin(key), self._side(key), self._meta(key)):
            try:
                os.replace(path, f"{path}.quarantined")
            except OSError:
                pass
        _metrics.aot_cache_events(site, "corrupt").inc()
        _metrics.recoveries("aotcache_fallback").inc()
        from znicz_tpu.observe import recorder as _recorder
        _recorder.record("aotcache_quarantine", key=key[:12],
                         site=site, reason=reason)
        with self._lock:
            self.corrupt += 1
        self._set_bytes_gauge()

    def put(self, key: str, compiled, site: str,
            meta: dict | None = None) -> bool:
        """Serialize + store one compiled executable.  Best-effort: an
        executable this backend cannot serialize just stays uncached
        (the compile already happened — nothing is lost)."""
        try:
            from jax.experimental import serialize_executable as _se
            payload = pickle.dumps(_se.serialize(compiled))
        except Exception as exc:  # noqa: BLE001 — not serializable
            self.debug("AOT cache: executable for site %s not "
                       "serializable (%s)", site, exc)
            return False
        entry = dict(meta or {})
        entry.update({"site": site, "bytes": len(payload),
                      "sha256": self._digest(payload)})
        try:
            with self._lock:
                entry["seq"] = self.puts = self.puts + 1
            self._atomic_write(self._bin(key), payload)
            self._atomic_write(self._side(key),
                               (entry["sha256"] + "\n").encode())
            self._atomic_write(self._meta(key),
                               json.dumps(entry,
                                          sort_keys=True).encode())
        except OSError as exc:
            self.warning("AOT cache write failed for site %s: %s",
                         site, exc)
            return False
        self._trim()
        self._write_manifest()
        self._set_bytes_gauge()
        return True

    def _trim(self) -> None:
        """Size bound: evict oldest entries (by store sequence, mtime
        as the cross-process tiebreak) until under ``max_bytes``."""
        if self.max_bytes <= 0:
            return
        total = self.total_bytes()
        if total <= self.max_bytes:
            return
        for key, _meta in self.entries():
            if total <= self.max_bytes:
                break
            try:
                size = os.path.getsize(self._bin(key))
            except OSError:
                continue
            for path in (self._bin(key), self._side(key),
                         self._meta(key)):
                try:
                    os.remove(path)
                except OSError:
                    pass
            total -= size
            self.debug("AOT cache: evicted %s… (%d bytes, size bound "
                       "%d)", key[:12], size, self.max_bytes)

    # -- publication pack (the fleet path) ------------------------------
    def matching_entries(self, digest: str) -> list[tuple[str, dict]]:
        """Entries whose metadata records this architecture digest."""
        return [(key, meta) for key, meta in self.entries()
                if meta.get("program_digest") == digest]

    def export_pack(self, path: str, digest: str) -> int:
        """Pack every entry for one architecture digest into an
        ``.npz`` (+ ``.sha256`` sidecar) at ``path``; returns the
        entry count (0 = nothing written)."""
        import io
        entries = self.matching_entries(digest)
        if not entries:
            return 0
        arrays = {}
        meta = {}
        for key, entry in entries:
            try:
                with open(self._bin(key), "rb") as f:
                    arrays[f"e_{key}"] = np.frombuffer(
                        f.read(), dtype=np.uint8)
            except OSError:
                continue
            meta[key] = entry
        if not meta:
            return 0
        arrays["pack_meta"] = np.frombuffer(
            json.dumps({"program_digest": digest, "entries": meta},
                       sort_keys=True).encode(), dtype=np.uint8)
        buf = io.BytesIO()
        np.savez_compressed(buf, **arrays)
        self._atomic_write(path, buf.getvalue())
        from znicz_tpu.utils.snapshotter import _sha256_file
        self._atomic_write(f"{path}.sha256",
                           (_sha256_file(path) + "\n").encode())
        return len(meta)

    def import_pack(self, path: str) -> int:
        """Unpack a verified programs pack into this store (per-entry
        digests re-checked; existing keys kept).  Returns entries
        imported.  Raises on a structurally-corrupt pack — the caller
        quarantines the pack and keeps serving (weights are good)."""
        with np.load(path) as pack:
            meta = json.loads(bytes(pack["pack_meta"]).decode())
            imported = 0
            for key, entry in meta["entries"].items():
                if os.path.exists(self._bin(key)):
                    continue
                payload = bytes(pack[f"e_{key}"])
                if self._digest(payload) != entry.get("sha256"):
                    raise ValueError(
                        f"programs pack {path}: entry {key[:12]}… "
                        f"fails its sha256")
                with self._lock:
                    entry["seq"] = self.puts = self.puts + 1
                self._atomic_write(self._bin(key), payload)
                self._atomic_write(self._side(key),
                                   (entry["sha256"] + "\n").encode())
                self._atomic_write(self._meta(key),
                                   json.dumps(entry,
                                              sort_keys=True).encode())
                imported += 1
        if imported:
            self._trim()
            self._write_manifest()
            self._set_bytes_gauge()
        return imported


def guard_donated(loaded, donate_argnums=()):
    """Make a DESERIALIZED executable safe to dispatch with donation.

    Observed on the CPU PJRT backend (jax 0.4.37): a deserialized
    executable that donates a multiply-referenced operand mishandles
    the buffer's ownership — the output that aliases the donated
    input gets freed while still live (non-finite garbage mid-train,
    ``double free or corruption`` at teardown).  Natively-compiled
    programs are immune; only the ``deserialize_and_load`` dispatch
    path double-frees.  Until a chip run validates native aliasing
    (CHIP_QUEUE ``COLDSTART_TPU=1``), donated operands of loaded
    programs are re-owned first: each is passed as a fresh
    single-owner device copy, which the probe matrix shows is
    bitwise-identical to the un-guarded dispatch and stable across
    thousands of steps.  A memcpy per donated leaf per dispatch —
    orders of magnitude below the compile it replaces, but not free:
    set ``engine.aot_cache_alias = "native"`` to dispatch unguarded
    where the runtime is known good."""
    if not donate_argnums:
        return loaded
    if str(root.common.engine.get("aot_cache_alias",
                                  "copy")) == "native":
        return loaded
    import jax
    import jax.numpy as jnp
    donated = frozenset(donate_argnums)

    def call(*args):
        # donated operands may be pytrees (a decode step donates the
        # whole KV-cache tuple) — re-own every leaf
        return loaded(*[
            jax.tree_util.tree_map(jnp.copy, a) if i in donated else a
            for i, a in enumerate(args)])

    return call


# ----------------------------------------------------------------------
# enablement
# ----------------------------------------------------------------------
def active_cache() -> AotCache | None:
    """The process's active store, resolved fresh on every call (the
    config tree is authoritative; the ``ZNICZ_AOT_CACHE`` environment
    variable is the fallback when the tree carries no decision; config
    ``False`` beats everything — the explicit opt-out).  Instances are
    memoized per directory so hit/miss tallies survive re-resolution.
    ``None`` = disabled: every compile site then behaves exactly as it
    did before this round."""
    cfg = root.common.engine.get("aot_cache", None)
    if cfg is False:
        return None
    path = None
    if isinstance(cfg, str):
        path = cfg
    elif cfg is True:
        path = os.environ.get("ZNICZ_AOT_CACHE") or os.path.join(
            str(root.common.dirs.snapshots), "aot_cache")
    elif cfg is None:
        path = os.environ.get("ZNICZ_AOT_CACHE") or None
    if not path:
        return None
    path = os.path.abspath(path)
    with _lock:
        cache = _caches.get(path)
        if cache is None:
            cache = _caches[path] = AotCache(path)
        return cache


def status() -> dict:
    """The ``stats()``/``web_status`` block: enablement, residency and
    this process's verdict tallies (the same numbers the
    ``znicz_aot_cache_total`` series carries)."""
    cache = active_cache()
    if cache is None:
        return {"enabled": False}
    return {
        "enabled": True,
        "dir": cache.directory,
        "entries": len(cache.entries()),
        "bytes": cache.total_bytes(),
        "hits": cache.hits,
        "misses": cache.misses,
        "corrupt": cache.corrupt,
    }


# ----------------------------------------------------------------------
# publication glue (round-13 sidecar machinery grows a programs pack)
# ----------------------------------------------------------------------
def _pack_path(bundle_path: str) -> str:
    base = bundle_path[:-len(".npz")] \
        if bundle_path.endswith(".npz") else bundle_path
    return f"{base}.programs.npz"


def publish_programs(directory: str, prefix: str, version: int,
                     bundle_path: str) -> int:
    """Publish the active cache's programs for ``bundle_path``'s
    architecture as ``<prefix>_v<version>.programs.npz``.  When the
    local cache holds nothing for this architecture, the previous
    version's pack is carried forward (weights-only refreshes keep
    their programs without the trainer ever compiling serving
    programs).  Returns entries packed (0 = no pack written) —
    best-effort: a publish never fails because programs could not be
    packed."""
    cache = active_cache()
    if cache is None:
        return 0
    try:
        from znicz_tpu.export import read_bundle
        manifest, _params = read_bundle(bundle_path)
        digest = program_digest(manifest)
        pack = _pack_path(bundle_path)
        n = cache.export_pack(pack, digest)
        if n:
            return n
        # carry the previous version's pack forward when its
        # architecture still matches
        prev = os.path.join(
            directory, f"{prefix}_v{version - 1:06d}.programs.npz")
        if version > 1 and os.path.exists(prev):
            with np.load(prev) as old:
                meta = json.loads(bytes(old["pack_meta"]).decode())
            if meta.get("program_digest") == digest:
                with open(prev, "rb") as f:
                    AotCache._atomic_write(pack, f.read())
                with open(f"{prev}.sha256") as f:
                    AotCache._atomic_write(
                        f"{pack}.sha256", f.read().encode())
                return len(meta.get("entries", {}))
    except Exception as exc:  # noqa: BLE001 — packing is best-effort
        import logging
        logging.getLogger("aot_cache").warning(
            "programs pack for v%d not published: %s", version, exc)
    return 0


def import_programs(bundle_path: str) -> int:
    """Import the programs pack published beside ``bundle_path`` into
    the active cache (digest-verified; corrupt packs are rejected with
    the fallback counted — the weights are untouched and still serve).
    Returns entries imported."""
    cache = active_cache()
    pack = _pack_path(bundle_path)
    if cache is None or not os.path.exists(pack):
        return 0
    try:
        from znicz_tpu.utils.snapshotter import (SnapshotCorrupt,
                                                 _sha256_file)
        sidecar = f"{pack}.sha256"
        if not os.path.exists(sidecar):
            raise SnapshotCorrupt(f"{pack}: no sha256 sidecar")
        with open(sidecar) as f:
            want = f.read().strip()
        got = _sha256_file(pack)
        if got != want:
            raise SnapshotCorrupt(
                f"{pack}: sha256 {got[:12]}… != sidecar {want[:12]}…")
        return cache.import_pack(pack)
    except Exception as exc:  # noqa: BLE001 — corrupt pack
        import logging
        logging.getLogger("aot_cache").warning(
            "programs pack rejected (%s) — serving will trace", exc)
        _metrics.snapshot_failures("programs").inc()
        _metrics.aot_cache_events("publish", "corrupt").inc()
        _metrics.recoveries("aotcache_fallback").inc()
        return 0
