"""Disaggregated decode serving: independent prefill and decode pools
with a page-table handoff (round 22, ROADMAP item 4).

The fused :class:`~znicz_tpu.serving.decode.DecodeEngine` runs
admission (compute-bound bucketed prefill) and the token loop
(memory-bound paged decode) on ONE scheduler thread: a burst of
prompts runs whole admission waves *between* token steps, so every
in-flight sequence's inter-token latency absorbs the burst.  DistServe
(arXiv:2401.09670) and Splitwise (arXiv:2311.18677) measure the same
interference at datacenter scale and reach the same design: split the
two phases into separately-scaled replica pools and ship the KV cache
from prefill to decode.

:class:`DisaggEngine` is that split, TPU-native:

- **One warmed** :class:`~znicz_tpu.serving.decode.DecodeModel` is
  shared by every worker in both pools.  Programs are pure functions
  of the cache operands, so each pool replica owns a private
  same-geometry :class:`~znicz_tpu.serving.decode.PagedKVCache`
  (:meth:`DecodeModel.make_cache`) and dispatches through the SAME
  compiled program families — **pool scale-up compiles nothing**
  (``znicz_xla_compiles_total`` stays flat, the round-12 retrace
  guard extended to fleets of caches).
- **Prefill workers** (the prefill :class:`ReplicaGroup`) pop prompts
  from the shared queue, run the bucketed prompt programs into their
  private cache — with their own prefix trie + host-DRAM spill tier,
  so the shareable working set survives past HBM — sample the first
  token (TTFT stamps here, same admission-eligible clock as the fused
  engine), then EXPORT the prompt's K/V pages (+ LSTM carry rows) to
  host memory and hand off.
- **The handoff** is the contract cross-host disaggregation needs and
  same-process disaggregation can already exercise: page payloads +
  first token + sampling state travel as host arrays, land in a
  decode worker's cache through the pinned staging ring
  (``memory.PageStager``) and the warmed ``page_in`` scatter, and the
  token budget reservation rides along (released exactly once at the
  decode end).  ``GRAFT_CHAOS=1`` drops handoffs in transit
  (``disagg.handoff_drop``): the request retries on a fresh prefill
  worker (prefix-hit, so the retry is cheap) with pages reclaimed and
  the budget still balanced.
- **Decode workers** accept handoffs between token steps (bounded by
  their free slots), reserve the full worst-case span up front
  (fresh private pages — handed-off content is COPIED in, never
  shared across caches), and run the continuous token loop exactly
  like the fused engine's ``_step``.

Per-pool telemetry: ``znicz_serving_queue_age_seconds{pool=prefill}``
is the shared prompt queue's head age (scales the prefill pool),
``{pool=decode}`` is the oldest unaccepted handoff (scales the decode
pool) — :class:`~znicz_tpu.serving.fleet.PoolAutoscaler` reads both
and grows/shrinks each pool independently.  Handoff traffic lands on
``znicz_kv_page_migrations_total{direction=handoff}`` next to the
spill tier's ``spill``/``restore``.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from concurrent.futures import Future

import numpy as np

from znicz_tpu.observe import metrics as _metrics
from znicz_tpu.resilience import faults as _faults
from znicz_tpu.serving.batcher import (DeadlineExceeded, Overloaded,
                                       QueueFull, TokenBudget)
from znicz_tpu.serving.decode import (DecodeModel, PoolExhausted,
                                      PrefixCache, _Live,
                                      _PageSetupMixin, _PromptReq)
from znicz_tpu.serving.fleet import ReplicaGroup
from znicz_tpu.utils.logger import Logger

__all__ = ["DisaggEngine", "Handoff"]

#: distinguishes same-named engines in the registry's labels
_DISAGG_SEQ = itertools.count()


class _DisaggReq(_PromptReq):
    """A queued prompt plus its handoff retry ledger."""

    __slots__ = ("handoff_retries",)

    def __init__(self, *args, **kwargs) -> None:
        super().__init__(*args, **kwargs)
        self.handoff_retries = 0


class Handoff:
    """One prefill→decode transfer: the request, its first sampled
    token, the prompt blocks' K/V pages as HOST arrays (one list of
    per-pool arrays per block — the cross-host wire format), and the
    LSTM carry rows when the chain has any.  Host arrays, not device
    references: the payload must outlive the prefill worker's cache
    (its pages are released the moment the export lands) and must be
    shippable over a heartbeat channel later."""

    __slots__ = ("req", "first_token", "pages", "carries")

    def __init__(self, req: _DisaggReq, first_token: int,
                 pages: list, carries: list | None) -> None:
        self.req = req
        self.first_token = int(first_token)
        self.pages = pages
        self.carries = carries

    @property
    def n_pages(self) -> int:
        return len(self.pages)


class _PrefillWorker(_PageSetupMixin, Logger):
    """One prefill-pool replica: private cache + prefix trie + spill
    tier, serving one prompt at a time off the parent's shared queue.
    No ``__slots__``: :class:`ReplicaGroup` assigns replica identity
    attributes."""

    def __init__(self, parent: "DisaggEngine", wid: int) -> None:
        super().__init__()
        self.parent = parent
        self.wid = wid
        self.model = parent.model
        self.cache = parent.model.make_cache()
        self._obs_id = parent._obs_id
        self.prefix = (PrefixCache(parent.model.page_tokens)
                       if parent.prefix_cache_enabled else None)
        self._spill = None
        if self.prefix is not None and parent.spill_pages > 0:
            from znicz_tpu.memory import HostPageTier
            self._spill = HostPageTier(parent.model.page_shapes(),
                                       parent.spill_pages)
        # pool workers feed the ENGINE's canonical children — the
        # fleet reads one engine id, not one per replica
        self._m_prefix_hit = parent._m_prefix_hit
        self._m_prefix_miss = parent._m_prefix_miss
        self._m_tok_shared = parent._m_tok_shared
        self._m_tok_computed = parent._m_tok_computed
        self._m_mig_spill = parent._m_mig_spill
        self._m_mig_restore = parent._m_mig_restore
        self.served = 0
        self.breaker_state = "closed"
        self._stop = False
        self._thread: threading.Thread | None = None

    def _kv_cache(self):
        return self.cache

    def start(self) -> "_PrefillWorker":
        self._thread = threading.Thread(
            target=self._loop, name=f"prefill-w{self.wid}",
            daemon=True)
        self._thread.start()
        return self

    def shutdown(self, timeout: float = 30.0) -> None:
        with self.parent._cond:
            self._stop = True
            self.parent._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        if self._spill is not None:
            self._spill.shutdown()

    def _loop(self) -> None:
        parent = self.parent
        while True:
            with parent._cond:
                while not self._stop and not parent._prefill_q:
                    parent._cond.wait(0.05)
                if not parent._prefill_q:
                    if self._stop:
                        return  # drained: a shrink loses no request
                    continue
                req = parent._prefill_q.popleft()
            now = time.monotonic()
            if req.expired(now):
                # TTFT deadline passed while queued: fail fast, the
                # prompt never costs a prefill
                parent._refund(req)
                parent._m_rejected.inc()
                req.trace.event("deadline_evicted",
                                engine=parent._obs_id)
                parent._finish_trace(req, "expired")
                waited_ms = (now - req.t_submit - req.pause_s) * 1e3
                if not req.future.done():
                    req.future.set_exception(DeadlineExceeded(
                        f"TTFT deadline passed after {waited_ms:.0f}"
                        f"ms in the prefill queue"))
                continue
            self._serve(req)

    def _serve(self, req: _DisaggReq) -> None:
        parent = self.parent
        model = self.model
        cache = self.cache
        parent._end_phase(req, "queue")
        req.trace.phase_begin("prefill")
        slot = cache.acquire()
        try:
            # prompt blocks only (max_new=0): the decode worker owns
            # the generation span's reservation — page pressure here
            # is prefix-trie pressure, absorbed by spill + eviction
            matched = self._setup_pages(slot, req.tokens, 0)
            logits = model.run_prefill(req.tokens[matched:], slot,
                                       matched, cache=cache)
        except Exception as exc:  # noqa: BLE001 — isolate the prompt
            cache.release_slot_pages(slot)
            cache.release(slot)
            parent._refund(req)
            parent._m_rejected.inc()
            parent._finish_trace(req, "failed")
            self.warning("prefill failed: %s", exc)
            if not req.future.done():
                req.future.set_exception(exc)
            return
        if self.prefix is not None:
            self.prefix.insert(req.tokens, cache.tables[slot], cache)
        token = parent._sample(logits, self._rng())
        parent._end_phase(req, "prefill", tokens=req.n,
                          worker=self.wid)
        ttft = time.monotonic() - req.t_submit - req.pause_s
        req.future.ttft_s = ttft
        parent._m_ttft.observe(ttft)
        parent._ttft_win.append(ttft)
        parent._m_tok_prompt.inc(req.n)
        parent._m_tok_gen.inc()
        self.served += 1
        if (parent.eos_token is not None
                and token == parent.eos_token) or req.max_new <= 1:
            cache.release_slot_pages(slot)
            cache.release(slot)
            parent._refund(req)
            parent._m_served.inc()
            parent._finish_trace(req, "ok")
            if not req.future.done():
                req.future.set_result(np.asarray([token], np.int32))
            return
        # export the prompt's K/V to host arrays — the handoff
        # payload — then drop this cache's references (trie pins
        # keep shareable blocks resident for the NEXT prompt)
        # (the handoff phase opens HERE and, idempotently, survives a
        # dropped-handoff retry: the retried prefill re-begins its own
        # phase but the handoff span keeps the FIRST begin, so the
        # whole retry loop is charged to the hop that lost the payload)
        req.trace.phase_begin("handoff")
        nblocks = -(-req.n // model.page_tokens)
        pages = [model.export_page(int(cache.tables[slot, b]),
                                   cache=cache)
                 for b in range(nblocks)]
        carries = (model.export_carry(slot, cache=cache)
                   if model.has_lstm else None)
        cache.release_slot_pages(slot)
        cache.release(slot)
        parent._route_handoff(Handoff(req, token, pages, carries))

    def _rng(self):
        return self.parent._worker_rng(self.wid)


class _DecodeWorker(Logger):
    """One decode-pool replica: private cache, an inbox of pending
    handoffs, and the continuous token loop.  Handoffs are accepted
    between steps, bounded by free slots — exactly the fused engine's
    admission point, minus the prefill work."""

    def __init__(self, parent: "DisaggEngine", wid: int) -> None:
        super().__init__()
        self.parent = parent
        self.wid = wid
        self.model = parent.model
        self.cache = parent.model.make_cache()
        self.inbox: deque = deque()
        self._live: list[_Live] = []
        self.served = 0
        self.breaker_state = "closed"
        self._stop = False
        self._thread: threading.Thread | None = None

    def start(self) -> "_DecodeWorker":
        self._thread = threading.Thread(
            target=self._loop, name=f"decode-w{self.wid}",
            daemon=True)
        self._thread.start()
        return self

    def shutdown(self, timeout: float = 30.0) -> None:
        with self.parent._cond:
            self._stop = True
            self.parent._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    def inbox_age(self) -> float:
        """Age of the oldest unaccepted handoff (the decode pool's
        scaling signal) — racy peek, scrape-tolerant."""
        try:
            h = self.inbox[0]
        except IndexError:
            return 0.0
        return max(0.0, time.monotonic() - h.req.t_submit
                   - h.req.pause_s)

    def _loop(self) -> None:
        parent = self.parent
        while True:
            intake: list[Handoff] = []
            with parent._cond:
                if not self.inbox and not self._live:
                    if self._stop:
                        return
                    parent._cond.wait(0.05)
                free = self.cache.free_slots
                while self.inbox and len(intake) < free:
                    intake.append(self.inbox.popleft())
            for h in intake:
                self._accept(h)
            if self._live:
                self._step()

    def _accept(self, h: Handoff) -> None:
        """Land one handoff: reserve the FULL worst-case span in
        fresh private pages (handed-off content is copied, never
        shared across caches), upload the payload through the staging
        ring, and join the live batch."""
        parent = self.parent
        model = self.model
        cache = self.cache
        req = h.req
        slot = cache.acquire()
        span = min(req.n + req.max_new, model.max_t)
        nblocks = -(-span // model.page_tokens)
        try:
            for b in range(nblocks):
                cache.new_block(slot, b)
        except PoolExhausted:
            cache.release_slot_pages(slot)
            cache.release(slot)
            with parent._cond:
                if self._live:
                    # draining lanes will free pages: retry next tick
                    self.inbox.appendleft(h)
                    return
            # an empty cache cannot hold it — ever
            parent._refund(req)
            parent._m_rejected.inc()
            parent._finish_trace(req, "failed")
            if not req.future.done():
                req.future.set_exception(PoolExhausted(
                    f"handoff of {req.n} prompt tokens cannot fit "
                    f"the decode pool ({cache.pool_pages} pages)"))
            return
        for b, pages in enumerate(h.pages):
            dev = parent._stager.upload(pages)
            model.page_in(dev, int(cache.tables[slot, b]),
                          cache=cache)
        if h.carries is not None:
            rows = parent._carry_stager.upload(h.carries)
            model.carry_in(rows, slot, cache=cache)
        parent._m_mig_handoff.inc(h.n_pages)
        parent._end_phase(req, "handoff", pages=h.n_pages,
                          worker=self.wid,
                          retries=req.handoff_retries)
        req.trace.phase_begin("decode")
        self._live.append(_Live(req, slot, h.first_token))

    def _finish(self, s: _Live) -> None:
        self.cache.release_slot_pages(s.slot)
        self.cache.release(s.slot)
        parent = self.parent
        parent._refund(s.req)
        parent._m_served.inc()
        self.served += 1
        parent._end_phase(s.req, "decode",
                          tokens=len(s.generated))
        parent._finish_trace(s.req, "ok")
        if not s.req.future.done():
            s.req.future.set_result(
                np.asarray(s.generated, np.int32))

    def _fail(self, s: _Live, exc: Exception) -> None:
        self.cache.release_slot_pages(s.slot)
        self.cache.release(s.slot)
        self.parent._refund(s.req)
        self.parent._finish_trace(s.req, "failed")
        if not s.req.future.done():
            s.req.future.set_exception(exc)

    def _step(self) -> None:
        parent = self.parent
        live = self._live
        tokens = np.asarray([s.generated[-1] for s in live], np.int32)
        slots = np.asarray([s.slot for s in live], np.int32)
        positions = np.asarray([s.pos for s in live], np.int32)
        try:
            logits = self.model.run_decode(tokens, slots, positions,
                                           cache=self.cache)
        except Exception as exc:  # noqa: BLE001 — the step is shared
            self.warning("decode step failed for %d lanes: %s",
                         len(live), exc)
            for s in live:
                self._fail(s, exc)
            self._live = []
            return
        now = time.monotonic()
        rng = parent._worker_rng(self.wid)
        still: list[_Live] = []
        for i, s in enumerate(live):
            token = parent._sample(logits[i], rng)
            dt = now - s.t_last
            s.t_last = now
            s.pos += 1
            s.generated.append(int(token))
            parent._m_token.observe(dt)
            parent._token_win.append(dt)
            parent._m_tok_gen.inc()
            if ((parent.eos_token is not None
                 and int(token) == parent.eos_token)
                    or len(s.generated) >= s.req.max_new
                    or s.pos >= self.model.max_t):
                self._finish(s)
            else:
                still.append(s)
        self._live = still


class DisaggEngine(Logger):
    """Prefill/decode-disaggregated token server (round 22).

    Same request contract as :class:`DecodeEngine` (``submit`` →
    future of generated ids, ``generate`` sync, greedy arms
    token-identical to the numpy oracle), different data plane: a
    prefill :class:`ReplicaGroup` and a decode :class:`ReplicaGroup`
    over ONE warmed :class:`DecodeModel`, joined by host-array page
    handoffs.  See the module docstring for the design; knobs:

    - ``prefill_replicas`` / ``decode_replicas`` — initial pool
      sizes (``max_*_replicas`` bound the autoscaler);
    - ``spill_pages`` — per-prefill-worker host-DRAM tier capacity
      (``engine.kv_spill_pages``; 0 disables the tier);
    - ``handoff_retry_budget`` — dropped-handoff retries before the
      request fails (the chaos site ``disagg.handoff_drop``);
    - ``autoscale`` — run a :class:`~znicz_tpu.serving.fleet.
      PoolAutoscaler` on a maintenance thread, growing each pool
      independently from its ``znicz_serving_queue_age_seconds``
      child.
    """

    def __init__(self, model, *, prefill_replicas: int = 1,
                 decode_replicas: int = 1,
                 max_prefill_replicas: int = 4,
                 max_decode_replicas: int = 4,
                 max_slots: int = 4, max_t: int = 64,
                 max_prompt: int | None = None,
                 prompt_align: int = 8,
                 max_new_tokens: int = 32,
                 eos_token: int | None = None,
                 temperature: float = 0.0, seed: int = 0,
                 max_queue: int = 256,
                 page_tokens: int | None = None,
                 pool_tokens: int | None = None,
                 prefix_cache: bool | None = None,
                 spill_pages: int | None = None,
                 max_queue_tokens: int | None = None,
                 handoff_retry_budget: int = 1,
                 autoscale: bool = False,
                 queue_age_up_s: float = 0.25,
                 idle_down_s: float = 5.0,
                 device=None) -> None:
        super().__init__()
        from znicz_tpu.utils.config import root
        if not isinstance(model, DecodeModel):
            model = DecodeModel(model, max_slots=max_slots,
                                max_t=max_t, max_prompt=max_prompt,
                                prompt_align=prompt_align,
                                device=device, paged=True,
                                page_tokens=page_tokens,
                                pool_tokens=pool_tokens, spec_k=0)
        if not model.paged:
            raise ValueError(
                "disaggregation needs the paged cache: the handoff "
                "ships pages, a flat cache has none")
        self.model = model
        if prefix_cache is None:
            prefix_cache = bool(root.common.engine.get(
                "prefix_cache", True))
        self.prefix_cache_enabled = bool(
            prefix_cache and not model.has_lstm)
        if spill_pages is None:
            spill_pages = int(root.common.engine.get(
                "kv_spill_pages", 0))
        self.spill_pages = int(spill_pages)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token = eos_token
        self.temperature = float(temperature)
        self.seed = int(seed)
        self.max_queue = int(max_queue)
        self.handoff_retry_budget = max(0, int(handoff_retry_budget))
        budget = (int(max_queue_tokens) if max_queue_tokens
                  else 16 * model.pool_tokens)
        self._token_budget = TokenBudget(budget)
        wf_name = model.model.manifest.get("workflow", "model")
        self._obs_id = f"{wf_name}#disagg{next(_DISAGG_SEQ)}"
        self._m_submitted = _metrics.serving_requests(
            self._obs_id, "submitted")
        self._m_served = _metrics.serving_requests(self._obs_id,
                                                   "served")
        self._m_rejected = _metrics.serving_requests(self._obs_id,
                                                     "rejected")
        self._m_ttft = _metrics.serving_ttft_seconds(self._obs_id)
        self._m_token = _metrics.serving_token_seconds(self._obs_id)
        self._m_tok_prompt = _metrics.serving_tokens(self._obs_id,
                                                     "prompt")
        self._m_tok_gen = _metrics.serving_tokens(self._obs_id,
                                                  "generated")
        self._m_prefix_hit = _metrics.prefix_cache_events(
            self._obs_id, "hit")
        self._m_prefix_miss = _metrics.prefix_cache_events(
            self._obs_id, "miss")
        self._m_tok_shared = _metrics.prefix_tokens(self._obs_id,
                                                    "shared")
        self._m_tok_computed = _metrics.prefix_tokens(self._obs_id,
                                                      "computed")
        self._m_mig_spill = _metrics.kv_page_migrations(
            self._obs_id, "spill")
        self._m_mig_restore = _metrics.kv_page_migrations(
            self._obs_id, "restore")
        self._m_mig_handoff = _metrics.kv_page_migrations(
            self._obs_id, "handoff")
        _metrics.kv_spill_pages(self._obs_id).set_function(
            self._spill_used)
        _metrics.serving_queue_age_seconds(
            self._obs_id, pool="prefill").set_function(
                self._prefill_queue_age)
        _metrics.serving_queue_age_seconds(
            self._obs_id, pool="decode").set_function(
                self._decode_queue_age)
        self._ttft_win: deque = deque(maxlen=4096)
        self._token_win: deque = deque(maxlen=4096)
        # per-phase latency windows behind the canonical
        # znicz_phase_p99_seconds gauges: the request trace's
        # phase_end() is the ONE measurement both the span tree and
        # these gauges report (round 24)
        self._phase_win: dict[str, deque] = {
            p: deque(maxlen=4096)
            for p in ("queue", "prefill", "handoff", "decode")}
        for _p, _win in self._phase_win.items():
            _metrics.phase_p99_seconds(self._obs_id, _p).set_function(
                lambda w=_win: _metrics.window_p99(w))
        _metrics.phase_p99_seconds(self._obs_id, "ttft").set_function(
            lambda w=self._ttft_win: _metrics.window_p99(w))
        _metrics.phase_p99_seconds(self._obs_id, "token").set_function(
            lambda w=self._token_win: _metrics.window_p99(w))
        self._federator = None
        self._prefill_q: deque = deque()
        self._cond = threading.Condition()
        self._rng_lock = threading.Lock()
        self._rngs: dict[int, np.random.Generator] = {}
        self.handoffs_total = 0
        self.handoff_drops = 0
        self.handoff_retries_total = 0
        self.warmup_compiles = 0
        self.warmup_seconds = 0.0
        self._started = False
        self._wid = itertools.count()
        self.prefill_pool = ReplicaGroup(
            self._obs_id, "prefill", "v0",
            lambda: _PrefillWorker(self, next(self._wid)),
            target=int(prefill_replicas),
            max_replicas=int(max_prefill_replicas))
        self.decode_pool = ReplicaGroup(
            self._obs_id, "decode", "v0",
            lambda: _DecodeWorker(self, next(self._wid)),
            target=int(decode_replicas),
            max_replicas=int(max_decode_replicas))
        self._stager = None
        self._carry_stager = None
        self._autoscaler = None
        self._maint: threading.Thread | None = None
        self._maint_stop = threading.Event()
        if autoscale:
            from znicz_tpu.serving.fleet import PoolAutoscaler
            self._autoscaler = PoolAutoscaler(
                {"prefill": self.prefill_pool,
                 "decode": self.decode_pool},
                self._obs_id, queue_age_up_s=queue_age_up_s,
                idle_down_s=idle_down_s)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> "DisaggEngine":
        if self._started:
            return self
        from znicz_tpu.memory import PageStager
        t0 = time.monotonic()
        # ONE warmup serves both pools: every worker cache is
        # geometry-identical, so the program dicts are shared and a
        # later scale-up compiles nothing
        self.warmup_compiles = self.model.warmup(
            prefix_cache=self.prefix_cache_enabled, page_io=True)
        self.warmup_seconds = time.monotonic() - t0
        self._stager = PageStager(self.model.page_shapes())
        if self.model.has_lstm:
            self._carry_stager = PageStager(self.model.carry_shapes())
        self._started = True
        if _metrics.enabled() and self._federator is None:
            # the disagg maintenance thread doubles as the gang's
            # metrics folder: one in-process source re-labels this
            # engine's series under {process, pool} fed children
            from znicz_tpu.observe.federation import Federator
            self._federator = Federator(self._obs_id)
            self._federator.add_registry(
                "self",
                pool_of=lambda eng: ("" if eng == self._obs_id
                                     else None))
        self.prefill_pool.scale_to(self.prefill_pool.target,
                                   reason="start")
        self.decode_pool.scale_to(self.decode_pool.target,
                                  reason="start")
        if self._autoscaler is not None or self._federator is not None:
            self._maint_stop.clear()
            self._maint = threading.Thread(
                target=self._maintenance, name="disagg-maint",
                daemon=True)
            self._maint.start()
        self.info(
            "disagg '%s': %d AOT programs warmed in %.2fs, pools "
            "prefill:%d + decode:%d (slots=%d/cache, page_tokens=%d, "
            "prefix_cache=%s, spill_pages=%d/prefill-worker)",
            self.model.model.manifest.get("workflow", "?"),
            self.warmup_compiles, self.warmup_seconds,
            self.prefill_pool.live(), self.decode_pool.live(),
            self.model.max_slots, self.model.page_tokens,
            self.prefix_cache_enabled, self.spill_pages)
        return self

    def shutdown(self, timeout: float = 60.0) -> None:
        """Drain both pools in dataflow order: prefill workers finish
        the prompt queue (routing their handoffs), then decode
        workers finish every inbox and live lane."""
        if self._maint is not None:
            self._maint_stop.set()
            self._maint.join(timeout=10.0)
            self._maint = None
        targets = (self.prefill_pool.target, self.decode_pool.target)
        self.prefill_pool.scale_to(0, reason="shutdown")
        self.decode_pool.scale_to(0, reason="shutdown")
        # a later start() restores the configured pool sizes
        self.prefill_pool.target, self.decode_pool.target = targets
        if self._stager is not None:
            self._stager.shutdown()
            self._stager = None
        if self._carry_stager is not None:
            self._carry_stager.shutdown()
            self._carry_stager = None
        if self._federator is not None:
            self._federator.close()
            self._federator = None
        self._started = False

    def __enter__(self) -> "DisaggEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()

    def _maintenance(self) -> None:
        while not self._maint_stop.wait(0.05):
            if self._autoscaler is not None:
                self._autoscaler.tick()
            if self._federator is not None:
                self._federator.scrape()

    # ------------------------------------------------------------------
    # request path
    # ------------------------------------------------------------------
    def submit(self, prompt, *, max_new_tokens: int | None = None,
               deadline_ms: float | None = None) -> Future:
        """Enqueue a prompt; returns a future of the generated ids
        (first sampled token onward) — the :class:`DecodeEngine`
        contract.  Token-denominated admission: the queue is bounded
        by the work it holds, and the reservation travels WITH the
        request across the handoff (released exactly once wherever
        the request exits)."""
        if not self._started:
            raise RuntimeError("engine not started")
        tokens = np.ascontiguousarray(prompt, np.int32).reshape(-1)
        if tokens.shape[0] < 1:
            raise ValueError("empty prompt")
        if tokens.shape[0] > self.model.max_prompt:
            raise ValueError(
                f"prompt of {tokens.shape[0]} tokens exceeds "
                f"max_prompt {self.model.max_prompt}")
        req = _DisaggReq(tokens,
                         max_new_tokens if max_new_tokens is not None
                         else self.max_new_tokens, deadline_ms)
        with self._cond:
            if len(self._prefill_q) >= self.max_queue:
                self._m_rejected.inc()
                self._finish_trace(req, "shed")
                raise QueueFull(
                    f"prefill queue full ({len(self._prefill_q)} "
                    f"prompts pending, limit {self.max_queue})")
            want = req.n + req.max_new
            if not self._token_budget.try_acquire(want):
                self._m_rejected.inc()
                self._finish_trace(req, "shed")
                raise QueueFull(
                    f"token budget full ({self._token_budget.used} "
                    f"of {self._token_budget.capacity} tokens held; "
                    f"request wants {want})")
            req.charged = want
            self._prefill_q.append(req)
            self._cond.notify_all()
        self._m_submitted.inc()
        return req.future

    def generate(self, prompt, timeout: float | None = None,
                 **kwargs) -> np.ndarray:
        return self.submit(prompt, **kwargs).result(timeout=timeout)

    def _refund(self, req: _PromptReq) -> None:
        if req.charged:
            self._token_budget.release(req.charged)
            req.charged = 0

    def _end_phase(self, req: _PromptReq, phase: str,
                   **args) -> float:
        """Close one trace phase; the SAME measurement feeds the
        windowed-p99 gauge family (round 24)."""
        dur = req.trace.phase_end(phase, engine=self._obs_id, **args)
        if dur > 0.0:
            win = self._phase_win.get(phase)
            if win is not None:
                win.append(dur)
        return dur

    def _finish_trace(self, req: _PromptReq, outcome: str) -> None:
        _metrics.trace_requests(self._obs_id, outcome).inc()
        req.trace.finish(outcome)

    def _sample(self, logits: np.ndarray,
                rng: np.random.Generator) -> int:
        if self.temperature <= 0:
            return int(np.argmax(logits))
        z = logits / self.temperature
        z = z - z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(rng.choice(len(p), p=p))

    def _worker_rng(self, wid: int) -> np.random.Generator:
        with self._rng_lock:
            rng = self._rngs.get(wid)
            if rng is None:
                rng = np.random.default_rng(self.seed * 1009 + wid)
                self._rngs[wid] = rng
            return rng

    # ------------------------------------------------------------------
    # the handoff (prefill worker thread → decode worker inbox)
    # ------------------------------------------------------------------
    def _route_handoff(self, h: Handoff) -> None:
        req = h.req
        if _faults.fire("disagg.handoff_drop") is not None:
            # the payload is lost in transit: the prefill worker
            # already released its pages, so recovery = redo the
            # prefill (a prefix HIT now — its trie kept the blocks)
            self.handoff_drops += 1
            req.trace.event("handoff_drop", engine=self._obs_id,
                            retries=req.handoff_retries)
            if req.handoff_retries >= self.handoff_retry_budget:
                self._refund(req)
                self._m_rejected.inc()
                self._finish_trace(req, "failed")
                if not req.future.done():
                    req.future.set_exception(_faults.FaultInjected(
                        f"handoff dropped {req.handoff_retries + 1} "
                        f"times (retry budget "
                        f"{self.handoff_retry_budget})"))
                return
            req.handoff_retries += 1
            self.handoff_retries_total += 1
            _metrics.recoveries("handoff_retry").inc()
            self.warning(
                "handoff dropped (chaos) — retrying prompt of %d "
                "tokens on a fresh prefill (%d/%d)", req.n,
                req.handoff_retries, self.handoff_retry_budget)
            with self._cond:
                # front of the queue: the reservation is still held,
                # the work is still pending (round-16 retry contract)
                self._prefill_q.appendleft(req)
                self._cond.notify_all()
            return
        worker = self.decode_pool.pick()
        if worker is None:
            self._refund(req)
            self._m_rejected.inc()
            self._finish_trace(req, "failed")
            if not req.future.done():
                req.future.set_exception(Overloaded(
                    "no live decode replica to accept the handoff"))
            return
        with self._cond:
            worker.inbox.append(h)
            self.handoffs_total += 1
            self._cond.notify_all()

    # ------------------------------------------------------------------
    # telemetry
    # ------------------------------------------------------------------
    def _prefill_queue_age(self) -> float:
        try:
            req = self._prefill_q[0]
        except IndexError:
            return 0.0
        return max(0.0, time.monotonic() - req.t_submit - req.pause_s)

    def _decode_queue_age(self) -> float:
        return max((w.inbox_age()
                    for w in self.decode_pool.engines()),
                   default=0.0)

    def _spill_used(self) -> int:
        return sum(w._spill.used for w in self.prefill_pool.engines()
                   if getattr(w, "_spill", None) is not None)

    @property
    def breaker_state(self) -> str:
        return "closed"

    def ready(self) -> bool:
        return bool(self._started and self.prefill_pool.live()
                    and self.decode_pool.live())

    def balanced(self) -> bool:
        """Exactly-once accounting across submit → prefill → handoff
        → decode: true when idle with every reservation returned."""
        return self._token_budget.balanced()

    def stats(self) -> dict:
        from znicz_tpu.serving.engine import _percentile

        def window(win):
            vals = sorted(win)
            if not vals:
                return {}
            return {"p50": round(1e3 * _percentile(vals, 50), 3),
                    "p95": round(1e3 * _percentile(vals, 95), 3),
                    "p99": round(1e3 * _percentile(vals, 99), 3),
                    "mean": round(1e3 * sum(vals) / len(vals), 3),
                    "window": len(vals)}

        return {
            "engine": "decode-disagg",
            "pools": {
                "prefill": {"live": self.prefill_pool.live(),
                            "target": self.prefill_pool.target,
                            "queue_age_s": round(
                                self._prefill_queue_age(), 4)},
                "decode": {"live": self.decode_pool.live(),
                           "target": self.decode_pool.target,
                           "queue_age_s": round(
                               self._decode_queue_age(), 4)},
            },
            "handoffs": {
                "total": self.handoffs_total,
                "dropped": self.handoff_drops,
                "retried": self.handoff_retries_total,
                "pages_moved": int(self._m_mig_handoff.value),
            },
            "prefix_cache": ({
                "hits": int(self._m_prefix_hit.value),
                "misses": int(self._m_prefix_miss.value),
                "shared_tokens": int(self._m_tok_shared.value),
                "computed_tokens": int(self._m_tok_computed.value),
                "spill_pages_used": self._spill_used(),
                "spill_capacity": self.spill_pages,
                "migrations": {
                    "spill": int(self._m_mig_spill.value),
                    "restore": int(self._m_mig_restore.value),
                },
            } if self.prefix_cache_enabled else None),
            "programs_compiled": self.model.compile_count,
            "warmup_seconds": round(self.warmup_seconds, 3),
            "submitted": int(self._m_submitted.value),
            "served": int(self._m_served.value),
            "rejected": int(self._m_rejected.value),
            "queued_prompts": len(self._prefill_q),
            "ttft_ms": window(self._ttft_win),
            "token_ms": window(self._token_win),
            "token_budget": {
                "capacity": self._token_budget.capacity,
                "used": self._token_budget.used,
                "over_released": self._token_budget.over_released,
            },
        }

    def serving_status(self) -> dict:
        """``web_status.gather_status`` hook."""
        out = {"name": f"disagg:{self.model.model.manifest.get('workflow', '?')}",
               "initialized": self._started,
               "stopped": not self._started}
        out.update(self.stats())
        return out
