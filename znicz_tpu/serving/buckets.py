"""Bucket-ladder math for the serving engine.

A ragged request stream (64, 64, 37, 1, 64, …) served through an
exact-batch-size program cache pays a fresh trace+compile for every
distinct size and keeps every program forever.  Rounding sizes up to a
power-of-two ladder caps the number of live programs at
``log2(max_batch) + 1`` while wasting at most 2× compute on the padded
tail (amortized far less on real traffic, where the batcher coalesces
toward full buckets).

``align`` folds data-parallel replication in: a batch sharded over an
``n_data``-way mesh axis must divide evenly (jax shardings reject
ragged splits), so the ladder becomes ``align·1, align·2, align·4, …``
— every bucket a legal data-axis split, ladder length
``log2(max_batch / align) + 1 ≤ log2(max_batch) + 1``.

The same math quantizes every dynamic axis of the round-12 decode
path (:mod:`znicz_tpu.serving.decode`): prompt lengths ride the
ladder on the T axis (``align = prompt_align``) for the prefill
program family, and live-batch sizes ride it for the single-token
decode family — the reason a warmed generation loop needs no
compiles at any prompt mix or batch occupancy.
"""

from __future__ import annotations


def next_pow2(n: int) -> int:
    """Smallest power of two ≥ ``n`` (n ≥ 1)."""
    if n < 1:
        raise ValueError(f"need n >= 1, got {n}")
    return 1 << (n - 1).bit_length()


def bucket_for(n: int, align: int = 1) -> int:
    """The ladder bucket covering a batch of ``n`` rows: the smallest
    ``align * 2**k ≥ n``.  With ``align=1`` this is the classic
    power-of-two ladder; with ``align = n_data`` every bucket divides
    evenly over the mesh's data axis."""
    if align < 1:
        raise ValueError(f"need align >= 1, got {align}")
    return align * next_pow2(max(1, -(-n // align)))


def ladder(max_batch: int, align: int = 1) -> list[int]:
    """All buckets up to (and covering) ``max_batch``:
    ``[align, 2·align, 4·align, …, bucket_for(max_batch)]``.  This is
    the warmup set — compiling exactly these programs at engine start
    means zero compiles at serve time for any request ≤ ``max_batch``.
    """
    if max_batch < 1:
        raise ValueError(f"need max_batch >= 1, got {max_batch}")
    out = []
    b = align
    top = bucket_for(max_batch, align)
    while b < top:
        out.append(b)
        b *= 2
    out.append(top)
    return out
