"""ServingEngine: throughput-oriented serving over the export format.

Glues the three round-8 pieces together on top of
:class:`znicz_tpu.export.ExportedModel`:

1. **Bucketed AOT program cache** — :meth:`start` warms every bucket
   of the power-of-two ladder (``serving.buckets``) through real
   ``jit(...).lower(...).compile()`` calls, so steady-state serving
   performs ZERO compiles and the number of live programs is
   ``log2(max_batch)+1`` regardless of how ragged the traffic is.
2. **Continuous batching** — :meth:`submit` enqueues onto a bounded
   queue drained by a scheduler thread
   (:class:`znicz_tpu.serving.batcher.ContinuousBatcher`) that
   coalesces pending requests into the smallest covering bucket, pads
   the tail, and masks the padded rows out of every reply.  Callers
   see :class:`QueueFull` backpressure, never a server OOM.
3. **Data-parallel replication** — on a multi-device backend the
   engine builds a data-axis mesh (``parallel.make_mesh``) and lets
   the existing ``XLADevice.sharding_for`` placement shard each
   coalesced batch across it: one compiled program, N-chip
   throughput, GSPMD inserting the collectives (gate:
   ``root.common.serving.replicate = False`` → single device).

Host-side allocation discipline: each bucket owns TWO pinned staging
buffers used alternately (donation double-buffering) — with input
donation the device consumes the uploaded buffer, and alternating the
host side keeps refills off any buffer a still-in-flight upload may
read, without allocating per request.

Telemetry: every counter lives in the process-global
:mod:`znicz_tpu.observe` registry under per-engine labels
(``znicz_serving_requests_total``, ``znicz_serving_latency_seconds``,
``znicz_serving_queue_rows``, per-bucket batch/row counters) so a
Prometheus scrape of ``/metrics`` sees serving beside the training
series; :meth:`stats` / :meth:`serving_status` are VIEWS over those
registry children (plus a sliding exact-value window for the
p50/p95/p99 the dashboard shows — the scrapeable histogram carries the
same distribution in buckets).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from concurrent.futures import Future

import numpy as np

from znicz_tpu.observe import metrics as _metrics
from znicz_tpu.resilience import faults as _faults
from znicz_tpu.serving.batcher import (ContinuousBatcher,
                                       DeadlineExceeded, Overloaded,
                                       QueueFull)
from znicz_tpu.serving.buckets import bucket_for, ladder
from znicz_tpu.utils.logger import Logger

__all__ = ["ServingEngine", "QueueFull", "Overloaded",
           "DeadlineExceeded", "resolve_swap_state"]


def resolve_swap_state(state) -> tuple:
    """Normalize a swap source into ``(manifest, params)``.

    Accepts a bundle path (digest-side verification is the
    publication watcher's job — this just reads), an
    :class:`~znicz_tpu.export.ExportedModel`, an already-read
    ``(manifest, params)`` pair (what the watcher hands the
    controller), or a plain ``{layer<i>_<attr>: array}`` dict (then
    manifest is ``None`` and only shape validation applies)."""
    from znicz_tpu.export import ExportedModel, read_bundle
    if isinstance(state, ExportedModel):
        return state.manifest, dict(state._params)
    if isinstance(state, (str, bytes)) or hasattr(state, "__fspath__"):
        return read_bundle(state)
    if isinstance(state, tuple) and len(state) == 2 \
            and isinstance(state[1], dict):
        return state
    if isinstance(state, dict):
        return None, state
    raise TypeError(f"cannot swap from {type(state).__name__}: pass a "
                    f"bundle path, an ExportedModel or a params dict")

#: distinguishes same-named engines in the registry's labels
_ENGINE_SEQ = itertools.count()

# round 24: the exact-windowed percentile helpers moved to
# observe.metrics so bench rows and the znicz_phase_p99_seconds
# callback gauges share one implementation; re-exported here because
# the benches and dryruns import them from serving.engine
_percentile = _metrics._percentile
window_p99 = _metrics.window_p99


class ServingEngine(Logger):
    """Continuous-batching server over an exported forward chain.

    ``model`` is an :class:`~znicz_tpu.export.ExportedModel` or a
    bundle path.  When a path is given (or the model's device should
    be replaced), the engine resolves its own device: a data-axis mesh
    over all visible devices when replication is on and more than one
    device exists, else the default single device.

    Lifecycle::

        with ServingEngine("model.npz", max_batch=64) as eng:
            future = eng.submit(x)          # async
            probs = future.result()
            probs = eng(x)                  # sync convenience

    ``start()`` compiles the whole ladder up front; ``shutdown()``
    drains the queue and stops the scheduler.
    """

    def __init__(self, model, *, max_batch: int = 64,
                 max_delay_ms: float = 5.0, max_queue: int | None = None,
                 replicate: bool | None = None,
                 device=None,
                 retry_budget: int = 1,
                 breaker_failure_rate: float = 0.5,
                 breaker_window: int = 8,
                 breaker_cooldown_ms: float = 1000.0,
                 max_queue_age_ms: float | None = 10_000.0,
                 shadow_audit_rate: float | None = None) -> None:
        super().__init__()
        from znicz_tpu.export import ExportedModel  # deferred: cycle
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.max_batch = int(max_batch)
        self.max_delay_ms = float(max_delay_ms)
        self.max_queue = int(max_queue if max_queue is not None
                             else max(4 * max_batch, 1024))
        # round-11 degradation knobs (see serving.batcher): a failed
        # dispatch retries once by default; sustained failure or a
        # stale queue opens the breaker and sheds load
        self.retry_budget = int(retry_budget)
        self.breaker_failure_rate = float(breaker_failure_rate)
        self.breaker_window = int(breaker_window)
        self.breaker_cooldown_ms = float(breaker_cooldown_ms)
        self.max_queue_age_ms = max_queue_age_ms
        if isinstance(model, (str, bytes)) or hasattr(model, "__fspath__"):
            if device is None:
                device = self.resolve_device(replicate)
            model = ExportedModel.load(model, device=device,
                                       max_batch=self.max_batch)
        self.model = model
        if device is None:
            device = model.device
        self.device = device
        self.n_replicas = max(1, getattr(self.device, "n_data_shards", 1))
        if replicate is False and self.n_replicas > 1:
            raise ValueError(
                "replicate=False but the model's device already "
                "carries a data-axis mesh — build the model on a "
                "single device instead")
        self._batcher: ContinuousBatcher | None = None
        self._staging: dict[int, list[np.ndarray]] = {}
        self._flip: dict[int, int] = {}
        self._lock = threading.Lock()
        # telemetry: counters live in the observe registry under a
        # per-engine label (unique even when two engines serve the
        # same workflow name); stats() reads these children back ----
        wf_name = self.model.manifest.get("workflow", "model")
        self._obs_id = f"{wf_name}#{next(_ENGINE_SEQ)}"
        self._m_submitted = _metrics.serving_requests(
            self._obs_id, "submitted")
        self._m_served = _metrics.serving_requests(self._obs_id, "served")
        self._m_rejected = _metrics.serving_requests(
            self._obs_id, "rejected")
        self._m_latency = _metrics.serving_latency_seconds(self._obs_id)
        self._m_queue = _metrics.serving_queue_rows(self._obs_id)
        self._m_warmup = _metrics.serving_warmup_seconds(self._obs_id)
        #: bucket size → (batches counter, rows counter)
        self._m_bucket: dict[int, tuple] = {}
        #: exact-value sliding window for the dashboard percentiles
        self._lat = deque(maxlen=4096)  # enqueue→reply seconds
        self.warmup_compiles = 0
        self.warmup_seconds = 0.0
        self._started = False
        # hot-swap bookkeeping (round 13)
        self.model_version = 0
        self._m_version = _metrics.model_version(self._obs_id)
        self._m_version.set(0)
        self._m_swap_dur = _metrics.swap_duration_seconds(self._obs_id)
        self.swap_counts = {"promoted": 0, "rejected": 0,
                            "rolled_back": 0}
        self._swap_pauses: list[float] = []  # seconds, per swap
        # round 19: sampled SDC shadow audit — a fraction of batches
        # is re-scored against the COMPILE-FREE numpy oracle; a
        # mismatching reply marks this replica SUSPECT (every later
        # batch audits + the reply is corrected from the oracle) and
        # fires on_sdc_suspect so a ReplicaGroup can quarantine it.
        from znicz_tpu.utils.config import root as _root
        self.shadow_audit_rate = float(
            _root.common.serving.get("sdc_audit_rate", 0.0)
            if shadow_audit_rate is None else shadow_audit_rate)
        self.sdc_audit_rtol = float(
            _root.common.serving.get("sdc_audit_rtol", 0.05))
        #: replica identity for sdc.serving_bitflip context filters
        #: and suspect attribution (a ReplicaGroup stamps its own)
        self.sdc_replica = self._obs_id
        #: callable(engine) invoked once on the first confirmed
        #: mismatch — the ReplicaGroup repair hook
        self.on_sdc_suspect = None
        self.sdc_suspect = False
        self._audit_acc = 0.0
        self._audit_stats = {"audited": 0, "mismatched": 0}
        self._oracle = None
        self._oracle_version = None

    # ------------------------------------------------------------------
    # construction helpers
    # ------------------------------------------------------------------
    @staticmethod
    def resolve_device(replicate: bool | None = None):
        """The serving device under the replication gate: a data-axis
        mesh over every visible device when allowed and useful, else
        the plain default device."""
        from znicz_tpu.backends import Device, XLADevice
        from znicz_tpu.utils.config import root
        if replicate is None:
            replicate = bool(root.common.serving.get("replicate", True))
        if not replicate:
            return Device.create()
        import jax
        devices = jax.devices()
        if len(devices) < 2:
            return Device.create()
        from znicz_tpu.parallel import make_mesh
        mesh = make_mesh(n_data=len(devices), n_model=1,
                         devices=devices)
        return XLADevice(mesh=mesh)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> "ServingEngine":
        """Warm the whole bucket ladder (every compile happens HERE)
        and start the scheduler thread."""
        if self._started:
            return self
        align = self.model._align
        t0 = time.monotonic()
        self.warmup_compiles = self.model.warmup(self.max_batch)
        self.warmup_seconds = time.monotonic() - t0
        shape, dtype = self.model.input_shape, self.model.serve_dtype
        for size in ladder(self.max_batch, align):
            # donation double-buffering: two host staging buffers per
            # bucket, used alternately by the scheduler thread
            self._staging[size] = [
                np.zeros((size,) + shape, dtype=dtype) for _ in range(2)]
            self._flip[size] = 0
        self._m_warmup.set(self.warmup_seconds)
        self._batcher = ContinuousBatcher(
            self._run_batch, max_batch=self.max_batch,
            max_delay_ms=self.max_delay_ms, max_queue=self.max_queue,
            name=self.model.manifest.get("workflow", "model"),
            queue_gauge=self._m_queue,
            retry_budget=self.retry_budget,
            breaker_failure_rate=self.breaker_failure_rate,
            breaker_window=self.breaker_window,
            breaker_cooldown_ms=self.breaker_cooldown_ms,
            max_queue_age_ms=self.max_queue_age_ms,
            obs_id=self._obs_id)
        self._started = True
        self.info(
            "serving '%s': %d AOT programs warmed in %.2fs "
            "(buckets %s, replicas=%d, donate=%s)",
            self.model.manifest.get("workflow", "?"),
            self.warmup_compiles, self.warmup_seconds,
            ladder(self.max_batch, align), self.n_replicas,
            self.model._donate_choice())
        return self

    def shutdown(self, timeout: float = 30.0) -> None:
        """Drain the queue, stop the scheduler."""
        if self._batcher is not None:
            self._batcher.shutdown(timeout=timeout)
            self._batcher = None
        self._started = False

    def __enter__(self) -> "ServingEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # ------------------------------------------------------------------
    # request path
    # ------------------------------------------------------------------
    def submit(self, x: np.ndarray,
               deadline_ms: float | None = None, *,
               tenant: str | None = None, priority: int = 0,
               retry_budget: int | None = None,
               tenant_max_rows: int | None = None) -> Future:
        """Enqueue a request (``x``: batch of samples, 1..max_batch
        rows); returns a future of the output rows.  Raises
        :class:`QueueFull` under backpressure and :class:`Overloaded`
        while the breaker sheds load.  With ``deadline_ms`` the future
        fails fast with :class:`DeadlineExceeded` if the request is
        still queued when the deadline passes — its rows are evicted
        before dispatch and never reach a program.  ``tenant`` /
        ``priority`` / ``retry_budget`` / ``tenant_max_rows`` are the
        round-16 tenancy knobs (see
        :class:`~znicz_tpu.serving.batcher.ContinuousBatcher` — the
        fleet passes them from the tenant's SLO class)."""
        if self._batcher is None:
            raise RuntimeError("engine not started — call start()")
        x = np.ascontiguousarray(x, dtype=self.model.serve_dtype)
        if x.shape[1:] != self.model.input_shape:
            raise ValueError(
                f"input sample shape {x.shape[1:]} != exported "
                f"{self.model.input_shape}")
        try:
            future = self._batcher.submit(
                x, deadline_ms=deadline_ms, tenant=tenant,
                priority=priority, retry_budget=retry_budget,
                tenant_max_rows=tenant_max_rows)
        except QueueFull:  # includes Overloaded load shedding
            self._m_rejected.inc()
            raise
        self._m_submitted.inc()
        return future

    def __call__(self, x: np.ndarray, timeout: float | None = None,
                 deadline_ms: float | None = None) -> np.ndarray:
        """Synchronous convenience: submit + wait."""
        return self.submit(x, deadline_ms=deadline_ms).result(
            timeout=timeout)

    def flush(self) -> None:
        """Dispatch pending requests without waiting out the admission
        window."""
        if self._batcher is not None:
            self._batcher.flush()

    # ------------------------------------------------------------------
    # weight hot-swap (round 13)
    # ------------------------------------------------------------------
    def current_bundle(self) -> tuple:
        """The live ``(manifest, params)`` — what a SwapController
        snapshots as the rollback target before promoting a
        candidate."""
        return self.model.manifest, dict(self.model._params)

    def swap_weights(self, state, *, version: int | None = None,
                     outcome: str = "promoted") -> dict:
        """Hot-swap the running replica set to a new weight set
        without recompiling.

        ``state`` is a bundle path, an ``ExportedModel`` or a params
        dict (see :func:`resolve_swap_state`).  Shapes/dtypes are
        validated against the export manifest first —
        :class:`~znicz_tpu.export.SwapIncompatible` leaves the old
        weights untouched.  New buffers stage onto the serving mesh
        off the dispatch path, then publish atomically between batch
        dispatches: in-flight requests finish on the old weights.

        ``outcome`` labels the ``znicz_swaps_total`` event (the
        controller passes ``rolled_back`` when this swap restores the
        prior version).  Returns a summary dict."""
        manifest, params = resolve_swap_state(state)
        t0 = time.monotonic()
        self.model.swap_weights(params, manifest=manifest)
        pause = time.monotonic() - t0
        if version is None:
            version = self.model_version + 1
        self.model_version = int(version)
        self._m_version.set(self.model_version)
        self._m_swap_dur.observe(pause)
        self._swap_pauses.append(pause)
        self.record_swap_outcome(outcome)
        self.info("weights hot-swapped → version %d (%s, %.1f ms, "
                  "zero recompiles by construction)",
                  self.model_version, outcome, 1e3 * pause)
        return {"version": self.model_version, "outcome": outcome,
                "pause_ms": round(1e3 * pause, 3),
                "weights_version": self.model.weights_version}

    def record_swap_outcome(self, outcome: str) -> None:
        """Count one swap verdict for this engine (the canary gate
        calls this with ``rejected`` without ever touching the
        weights)."""
        self.swap_counts[outcome] = self.swap_counts.get(outcome, 0) + 1
        _metrics.swaps_total(self._obs_id, outcome).inc()
        from znicz_tpu.observe import recorder as _recorder
        _recorder.record("swap", engine=self._obs_id, outcome=outcome,
                         version=self.model_version)

    def set_model_version(self, version: int) -> None:
        """Label the CURRENTLY loaded bundle's published version (an
        engine started straight from a published file was never
        swapped, so the gauge would otherwise read 0)."""
        self.model_version = int(version)
        self._m_version.set(self.model_version)

    def swap_pauses_ms(self) -> list[float]:
        """Per-swap publish pauses (ms) — the soak bench reports their
        percentiles."""
        return [1e3 * p for p in self._swap_pauses]

    # ------------------------------------------------------------------
    def _run_batch(self, batch) -> None:
        """Scheduler-thread dispatch: coalesce → pad → one AOT program
        → split replies.  Sole caller of the compiled programs, so the
        model's cache bookkeeping needs no locking."""
        spike = _faults.fire("serving.latency_spike")
        if spike is not None:  # chaos: a slow program / stalled device
            time.sleep(float(spike.get("ms", 50.0)) / 1e3)
        if _faults.fire("serving.program_error") is not None:
            raise _faults.FaultInjected(
                "injected serving program failure")
        total = sum(req.n for req in batch)
        size = bucket_for(total, self.model._align)
        staging = self._staging.get(size)
        if staging is None:  # bucket above the warmed ladder
            staging = self._staging[size] = [
                np.zeros((size,) + self.model.input_shape,
                         dtype=self.model.serve_dtype) for _ in range(2)]
            self._flip[size] = 0
        self._flip[size] ^= 1
        buf = staging[self._flip[size]]
        row = 0
        for req in batch:
            buf[row:row + req.n] = req.x
            row += req.n
        if row < size:
            buf[row:] = 0  # padded tail: never leaks, but keep it clean
        # pin the published weight tuple ONCE for this dispatch: a
        # swap landing mid-batch flips live_params for the NEXT
        # dispatch; this one completes on the weights it started with
        params = self.model.live_params or None
        out = np.asarray(self.model.program_for(size)(
            buf, _params=params))
        out = self._shadow_audit(buf, out, row)
        now = time.monotonic()
        row = 0
        for req in batch:
            req.future.set_result(np.array(out[row:row + req.n],
                                           copy=True))
            row += req.n
        self._m_served.inc(len(batch))
        with self._lock:
            pair = self._m_bucket.get(size)
            if pair is None:
                pair = self._m_bucket[size] = (
                    _metrics.serving_bucket_batches(self._obs_id, size),
                    _metrics.serving_bucket_rows(self._obs_id, size))
            pair[0].inc()
            pair[1].inc(total)
            for req in batch:
                lat = now - req.t_submit
                self._lat.append(lat)
                self._m_latency.observe(lat)

    # ------------------------------------------------------------------
    # round 19: sampled SDC shadow audit
    # ------------------------------------------------------------------
    def _shadow_oracle(self):
        """The compile-free numpy oracle over the CURRENT weights
        (rebuilt lazily after a hot-swap — cached K/V-free forward on
        the host, never a serving-AOT compile)."""
        if self._oracle is None \
                or self._oracle_version != self.model.weights_version:
            from znicz_tpu.backends import NumpyDevice
            from znicz_tpu.export import ExportedModel
            manifest, params = self.current_bundle()
            host = {k: np.asarray(v) for k, v in params.items()}
            self._oracle = ExportedModel(dict(manifest), host,
                                         device=NumpyDevice())
            self._oracle_version = self.model.weights_version
        return self._oracle

    def _shadow_audit(self, buf: np.ndarray, out: np.ndarray,
                      rows: int) -> np.ndarray:
        """Scheduler-thread tail of a dispatch: apply the seeded
        ``sdc.serving_bitflip`` (chaos), then — for the sampled
        fraction (``shadow_audit_rate``, every batch once suspect) —
        re-score the real rows on the numpy oracle.  A mismatch marks
        this replica suspect, CORRECTS the reply from the oracle (the
        caller never receives the wrong answer), and fires
        ``on_sdc_suspect`` exactly once so the owning ReplicaGroup
        can remove the replica via its repair path."""
        flip = _faults.fire("sdc.serving_bitflip",
                            replica=self.sdc_replica)
        if flip is not None:
            out = np.array(out, copy=True)
            out[:, 0] = out[:, 0] * float(flip.get("factor", 2.0 ** 14))
        rate = self.shadow_audit_rate
        if rate <= 0.0 and not self.sdc_suspect:
            return out
        self._audit_acc += rate
        audit = self.sdc_suspect or self._audit_acc >= 1.0
        if self._audit_acc >= 1.0:
            self._audit_acc -= 1.0
        if not audit or rows == 0:
            return out
        ref = np.asarray(self._shadow_oracle()(
            np.asarray(buf[:rows], dtype=np.float32)))
        got = np.asarray(out[:rows], dtype=np.float32)
        self._audit_stats["audited"] += 1
        scale = np.maximum(np.abs(ref), 1.0)
        if np.all(np.abs(got - ref) <= self.sdc_audit_rtol * scale):
            return out
        self._audit_stats["mismatched"] += 1
        first = not self.sdc_suspect
        self.sdc_suspect = True
        from znicz_tpu.parallel.process_shard import process_info
        _metrics.sdc_suspects(process_info()[0],
                              self.sdc_replica).inc()
        if first:
            _metrics.sdc_detected("serving").inc()
            self.error(
                "SDC shadow audit: replica %s returned wrong scores "
                "(max dev %.3g) — reply corrected from the oracle, "
                "replica marked suspect", self.sdc_replica,
                float(np.max(np.abs(got - ref))))
        out = np.array(out, copy=True)
        out[:rows] = ref.astype(out.dtype)
        if first and self.on_sdc_suspect is not None:
            try:
                self.on_sdc_suspect(self)
            except Exception as exc:  # noqa: BLE001 — audit must not
                self.error("on_sdc_suspect hook failed: %s", exc)
        return out

    # ------------------------------------------------------------------
    # telemetry
    # ------------------------------------------------------------------
    @property
    def requests_submitted(self) -> int:
        return int(self._m_submitted.value)

    @property
    def requests_served(self) -> int:
        return int(self._m_served.value)

    @property
    def requests_rejected(self) -> int:
        return int(self._m_rejected.value)

    def stats(self) -> dict:
        """The engine's live snapshot — a VIEW over this engine's
        children in the observe registry (the same numbers a
        Prometheus ``/metrics`` scrape sees), plus exact windowed
        latency percentiles for the dashboard."""
        with self._lock:
            lat = sorted(self._lat)
            buckets = {}
            for size in sorted(self._m_bucket):
                batches_c, rows_c = self._m_bucket[size]
                batches, rows = int(batches_c.value), int(rows_c.value)
                buckets[size] = {
                    "batches": batches,
                    "rows": rows,
                    "occupancy_pt": round(
                        100.0 * rows / (batches * size), 1),
                }
            out = {
                "engine": "bucketed-aot",
                "replicas": self.n_replicas,
                "max_batch": self.max_batch,
                "max_delay_ms": self.max_delay_ms,
                "buckets_warmed": sorted(self._staging),
                "programs_compiled": self.model.compile_count,
                "programs_loaded": getattr(self.model, "load_count", 0),
                "programs_live": len(self.model._programs),
                "warmup_seconds": round(self.warmup_seconds, 3),
                "submitted": self.requests_submitted,
                "served": self.requests_served,
                "rejected": self.requests_rejected,
                "model_version": self.model_version,
                "weights_version": self.model.weights_version,
                "swaps": dict(self.swap_counts),
                "queue_rows": (self._batcher.queue_rows
                               if self._batcher else 0),
                "buckets": buckets,
            }
            b = self._batcher
            out["resilience"] = {
                "breaker": b.breaker_state if b else "closed",
                "retry_budget": self.retry_budget,
                "retried": b.retries_total if b else 0,
                "expired": b.expired_total if b else 0,
                "shed": b.shed_total if b else 0,
                "queue_age_ms": round(1e3 * b.oldest_age_s(), 1)
                if b else 0.0,
                "sdc": {"audit_rate": self.shadow_audit_rate,
                        "suspect": self.sdc_suspect,
                        **self._audit_stats},
            }
        from . import aot_cache as _aot
        out["aot_cache"] = _aot.status()
        if lat:
            out["latency_ms"] = {
                "p50": round(1e3 * _percentile(lat, 50), 3),
                "p95": round(1e3 * _percentile(lat, 95), 3),
                "p99": round(1e3 * _percentile(lat, 99), 3),
                "mean": round(1e3 * sum(lat) / len(lat), 3),
                "window": len(lat),
            }
        return out

    def ready(self) -> bool:
        """/readyz signal: started and not shedding load."""
        b = self._batcher
        return bool(self._started and b is not None
                    and b.breaker_state != "open")

    def serving_status(self) -> dict:
        """``web_status.gather_status`` hook: the dashboard entry for
        this engine."""
        out = {"name": f"serving:{self.model.manifest.get('workflow', '?')}",
               "initialized": self._started,
               "stopped": not self._started}
        out.update(self.stats())
        dev = self.device
        if dev is not None:
            out["backend"] = dev.backend
            mesh = getattr(dev, "mesh", None)
            if mesh is not None:
                out["mesh"] = {ax: int(n) for ax, n
                               in zip(mesh.axis_names, mesh.devices.shape)}
        return out
