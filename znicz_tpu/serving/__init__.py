"""Serving: a throughput-oriented engine over the export format.

The reference shipped trained nets to a standalone C++ engine
(libZnicz) that served one synchronous request at a time.  This
package is the TPU-native replacement for that serving story:
continuous batching of asynchronously arriving requests (Orca-style)
into a power-of-two bucket ladder of AOT-compiled programs, optionally
replicated across a data-axis mesh (GSPMD) — one compiled program,
N-chip throughput, zero compiles at serve time.

Entry points::

    from znicz_tpu.serving import ServingEngine
    with ServingEngine("model.npz", max_batch=64) as engine:
        probs = engine(x)               # sync
        future = engine.submit(x)       # async → future

    from znicz_tpu.serving import DecodeEngine      # round 12
    with DecodeEngine("lm.npz", max_slots=4, max_t=64) as eng:
        tokens = eng.generate(prompt)   # autoregressive generation

    from znicz_tpu.serving import FleetEngine, TenantClass  # round 16
    fleet = FleetEngine(tenants=[TenantClass("hi", priority=0)])
    fleet.add_model("scorer", "model.npz")
    fleet.add_model("lm", "lm.npz", kind="lm")
    with fleet:
        probs = fleet("scorer", x, tenant="hi")    # multi-tenant SLOs

See :mod:`znicz_tpu.serving.engine` (one-shot scoring) and
:mod:`znicz_tpu.serving.decode` (KV-cache generation) for the design
notes.
"""

from znicz_tpu.serving.batcher import (  # noqa: F401
    ContinuousBatcher,
    DeadlineExceeded,
    Overloaded,
    PriorityQueue,
    QueueFull,
    TokenBucketLimiter,
    TokenBudget,
)
from znicz_tpu.serving.buckets import (  # noqa: F401
    bucket_for,
    ladder,
    next_pow2,
)
from znicz_tpu.serving.decode import (  # noqa: F401
    DecodeEngine,
    DecodeModel,
    KVCache,
    PagedKVCache,
    PoolExhausted,
    PrefixCache,
)
from znicz_tpu.serving.disagg import (  # noqa: F401
    DisaggEngine,
)
from znicz_tpu.serving.engine import (  # noqa: F401
    ServingEngine,
    resolve_swap_state,
)
from znicz_tpu.serving.fleet import (  # noqa: F401
    FleetAutoscaler,
    FleetEngine,
    PoolAutoscaler,
    ReplicaGroup,
    SharedLadderBudget,
    TenantClass,
)


def __getattr__(name):
    # lazy: export.py itself imports serving.buckets at module load,
    # so a direct top-level re-export here would be a circular import
    if name == "SwapIncompatible":
        from znicz_tpu.export import SwapIncompatible
        return SwapIncompatible
    raise AttributeError(name)
