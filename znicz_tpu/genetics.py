"""Genetics: hyperparameter search over workflow configs.

Rebuilds the reference's ``veles/genetics/`` — config values declared
as tunable ranges (``Tune``), a population of candidate configs, each
evaluated by training a workflow instance, evolved by
selection/crossover/mutation.

TPU-first deltas: the reference farmed one genome per cluster node
through the master–slave launcher; here evaluation is a plain callable
(train a workflow on the local device by default), and multi-host
scale-out is process-level — with ``jax.distributed`` each process
evaluates ``genomes[process_index::process_count]`` and the scores are
all-gathered once per generation (``_score_population``), replacing
the reference's job queue.  Each evaluation is collective-free (local
devices only), so differently-sized local slices cannot deadlock; the
GA's own PRNG stream is consumed identically on every process, so the
populations — and therefore the work lists — agree by construction.
Tested across real OS processes in ``tests/test_distributed.py``
(``genetics`` mode: disjoint evaluation sets, identical best genome).
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from znicz_tpu.parallel.process_shard import (allgather_sum,
                                              merge_sharded_scores,
                                              pick_eval_device,
                                              process_info)
from znicz_tpu.utils.logger import Logger


class Tune:
    """A tunable config leaf: default value + inclusive range
    (reference: ``veles/genetics/config.py`` ``Tune``)."""

    def __init__(self, default, min_value, max_value,
                 is_int: bool | None = None) -> None:
        if not (min_value <= default <= max_value):
            raise ValueError(
                f"Tune default {default} outside [{min_value}, "
                f"{max_value}]")
        self.default = default
        self.min_value = min_value
        self.max_value = max_value
        self.is_int = (isinstance(default, (int, np.integer))
                       and not isinstance(default, bool)
                       if is_int is None else is_int)

    def clip(self, value):
        value = min(max(value, self.min_value), self.max_value)
        return int(round(value)) if self.is_int else float(value)

    def sample(self, rng: np.random.Generator):
        if self.is_int:
            return int(rng.integers(self.min_value, self.max_value + 1))
        return float(rng.uniform(self.min_value, self.max_value))

    def __repr__(self) -> str:
        return (f"Tune({self.default}, {self.min_value}, "
                f"{self.max_value})")


def collect_tunes(node, prefix: str = "") -> dict[str, Tune]:
    """Walk a :class:`~znicz_tpu.utils.config.Config` subtree and pull
    out every ``Tune`` leaf (reference behavior: config files wrap
    leaves in ``Tune`` and genetics discovers them)."""
    from znicz_tpu.utils.config import Config
    out: dict[str, Tune] = {}
    for name, value in node.items():
        path = f"{prefix}{name}"
        if isinstance(value, Tune):
            out[path] = value
        elif isinstance(value, Config):
            out.update(collect_tunes(value, prefix=f"{path}."))
    return out


def apply_genome(genome: dict[str, Any]) -> dict[str, Any]:
    """Split a genome into build-kwargs (plain keys) and config-tree
    writes (dotted keys, applied to ``root`` immediately).

    The writes are global state: callers that evaluate MANY genomes
    (the GA's fitness loop) must bracket each evaluation with
    :func:`snapshot_genome_leaves` / :func:`restore_genome_leaves`, or
    the tree keeps whatever candidate ran last —
    :meth:`GeneticsOptimizer.run` restores around every evaluation and
    re-applies the BEST genome on exit."""
    from znicz_tpu.utils.config import root
    kwargs = {}
    for key, value in genome.items():
        if "." in key:
            node = root
            parts = key.split(".")
            for part in parts[:-1]:
                node = getattr(node, part)
            setattr(node, parts[-1], value)
        else:
            kwargs[key] = value
    return kwargs


#: sentinel for "this leaf did not exist before apply_genome"
_MISSING = object()


def snapshot_genome_leaves(genome: dict[str, Any]) -> dict[str, Any]:
    """Current values of the genome's dotted config leaves (the state
    :func:`apply_genome` is about to clobber — typically the ``Tune``
    objects the search space was collected from)."""
    from znicz_tpu.utils.config import root
    snap: dict[str, Any] = {}
    for key in genome:
        if "." not in key:
            continue
        node = root
        parts = key.split(".")
        for part in parts[:-1]:
            node = getattr(node, part)
        snap[key] = node.__dict__.get(parts[-1], _MISSING)
    return snap


def restore_genome_leaves(snapshot: dict[str, Any]) -> None:
    """Undo :func:`apply_genome`'s config-tree writes: put back the
    snapshotted values, deleting leaves that did not exist."""
    from znicz_tpu.utils.config import root
    for key, value in snapshot.items():
        node = root
        parts = key.split(".")
        for part in parts[:-1]:
            node = getattr(node, part)
        if value is _MISSING:
            node.__dict__.pop(parts[-1], None)
        else:
            setattr(node, parts[-1], value)


def workflow_fitness(workflow) -> float:
    """Score a trained workflow: negated validation metric (higher is
    better).  The one metric-extraction point for every GA driver."""
    d = workflow.decision
    if getattr(d, "min_validation_n_err_pt", None) is not None:
        return -float(d.min_validation_n_err_pt)
    if getattr(d, "min_validation_mse", None) is not None:
        return -float(d.min_validation_mse)
    raise ValueError("decision exposes no validation metric")


class GeneticsOptimizer(Logger):
    """Evolve workflow hyperparameters.

    Parameters
    ----------
    build_fn:
        ``callable(**overrides) -> Workflow`` (a sample's ``build``).
    space:
        genome layout: key → :class:`Tune`.  Plain keys become
        ``build_fn`` kwargs; dotted keys are config-tree leaves.
    fitness_fn:
        ``callable(genome) -> float`` (higher is better).  Default:
        build + train the workflow and return
        ``-min_validation_n_err_pt`` (or ``-min_validation_mse``).
    backend:
        ``"process"`` (default — one sequential training per fresh
        genome; scales out process-sharded under ``jax.distributed``,
        the multi-host path) or ``"mesh"`` — score a WHOLE generation
        in one population run: K stacked replicas of the architecture
        train simultaneously in one vmapped jit region (member axis
        sharded over ``mesh``'s data axis), each member carrying its
        genome's learning rate as a device leaf.  The mesh backend
        requires ``build_fn`` and a single-key search space named
        ``learning_rate`` (or any dotted path ending in it) — the one
        hyperparameter that is per-member device state; anything that
        changes the architecture still needs the process backend.
    """

    def __init__(self, build_fn: Callable | None = None,
                 space: dict[str, Tune] | None = None,
                 population_size: int = 8,
                 generations: int = 5,
                 elite: int = 1,
                 mutation_rate: float = 0.25,
                 mutation_sigma: float = 0.2,
                 seed: int = 1234,
                 fitness_fn: Callable[[dict], float] | None = None,
                 device_factory: Callable | None = None,
                 train_kwargs: dict | None = None,
                 backend: str = "process",
                 mesh=None) -> None:
        super().__init__()
        if space is None or not space:
            raise ValueError("empty search space")
        if backend not in ("process", "mesh"):
            raise ValueError(f"unknown genetics backend '{backend}'")
        if backend == "mesh":
            if build_fn is None:
                raise ValueError("mesh backend needs build_fn")
            if fitness_fn is not None:
                raise ValueError(
                    "mesh backend scores through the population "
                    "engine — it cannot take a custom fitness_fn")
            bad = [k for k in space
                   if k != "learning_rate"
                   and not k.endswith(".learning_rate")]
            if bad or len(space) != 1:
                raise ValueError(
                    f"mesh backend tunes exactly one learning_rate "
                    f"key (per-member device state); got "
                    f"{sorted(space)} — use backend='process' for "
                    f"architecture-changing genomes")
        self.backend = backend
        self.mesh = mesh
        self.build_fn = build_fn
        self.space = dict(space)
        self.population_size = int(population_size)
        self.generations = int(generations)
        self.elite = max(0, int(elite))
        self.mutation_rate = float(mutation_rate)
        self.mutation_sigma = float(mutation_sigma)
        self.rng = np.random.default_rng(seed)
        self.fitness_fn = fitness_fn or self._train_fitness
        self.device_factory = device_factory
        self.train_kwargs = dict(train_kwargs or {})
        self.history: list[dict] = []   # per-generation stats
        self.best_genome: dict | None = None
        self.best_fitness = -np.inf
        self._cache: dict[tuple, float] = {}
        #: genome keys THIS process trained (disjoint across processes
        #: in multi-process mode; every fresh genome in single-process)
        self.local_evaluated: list[tuple] = []

    # ------------------------------------------------------------------
    def _train_fitness(self, genome: dict) -> float:
        """Default fitness: train a fresh workflow, score validation.

        The genome's dotted config writes are scoped to THIS
        evaluation: the touched leaves are snapshotted before
        ``apply_genome`` and restored after — the next candidate (and
        the caller) sees the tree it started from, not whatever genome
        happened to run last."""
        from znicz_tpu.utils import prng
        from znicz_tpu.utils.config import root
        if self.build_fn is None:
            raise ValueError("no build_fn and no fitness_fn given")
        # same init/shuffle stream per candidate, from the documented
        # config seed (matches the CLI --optimize path)
        prng.seed_all(root.common.seed)
        snapshot = snapshot_genome_leaves(genome)
        try:
            kwargs = apply_genome(genome)
            kwargs.update(self.train_kwargs)
            wf = self.build_fn(**kwargs)
            # multi-process: evaluates on LOCAL devices only — each
            # genome is an independent run, no cross-process
            # collectives
            device = pick_eval_device(self.device_factory)
            wf.initialize(device=device)
            wf.run()
            return workflow_fitness(wf)
        finally:
            restore_genome_leaves(snapshot)

    # ------------------------------------------------------------------
    def _genome_lr(self, genome: dict) -> float:
        """The single learning-rate value a mesh-backend genome
        carries (validated at construction)."""
        return float(next(iter(genome.values())))

    def _score_population_mesh(self, pending: list[tuple]) -> None:
        """Mesh backend: score the generation's fresh genomes in ONE
        population run — K stacked members, identical init/shuffle
        stream (``prng.seed_all(root.common.seed)``, the same contract
        ``_train_fitness`` gives every candidate), one learning rate
        per member.  Fitness per member is ``-min`` validation error
        over the run, exactly :func:`workflow_fitness`'s number."""
        from znicz_tpu.population import PopulationTrainer
        from znicz_tpu.utils.config import root
        lrs = [self._genome_lr(genome) for _, genome in pending]
        seed = int(root.common.seed)
        trainer = PopulationTrainer(
            self.build_fn, len(pending),
            member_seeds=[seed] * len(pending),
            build_kwargs=dict(self.train_kwargs),
            mesh=self.mesh, member_lrs=lrs, evolve=None,
            name="genetics-mesh")
        trainer.initialize()
        trainer.run()
        for (key, _), fit in zip(pending,
                                 trainer.member_best_fitness):
            self.local_evaluated.append(key)
            self._cache[key] = float(fit)

    # ------------------------------------------------------------------
    # GA machinery
    # ------------------------------------------------------------------
    def _initial_population(self) -> list[dict]:
        pop = [{k: t.default for k, t in self.space.items()}]
        while len(pop) < self.population_size:
            pop.append({k: t.sample(self.rng)
                        for k, t in self.space.items()})
        return pop

    def _crossover(self, a: dict, b: dict) -> dict:
        """Uniform crossover with arithmetic blending on floats."""
        child = {}
        for key, tune in self.space.items():
            if tune.is_int:
                child[key] = a[key] if self.rng.random() < 0.5 else b[key]
            else:
                w = self.rng.random()
                child[key] = tune.clip(w * a[key] + (1 - w) * b[key])
        return child

    def _mutate(self, genome: dict) -> dict:
        out = dict(genome)
        for key, tune in self.space.items():
            if self.rng.random() >= self.mutation_rate:
                continue
            span = tune.max_value - tune.min_value
            if tune.is_int:
                step = max(1, int(round(span * self.mutation_sigma)))
                out[key] = tune.clip(
                    out[key] + int(self.rng.integers(-step, step + 1)))
            else:
                out[key] = tune.clip(
                    out[key]
                    + self.rng.normal(0.0, span * self.mutation_sigma))
        return out

    def _score_population(self, population: list[dict]) -> list[float]:
        """Score one generation; with ``jax.distributed``, process *p*
        trains the fresh genomes ``pending[p::process_count]`` and the
        scores merge in one all-gather (docstring contract above).
        Cache hits (elites, duplicate children) never retrain."""
        keys = [tuple(sorted(g.items())) for g in population]
        pending, seen = [], set()
        for key, genome in zip(keys, population):
            if key not in self._cache and key not in seen:
                seen.add(key)
                pending.append((key, genome))
        pidx, pcount = process_info()
        if self.backend == "mesh":
            if pending:
                self._score_population_mesh(pending)
            return [self._cache[k] for k in keys]
        if pcount > 1 and pending:
            # a local fitness exception must not raise before the
            # collectives (a lone raise would leave peers blocked in
            # process_allgather): record it, gather an explicit
            # failure flag, raise together.  A legitimately-NaN
            # fitness is NOT a failure — it caches and sorts exactly
            # as in the single-process path.
            scores = np.zeros(len(pending), np.float64)
            local_exc: Exception | None = None
            for i in range(pidx, len(pending), pcount):
                key, genome = pending[i]
                self.local_evaluated.append(key)
                try:
                    scores[i] = float(self.fitness_fn(dict(genome)))
                except Exception as exc:
                    local_exc = exc
                    break
            if allgather_sum(
                    np.array([1.0 if local_exc else 0.0]))[0] > 0:
                raise RuntimeError(
                    "fitness evaluation failed on a process; every "
                    "process aborts the GA together") from local_exc
            merged = merge_sharded_scores(scores, pcount)
            for i, (key, _) in enumerate(pending):
                self._cache[key] = float(merged[i])
        else:
            for key, genome in pending:
                self.local_evaluated.append(key)
                self._cache[key] = float(self.fitness_fn(dict(genome)))
        return [self._cache[k] for k in keys]

    def _select(self, scored: list[tuple[float, dict]]) -> dict:
        """Tournament of 2 over the current generation."""
        i, j = self.rng.integers(0, len(scored), size=2)
        return scored[i][1] if scored[i][0] >= scored[j][0] \
            else scored[j][1]

    # ------------------------------------------------------------------
    def run(self) -> dict:
        """Evolve; returns the best genome found."""
        population = self._initial_population()
        for gen in range(self.generations):
            scores = self._score_population(population)
            scored = sorted(
                zip(scores, population),
                key=lambda t: t[0], reverse=True)
            if scored[0][0] > self.best_fitness:
                self.best_fitness, self.best_genome = \
                    scored[0][0], dict(scored[0][1])
            fits = [s for s, _ in scored]
            self.history.append({
                "generation": gen,
                "best": fits[0],
                "mean": float(np.mean(fits)),
                "best_genome": dict(scored[0][1])})
            self.info(
                "generation %d: best %.4f mean %.4f (%s)", gen,
                fits[0], float(np.mean(fits)), scored[0][1])
            next_pop = [dict(g) for _, g in scored[:self.elite]]
            while len(next_pop) < self.population_size:
                child = self._crossover(self._select(scored),
                                        self._select(scored))
                next_pop.append(self._mutate(child))
            population = next_pop
        assert self.best_genome is not None
        # per-candidate writes were restored after each evaluation;
        # leave the tree holding the WINNER's values (callers build
        # the final model straight off root)
        apply_genome(self.best_genome)
        return self.best_genome
