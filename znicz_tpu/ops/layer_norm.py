"""Layer normalization — companion to the attention family (beyond
the 2015 reference, whose normalizers are cross-channel LRN and
mean-dispersion; SURVEY.md §5.7 marks sequence machinery as this
framework's extension).

``y = γ · (x − μ) / √(σ² + ε) + β`` with statistics over the LAST
(feature) axis per position.  γ/β live in the standard
``weights``/``bias`` Vectors (shape (D,)), so the GD base's momentum/
decay update rule, the exporter, and the publisher all apply
unchanged.

Statistics are computed in f32 even under bf16 activation storage
(the variance of near-equal values cancels catastrophically in bf16);
the normalized output is stored back at the activation dtype.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from znicz_tpu.ops.nn_units import Forward, GradientDescentBase


class LayerNorm(Forward):
    """Per-position feature normalization with learned scale/shift."""

    def __init__(self, workflow, eps: float = 1e-5, name=None,
                 **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.eps = float(eps)

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        if self.input is None or not self.input:
            raise AttributeError(f"{self}: input not linked yet")
        d = self.input.shape[-1]
        if not self.weights:
            self.weights.reset(np.ones(d, np.float32))   # γ
        if self.include_bias and not self.bias:
            self.bias.reset(np.zeros(d, np.float32))     # β
        self.output.reset(np.zeros(self.input.shape,
                                   dtype=self.output_store_dtype))
        self.inherit_model_shard(self.output)
        # fused Pallas layer norm (one VMEM pass vs the XLA
        # composition's materialized xhat + f32 upcasts): default ON
        # for real TPU devices per the round-5 in-graph A/B (PERF.md);
        # opt out with engine.pallas_layer_norm = False.
        from znicz_tpu.ops import pallas_kernels
        from znicz_tpu.parallel.mesh import kernel_shard_spec, \
            spec_divides
        from znicz_tpu.utils.config import root
        flag = root.common.engine.get("pallas_layer_norm", "auto")
        if flag == "auto":
            flag = pallas_kernels.is_tpu_device(self.device)
        interpret = bool(root.common.engine.get("pallas_interpret",
                                                False))
        mesh = getattr(self.device, "mesh", None)
        multi_device = mesh is not None and mesh.size > 1
        engaged = bool(flag) and (
            pallas_kernels.is_tpu_device(self.device) or interpret)
        self._ln_interpret = interpret
        self._ln_mesh = None
        self._ln_spec = None
        msd = getattr(self.input, "model_shard_dim", None)
        msd_axis = getattr(self.input, "model_shard_axis", None)
        ndim = len(self.input.shape)
        if engaged and multi_device:
            # mesh-native path: a pallas_call has no GSPMD sharding
            # rule — un-shard_mapped it would gather the sharded
            # operand onto every device.  Run per-shard under
            # shard_map instead: batch rides the data axis, a ring-
            # sharded time axis (model_shard_dim) rides the model
            # axis; γ/β grad sums psum in the backward.
            # ``engine.pallas_shard_map = False`` restores the old
            # conservative gate (kernel off on multi-device meshes).
            spec, _ = kernel_shard_spec(
                mesh, ndim, model_shard_dim=msd,
                **({"model_axis": msd_axis} if msd_axis else {}))
            engaged = (
                bool(root.common.engine.get("pallas_shard_map", True))
                and msd != ndim - 1  # feature axis must stay whole
                and spec_divides(mesh, self.input.shape, spec))
            if engaged:
                self._ln_mesh, self._ln_spec = mesh, spec
        elif engaged:
            # single device: plain kernel; a (trivially) model-sharded
            # input keeps the XLA path as before
            engaged = msd is None
        self._pallas_ln = engaged
        self.init_vectors(self.input, self.output, self.weights,
                          self.bias)

    # xp-generic cores (shared by the oracle, XLA path and backward)
    def _normalize(self, xp, x):
        mu = x.mean(axis=-1, keepdims=True)
        var = ((x - mu) ** 2).mean(axis=-1, keepdims=True)
        return (x - mu) / xp.sqrt(var + self.eps), var

    def _forward(self, xp, x, gamma, beta):
        xhat, var = self._normalize(xp, x)
        y = gamma * xhat
        if beta is not None:
            y = y + beta
        return y, xhat, var

    def numpy_run(self) -> None:
        self.input.map_read()
        self.weights.map_read()
        beta = None
        if self.include_bias:
            self.bias.map_read()
            beta = self.bias.mem
        y, _, _ = self._forward(np, self.input.mem.astype(np.float32),
                                self.weights.mem, beta)
        self.output.map_invalidate()
        self.output.mem[...] = y

    def xla_run(self) -> None:
        beta = self.bias.devmem if self.include_bias else None
        if getattr(self, "_pallas_ln", False):
            from znicz_tpu.ops import pallas_kernels
            self.output.devmem = pallas_kernels.layer_norm_forward(
                self.input.devmem, self.weights.devmem, beta, self.eps,
                interpret=getattr(self, "_ln_interpret", False),
                mesh=getattr(self, "_ln_mesh", None),
                spec=getattr(self, "_ln_spec", None))
            return
        x = self.input.devmem.astype(jnp.float32)  # f32 statistics
        y, _, _ = self._forward(jnp, x, self.weights.devmem, beta)
        self.output.devmem = y


class GDLayerNorm(GradientDescentBase):
    """Analytic layer-norm backward (identical math on both paths):

    .. code-block:: text

        dβ = Σ err          dγ = Σ err·x̂
        dx̂ = err·γ
        dx = (dx̂ − mean(dx̂) − x̂·mean(dx̂·x̂)) / √(σ² + ε)
    """

    MATCHES = (LayerNorm,)
    REQUIRES_FORWARD_UNIT = True
    REQUIRES_INPUT = True

    def __init__(self, workflow, name=None, **kwargs):
        super().__init__(workflow, name=name, **kwargs)
        self.forward_unit: LayerNorm | None = None

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        self.init_vectors(self.err_input, self.err_output, self.input,
                          self.output, self.weights, self.bias)

    def _backward(self, xp, x, err, gamma, has_bias: bool):
        fwd = self.forward_unit
        xhat, var = fwd._normalize(xp, x)
        reduce_axes = tuple(range(x.ndim - 1))
        grad_b = err.sum(axis=reduce_axes) if has_bias else None
        grad_g = (err * xhat).sum(axis=reduce_axes)
        dxhat = err * gamma
        dx = (dxhat - dxhat.mean(axis=-1, keepdims=True)
              - xhat * (dxhat * xhat).mean(axis=-1, keepdims=True)) \
            / xp.sqrt(var + fwd.eps)
        return dx, grad_g, grad_b

    def numpy_run(self) -> None:
        for vec in (self.err_output, self.input):
            vec.map_read()
        self.weights.map_write()
        has_bias = self.bias is not None and self.bias
        if has_bias:
            self.bias.map_write()
        dx, grad_g, grad_b = self._backward(
            np, self.input.mem.astype(np.float32),
            self.err_output.mem.astype(np.float32), self.weights.mem,
            has_bias)
        if self.need_err_input:
            self.err_input.map_invalidate()
            self.err_input.mem[...] = dx
        self._apply_weights_np(grad_g)
        if has_bias:
            self._apply_bias_np(grad_b)

    def xla_run(self) -> None:
        has_bias = self.bias is not None and self.bias
        if getattr(self.forward_unit, "_pallas_ln", False):
            from znicz_tpu.ops import pallas_kernels
            fwd = self.forward_unit
            dx, grad_g, grad_b = pallas_kernels.layer_norm_backward(
                self.input.devmem, self.err_output.devmem,
                self.weights.devmem, fwd.eps,
                with_beta=bool(has_bias),
                interpret=getattr(fwd, "_ln_interpret", False),
                mesh=getattr(fwd, "_ln_mesh", None),
                spec=getattr(fwd, "_ln_spec", None))
        else:
            dx, grad_g, grad_b = self._backward(
                jnp, self.input.devmem.astype(jnp.float32),
                self.err_output.devmem.astype(jnp.float32),
                self.weights.devmem, has_bias)
        if self.need_err_input:
            self.err_input.devmem = dx
        self._apply_weights_xla(grad_g)
        if has_bias:
            self._apply_bias_xla(grad_b)
