"""LSTM recurrent layer, forward + backward.

The reference repo's late-2015 tail may carry an ``lstm.py``
contribution (SURVEY.md §2.2 verify-on-mount item; the mount is empty,
so this is built to the standard LSTM formulation).  TPU-first design:

- the time recursion is ``jax.lax.scan`` — ONE compiled loop on
  device, no Python stepping (SURVEY.md "no data-dependent Python
  control flow inside jit");
- weights are a single fused ``(F+H, 4H)`` matrix so each step is one
  MXU GEMM over the concatenated ``[x_t, h_{t-1}]``, gates split
  i|f|g|o; forget-gate bias initialized to +1 (standard);
- the backward unit's XLA path is ``jax.vjp`` of the scan (XLA derives
  BPTT); the numpy oracle implements explicit BPTT independently — the
  same oracle-vs-transform pattern as ``gd_conv``.

``return_sequence=False`` (default) emits the last hidden state
``(B, H)`` — the classification-head shape; ``True`` emits the whole
``(B, T, H)`` sequence for stacking.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from znicz_tpu.ops.nn_units import Forward, GradientDescentBase


def _sigmoid(xp, x):
    return 1.0 / (1.0 + xp.exp(-x))


class LSTM(Forward):
    """Single-layer LSTM over ``(batch, time, features)`` input."""

    def __init__(self, workflow, output_sample_shape=None,
                 units: int | None = None, name=None,
                 return_sequence: bool = False, **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        units = units if units is not None else output_sample_shape
        if units is None:
            raise ValueError(f"{self}: units (hidden size) required")
        self.units = int(units)
        self.return_sequence = bool(return_sequence)

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        if self.input is None or not self.input:
            raise AttributeError(f"{self}: input not linked yet")
        if len(self.input.shape) != 3:
            raise ValueError(f"{self}: input must be (batch, time, "
                             f"features), got {self.input.shape}")
        batch, steps, features = self.input.shape
        h = self.units
        if not self.weights:
            self.weights.reset(self.fill_array(
                (features + h, 4 * h), self.weights_filling,
                self.weights_stddev, fan_in=features + h))
        if self.include_bias and not self.bias:
            b = np.zeros(4 * h, dtype=np.float32)
            b[h:2 * h] = 1.0  # forget-gate bias: remember by default
            self.bias.reset(b)
        out_shape = (batch, steps, h) if self.return_sequence \
            else (batch, h)
        self.output.reset(np.zeros(out_shape, dtype=np.float32))
        self.init_vectors(self.input, self.output, self.weights,
                          self.bias)

    # -- one step (xp-generic) ------------------------------------------
    def _step(self, xp, x_t, h_prev, c_prev, w, b):
        z = self.mxu_dot(xp, xp.concatenate([x_t, h_prev], axis=1), w)
        if b is not None:
            z = z + b
        hsz = self.units
        i = _sigmoid(xp, z[:, 0 * hsz:1 * hsz])
        f = _sigmoid(xp, z[:, 1 * hsz:2 * hsz])
        g = xp.tanh(z[:, 2 * hsz:3 * hsz])
        o = _sigmoid(xp, z[:, 3 * hsz:4 * hsz])
        c = f * c_prev + i * g
        h = o * xp.tanh(c)
        return h, c, (i, f, g, o)

    # -- XLA: one lax.scan over time ------------------------------------
    def xla_forward(self, x, w, b):
        batch, steps, _ = x.shape
        h0 = jnp.zeros((batch, self.units), jnp.float32)
        c0 = jnp.zeros((batch, self.units), jnp.float32)

        def step(carry, x_t):
            h_prev, c_prev = carry
            h, c, _ = self._step(jnp, x_t, h_prev, c_prev, w, b)
            return (h, c), h

        (h_last, _), hs = jax.lax.scan(
            step, (h0, c0), jnp.swapaxes(x, 0, 1))
        if self.return_sequence:
            return jnp.swapaxes(hs, 0, 1)
        return h_last

    def xla_run(self) -> None:
        b = self.bias.devmem if self.include_bias else None
        self.output.devmem = self.xla_forward(
            self.input.devmem, self.weights.devmem, b)

    # -- autoregressive decode (round 12, serving.decode) ---------------
    def xla_prefill(self, x, w, b, length=None):
        """Scan the prompt and ALSO return the final recurrent state:
        (B, T, F) → ``(y, h, c)`` with h/c shaped (B, H) — the decode
        cache for a recurrent layer IS its carry.

        ``length`` (optional (B,) int32): per-sequence true prompt
        length for right-padded prompts — steps at ``t >= length``
        hold the carry instead of folding padded garbage into it.
        """
        batch, steps, _ = x.shape
        h0 = jnp.zeros((batch, self.units), jnp.float32)
        c0 = jnp.zeros((batch, self.units), jnp.float32)

        def step(carry, inp):
            h_prev, c_prev = carry
            t, x_t = inp
            h, c, _ = self._step(jnp, x_t, h_prev, c_prev, w, b)
            if length is not None:
                live = (t < length)[:, None]
                h = jnp.where(live, h, h_prev)
                c = jnp.where(live, c, c_prev)
            return (h, c), h

        (h_last, c_last), hs = jax.lax.scan(
            step, (h0, c0),
            (jnp.arange(steps), jnp.swapaxes(x, 0, 1)))
        y = jnp.swapaxes(hs, 0, 1) if self.return_sequence else h_last
        return y, h_last, c_last

    def xla_decode_step(self, x, h, c, w, b):
        """One incremental token: (B, F) input + (B, H) carry →
        ``(y, h, c)`` — the recurrent analogue of attention's cached
        step (state read/written in place of a position-indexed
        page)."""
        if x.ndim == 3:
            x = x.reshape(x.shape[0], -1)
        h, c, _ = self._step(jnp, x.astype(jnp.float32), h, c, w, b)
        return h, h, c

    # -- numpy oracle: explicit loop ------------------------------------
    def numpy_run(self) -> None:
        self.input.map_read()
        self.weights.map_read()
        b = None
        if self.include_bias:
            self.bias.map_read()
            b = self.bias.mem
        x = self.input.mem.astype(np.float32)
        w = self.weights.mem
        batch, steps, _ = x.shape
        h = np.zeros((batch, self.units), np.float32)
        c = np.zeros((batch, self.units), np.float32)
        hs = np.zeros((batch, steps, self.units), np.float32)
        for t in range(steps):
            h, c, _ = self._step(np, x[:, t], h, c, w, b)
            hs[:, t] = h
        self.output.map_invalidate()
        self.output.mem[...] = hs if self.return_sequence else h


class GDLSTM(GradientDescentBase):
    """LSTM backward: explicit BPTT oracle vs ``jax.vjp``-of-scan."""

    MATCHES = (LSTM,)

    def __init__(self, workflow, name=None, **kwargs):
        super().__init__(workflow, name=name, **kwargs)
        self.forward_unit: LSTM | None = None

    def initialize(self, device=None, **kwargs) -> None:
        if self.input is None or not self.input:
            raise AttributeError(f"{self}: input not linked yet")
        super().initialize(device=device, **kwargs)
        self.init_vectors(self.err_input, self.err_output, self.input,
                          self.output, self.weights, self.bias)

    # -- XLA path -------------------------------------------------------
    def xla_run(self) -> None:
        fwd = self.forward_unit
        x = self.input.devmem
        w = self.weights.devmem
        has_bias = self.bias is not None and self.bias
        b = self.bias.devmem if has_bias else None
        _, vjp = jax.vjp(lambda xx, ww, bb: fwd.xla_forward(xx, ww, bb),
                         x, w, b)
        grad_x, grad_w, grad_b = vjp(self.err_output.devmem)
        if self.need_err_input:
            self.err_input.devmem = grad_x
        self._apply_weights_xla(grad_w)
        if has_bias:
            self._apply_bias_xla(grad_b)

    # -- numpy oracle: explicit BPTT ------------------------------------
    def numpy_run(self) -> None:
        fwd = self.forward_unit
        for vec in (self.err_output, self.input):
            vec.map_read()
        self.weights.map_write()
        has_bias = self.bias is not None and self.bias
        b = None
        if has_bias:
            self.bias.map_write()
            b = self.bias.mem
        x = self.input.mem.astype(np.float32)
        w = self.weights.mem
        err = self.err_output.mem
        batch, steps, features = x.shape
        hsz = fwd.units

        # forward replay caching per-step state (recompute-in-bwd)
        h = np.zeros((batch, hsz), np.float32)
        c = np.zeros((batch, hsz), np.float32)
        cache = []
        for t in range(steps):
            h_prev, c_prev = h, c
            h, c, (i, f, g, o) = fwd._step(np, x[:, t], h_prev, c_prev,
                                           w, b)
            cache.append((h_prev, c_prev, c, i, f, g, o))

        grad_w = np.zeros_like(w)
        grad_b = np.zeros(4 * hsz, np.float32)
        grad_x = np.zeros_like(x)
        dh = np.zeros((batch, hsz), np.float32)
        dc = np.zeros((batch, hsz), np.float32)
        for t in reversed(range(steps)):
            h_prev, c_prev, c_t, i, f, g, o = cache[t]
            dh_t = dh + (err[:, t] if fwd.return_sequence
                         else (err if t == steps - 1 else 0.0))
            tc = np.tanh(c_t)
            do = dh_t * tc
            dc_t = dc + dh_t * o * (1.0 - tc * tc)
            di = dc_t * g
            df = dc_t * c_prev
            dg = dc_t * i
            dz = np.concatenate([
                di * i * (1.0 - i), df * f * (1.0 - f),
                dg * (1.0 - g * g), do * o * (1.0 - o)], axis=1)
            xc = np.concatenate([x[:, t], h_prev], axis=1)
            grad_w += xc.T @ dz
            grad_b += dz.sum(axis=0)
            dxc = dz @ w.T
            grad_x[:, t] = dxc[:, :features]
            dh = dxc[:, features:]
            dc = dc_t * f
        if self.need_err_input:
            self.err_input.map_invalidate()
            self.err_input.mem[...] = grad_x
        self._apply_weights_np(grad_w)
        if has_bias:
            self._apply_bias_np(grad_b)
