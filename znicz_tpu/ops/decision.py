"""Decision units: end-of-minibatch bookkeeping and stop logic
(reference: ``znicz/decision.py``).

A Decision unit runs on the host every minibatch, after the evaluator:

- accumulates per-class error statistics for the epoch;
- at epoch end compares validation error against the best seen,
  raising ``improved`` (the Snapshotter's trigger) and resetting the
  patience counter;
- raises ``complete`` when ``max_epochs`` is reached or validation has
  not improved for ``fail_iterations`` epochs — ``complete`` gates the
  workflow's end point.

This is control plane by design: the only device→host traffic is the
evaluator's scalar metric (``n_err`` / ``metrics``) per step.
"""

from __future__ import annotations

import time

import numpy as np

from znicz_tpu.loader.base import CLASS_NAME, TRAIN, VALID
from znicz_tpu.memory import Vector
from znicz_tpu.mutable import Bool
from znicz_tpu.observe import metrics as _metrics
from znicz_tpu.observe import tracing as _tracing
from znicz_tpu.units import Unit


class DecisionBase(Unit):
    def __init__(self, workflow, name: str | None = None,
                 max_epochs: int | None = None,
                 fail_iterations: int = 100,
                 **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.max_epochs = max_epochs
        self.fail_iterations = fail_iterations
        self.complete = Bool(False)
        self.improved = Bool(False)
        self.epoch_ended = Bool(False)  # mirrored for side-chain gating
        # linked from loader by the workflow builder:
        self.loader = None
        self._epochs_without_improvement = 0
        self._epoch_t0_us: float | None = None  # telemetry span base

    def on_epoch_ended(self) -> None:
        """Subclass hook: finalize epoch stats, update improved flag."""

    def run(self) -> None:
        loader = self.loader
        if self._epoch_t0_us is None:
            self._epoch_t0_us = _tracing.now_us()
        self.improved.value = False
        self.epoch_ended.value = False
        self.accumulate_minibatch()
        if loader.epoch_ended:
            self.on_epoch_ended()
            self.epoch_ended.value = True
            if _metrics.enabled():
                # epoch boundaries are only known here, so the epoch
                # span is recorded retroactively: one "X" event per
                # epoch over the device lanes in a merged timeline
                now = _tracing.now_us()
                wf = self.workflow
                wf_name = wf.name if wf is not None else "?"
                _tracing.TRACER.complete(
                    f"epoch:{int(loader.epoch_number)}",
                    self._epoch_t0_us, now, cat="epoch",
                    workflow=wf_name)
                self._epoch_t0_us = now
                _metrics.epochs_total(wf_name).inc()
            if self.improved:
                self._epochs_without_improvement = 0
            else:
                self._epochs_without_improvement += 1
            epochs_done = loader.epoch_number + 1
            if self.max_epochs is not None and epochs_done >= self.max_epochs:
                self.complete.value = True
            if self._epochs_without_improvement >= self.fail_iterations:
                self.info("no improvement for %d epochs — stopping",
                          self._epochs_without_improvement)
                self.complete.value = True
        self._resilience_tick()

    def _resilience_tick(self) -> None:
        """Round-11 host hook, every fire: translate the anomaly
        guard's on-device totals into registry counters, trigger the
        K-streak rollback, and stamp the last-step gauge /readyz turns
        into staleness.  One tiny d2h read per step when the guard is
        on; nothing otherwise."""
        wf = self.workflow
        if wf is None:
            return
        if _metrics.enabled():
            _metrics.last_step_timestamp(wf.name).set(time.time())
        if getattr(wf, "_step_hooks", None):
            # round 18: the elastic WorkerSupervisor's heartbeat /
            # preemption service point — one list check when detached
            wf.on_step_boundary()
        sentinel = getattr(wf, "integrity", None)
        if sentinel is not None:
            # round 19: the SDC sentinel's vote/audit cadence — one
            # counter increment per step until an interval fires
            sentinel.on_step()
        guard = getattr(wf, "anomaly_guard", None)
        if guard is None or not guard.is_initialized:
            return
        from znicz_tpu.utils.config import root
        # the guard state read is a tiny d2h sync; on a tunneled TPU
        # per-step path raise the interval to amortize it (rollback
        # detection latency grows to `interval` steps — the skip
        # itself is on-device and never waits for this read)
        interval = int(root.common.engine.get("anomaly_check_interval",
                                              1))
        self._guard_tick = getattr(self, "_guard_tick", 0) + 1
        if interval > 1 and self._guard_tick % interval:
            return
        streak, loss_t, grad_t = guard.read_state()
        base_l, base_g = guard._metric_base
        if loss_t > base_l:
            _metrics.step_anomalies(wf.name, "loss").inc(loss_t - base_l)
        if grad_t > base_g:
            _metrics.step_anomalies(wf.name, "grad").inc(grad_t - base_g)
        delta = (loss_t - base_l) + (grad_t - base_g)
        if delta > 0:
            # every anomalous step the guard absorbed (update skipped,
            # run continued) is a recovery the chaos dryrun attests
            _metrics.recoveries("anomaly_step").inc(delta)
            guard._metric_base = (loss_t, grad_t)
            self.warning("%d non-finite step(s) skipped by the "
                         "anomaly guard (streak %d)", delta, streak)
        k = int(root.common.engine.get("anomaly_rollback_k", 5))
        if streak >= k > 0 and hasattr(wf, "rollback_to_snapshot"):
            wf.rollback_to_snapshot(streak)

    def accumulate_minibatch(self) -> None:
        raise NotImplementedError


class DecisionGD(DecisionBase):
    """Classification decision driven by ``EvaluatorSoftmax.n_err``
    (reference: ``DecisionGD``)."""

    SNAPSHOT_ATTRS = ("epoch_n_err", "epoch_n_err_pt",
                      "min_validation_n_err", "min_validation_n_err_pt",
                      "min_train_n_err", "_epochs_without_improvement")

    def __init__(self, workflow, name: str | None = None, **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.evaluator = None  # linked: needs .n_err
        self.epoch_n_err = [0, 0, 0]          # running, current epoch
        self.epoch_loss = [None, None, None]  # mean CE per class, last epoch
        self.epoch_n_err_pt = [100.0, 100.0, 100.0]
        self.min_validation_n_err = None
        self.min_validation_n_err_pt = 100.0
        self.min_train_n_err = None
        # last epoch's per-class confusion matrices (filled when the
        # evaluator has compute_confusion enabled)
        self.confusion_matrixes = [None, None, None]
        # last COMPLETED epoch's error counts (epoch_n_err is a running
        # accumulator reset at each epoch end)
        self.last_epoch_n_err = [None, None, None]

    def accumulate_minibatch(self) -> None:
        # per-class accumulation happens ON DEVICE in the evaluator
        # (one host sync per epoch, not per step — see evaluator.py)
        pass

    def on_epoch_ended(self) -> None:
        loader = self.loader
        acc: Vector = self.evaluator.epoch_n_err
        acc.map_read()
        self.epoch_n_err = [int(x) for x in acc.mem]
        acc.map_invalidate()
        acc.mem[...] = 0  # uploaded on the next region fire
        loss_acc: Vector = getattr(self.evaluator, "epoch_loss", None)
        if isinstance(loss_acc, Vector) and loss_acc:
            loss_acc.map_read()
            # summed −log p(true) → mean per sample (the loss curve)
            self.epoch_loss = [
                float(loss_acc.mem[c]) / loader.class_lengths[c]
                if loader.class_lengths[c] else None for c in range(3)]
            loss_acc.map_invalidate()
            loss_acc.mem[...] = 0.0
        cm: Vector = getattr(self.evaluator, "confusion_matrix", None)
        if isinstance(cm, Vector) and cm:
            cm.map_read()
            self.confusion_matrixes = [np.array(cm.mem[c])
                                       for c in range(3)]
            cm.map_invalidate()
            cm.mem[...] = 0
        for cls in range(3):
            length = loader.class_lengths[cls]
            if length:
                self.epoch_n_err_pt[cls] = \
                    100.0 * self.epoch_n_err[cls] / length
        has_valid = loader.class_lengths[VALID] > 0
        n_err = self.epoch_n_err[VALID if has_valid else TRAIN]
        best = (self.min_validation_n_err if has_valid
                else self.min_train_n_err)
        if best is None or n_err < best:
            if has_valid:
                self.min_validation_n_err = n_err
                self.min_validation_n_err_pt = self.epoch_n_err_pt[VALID]
            else:
                self.min_train_n_err = n_err
            self.improved.value = True
        self.info(
            "epoch %d: %s", loader.epoch_number,
            "  ".join(f"{CLASS_NAME[c]} err {self.epoch_n_err[c]} "
                      f"({self.epoch_n_err_pt[c]:.2f}%)"
                      for c in range(3) if loader.class_lengths[c]))
        self.last_epoch_n_err = list(self.epoch_n_err)
        self.epoch_n_err = [0, 0, 0]


class DecisionMSE(DecisionBase):
    """Regression/autoencoder decision driven by
    ``EvaluatorMSE.metrics`` (reference: ``DecisionMSE``)."""

    SNAPSHOT_ATTRS = ("epoch_sse", "epoch_mse", "epoch_mse_history",
                      "min_validation_mse", "min_train_mse",
                      "_epochs_without_improvement")

    def __init__(self, workflow, name: str | None = None, **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.evaluator = None
        self.epoch_sse = [0.0, 0.0, 0.0]
        self.epoch_mse = [np.inf, np.inf, np.inf]
        #: per-class mse trajectory, one entry per finished epoch
        self.epoch_mse_history: list[list[float]] = [[], [], []]
        self.min_validation_mse = None
        self.min_train_mse = None

    def accumulate_minibatch(self) -> None:
        pass  # accumulated on device (evaluator.epoch_sse)

    def on_epoch_ended(self) -> None:
        loader = self.loader
        acc: Vector = self.evaluator.epoch_sse
        acc.map_read()
        self.epoch_sse = [float(x) for x in acc.mem]
        acc.map_invalidate()
        acc.mem[...] = 0
        for cls in range(3):
            length = loader.class_lengths[cls]
            if length:
                self.epoch_mse[cls] = self.epoch_sse[cls] / length
                self.epoch_mse_history[cls].append(self.epoch_mse[cls])
        has_valid = loader.class_lengths[VALID] > 0
        mse = self.epoch_mse[VALID if has_valid else TRAIN]
        best = self.min_validation_mse if has_valid else self.min_train_mse
        if best is None or mse < best:
            if has_valid:
                self.min_validation_mse = mse
            else:
                self.min_train_mse = mse
            self.improved.value = True
        self.info(
            "epoch %d: %s", loader.epoch_number,
            "  ".join(f"{CLASS_NAME[c]} mse {self.epoch_mse[c]:.6f}"
                      for c in range(3) if loader.class_lengths[c]))
        self.epoch_sse = [0.0, 0.0, 0.0]
