"""Sinusoidal positional encoding — companion to the attention family
(beyond the 2015 reference, which has no sequence models;
SURVEY.md §5.7 marks long-context machinery as this framework's
extension).

``y[b, t, d] = x[b, t, d] + PE[t, d]`` with the standard interleaved
sin/cos table.  Weightless and elementwise-additive, so the backward
is the identity pass-through; the table is baked into the jit region
as a constant (XLA folds the add into neighbors).  Sequence-parallel
friendly: positions are GLOBAL indices, so a time-sharded input adds
the correct table slice per shard (the table is computed from the
full length and sliced by the same sharding, handled by GSPMD).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from znicz_tpu.ops.nn_units import Forward, WeightlessGradientUnit


def sinusoid_table(t: int, d: int) -> np.ndarray:
    """The (T, D) encoding table: even dims sin, odd dims cos, with
    the 10000^(2i/d) wavelength ladder."""
    pos = np.arange(t, dtype=np.float32)[:, None]
    i = np.arange(d, dtype=np.float32)[None, :]
    angle = pos / np.power(10000.0, 2.0 * (i // 2) / d)
    table = np.where(i % 2 == 0, np.sin(angle), np.cos(angle))
    return table.astype(np.float32)


class PositionalEncoding(Forward):
    """Adds the sinusoidal table to a (B, T, D) input."""

    def __init__(self, workflow, scale: float = 1.0, name=None,
                 **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.scale = float(scale)
        self._table: np.ndarray | None = None

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        if self.input is None or not self.input:
            raise AttributeError(f"{self}: input not linked yet")
        if len(self.input.shape) != 3:
            raise ValueError(f"{self}: expected (batch, time, features) "
                             f"input, got {self.input.shape}")
        _, t, d = self.input.shape
        self._table = self.scale * sinusoid_table(t, d)
        self.output.reset(np.zeros(self.input.shape,
                                   dtype=self.output_store_dtype))
        self.inherit_model_shard(self.output)
        self.init_vectors(self.input, self.output)

    def numpy_run(self) -> None:
        self.input.map_read()
        self.output.map_invalidate()
        self.output.mem[...] = \
            self.input.mem.astype(np.float32) + self._table

    def xla_run(self) -> None:
        self.output.devmem = (
            self.input.devmem.astype(jnp.float32)
            + jnp.asarray(self._table))

    # -- autoregressive decode (round 12, serving.decode) ---------------
    def table_to(self, t: int, d: int) -> np.ndarray:
        """The scaled (t, D) table up to an arbitrary horizon —
        positions are GLOBAL indices, so a decode engine extends the
        training-time table to its ``max_t`` without retraining
        anything (the table is parameter-free)."""
        return self.scale * sinusoid_table(t, d)

    def xla_decode_step(self, x, pos, table):
        """Position-offset add for one incremental token: (B, 1, D)
        features + (B,) int32 positions + a baked (Tmax, D) table →
        ``x + PE[pos]`` per sequence (ragged positions — each decode
        lane sits at its own depth)."""
        return x.astype(jnp.float32) + table[pos][:, None, :]


class GDPositionalEncoding(WeightlessGradientUnit):
    """Backward of an additive constant: identity pass-through."""

    MATCHES = (PositionalEncoding,)

    def numpy_run(self) -> None:
        self.err_output.map_read()
        self.err_input.map_invalidate()
        self.err_input.mem[...] = self.err_output.mem

    def xla_run(self) -> None:
        self.err_input.devmem = self.err_output.devmem
