"""Signal accumulators: running histograms/ranges for diagnostics
(reference: ``znicz/accumulator.py`` — ``FixAccumulator`` over a fixed
bin range, ``RangeAccumulator`` tracking the observed min/max).

Host-side units: they read their input Vector between steps (wire on a
side chain or gate per-epoch) and keep numpy histogram state that
plotters or the metrics stream can consume.
"""

from __future__ import annotations

import numpy as np

from znicz_tpu.memory import Vector
from znicz_tpu.units import Unit


class FixAccumulator(Unit):
    """Histogram over a fixed ``[lo, hi]`` range with ``n_bins`` bins;
    out-of-range values clamp into the edge bins."""

    SNAPSHOT_ATTRS = ("n_observed",)

    def __init__(self, workflow, name: str | None = None,
                 lo: float = 0.0, hi: float = 1.0, n_bins: int = 30,
                 **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.input: Vector | None = None
        self.lo = float(lo)
        self.hi = float(hi)
        self.n_bins = int(n_bins)
        self.histogram = Vector(
            np.zeros(self.n_bins, dtype=np.int64),
            name=f"{self.name}.histogram")
        self.n_observed = 0

    @property
    def bin_centers(self) -> np.ndarray:
        edges = np.linspace(self.lo, self.hi, self.n_bins + 1)
        return 0.5 * (edges[:-1] + edges[1:])

    def reset(self) -> None:
        self.histogram.mem[...] = 0
        self.n_observed = 0

    def observe(self, values: np.ndarray) -> None:
        v = np.clip(np.asarray(values, dtype=np.float64).ravel(),
                    self.lo, self.hi)
        counts, _ = np.histogram(v, bins=self.n_bins,
                                 range=(self.lo, self.hi))
        self.histogram.mem += counts
        self.n_observed += v.size

    def run(self) -> None:
        if isinstance(self.input, Vector) and self.input:
            self.input.map_read()
            self.observe(np.asarray(self.input.mem))


class RangeAccumulator(Unit):
    """Tracks the running min/max of a signal and a histogram over the
    range seen so far (rebinned as the range grows)."""

    SNAPSHOT_ATTRS = ("x_min", "x_max", "n_observed")

    def __init__(self, workflow, name: str | None = None,
                 n_bins: int = 30, max_retained: int = 1 << 20,
                 **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.input: Vector | None = None
        self.n_bins = int(n_bins)
        self.x_min = np.inf
        self.x_max = -np.inf
        self.n_observed = 0
        self.histogram = Vector(
            np.zeros(self.n_bins, dtype=np.int64),
            name=f"{self.name}.histogram")
        #: exact-rebin buffer, bounded: once more than
        #: ``max_retained`` values have been seen, retention stops and
        #: later range growth rebins approximately from bin centers
        self.max_retained = int(max_retained)
        self._samples: list[np.ndarray] | None = []
        self._retained = 0

    @property
    def bin_centers(self) -> np.ndarray:
        lo = self.x_min if np.isfinite(self.x_min) else 0.0
        hi = self.x_max if np.isfinite(self.x_max) else 1.0
        edges = np.linspace(lo, hi, self.n_bins + 1)
        return 0.5 * (edges[:-1] + edges[1:])

    def reset(self) -> None:
        self.x_min, self.x_max = np.inf, -np.inf
        self.n_observed = 0
        self.histogram.mem[...] = 0
        self._samples = []
        self._retained = 0

    def observe(self, values: np.ndarray) -> None:
        v = np.asarray(values, dtype=np.float64).ravel()
        if v.size == 0:
            return
        lo, hi = float(v.min()), float(v.max())
        grew = lo < self.x_min or hi > self.x_max
        old_min, old_max = self.x_min, self.x_max
        self.x_min = min(self.x_min, lo)
        self.x_max = max(self.x_max, hi)
        if self._samples is not None:
            self._samples.append(v)
            self._retained += v.size
        self.n_observed += v.size
        if grew:  # rebin everything over the widened range
            if self._samples is not None:  # exact
                self.histogram.mem[...] = 0
                for s in self._samples:
                    self._bin(s)
            else:  # approximate: redistribute old counts by center
                self._rebin_approx(old_min, old_max)
                self._bin(v)
        else:
            self._bin(v)
        if self._samples is not None and self._retained > self.max_retained:
            self._samples = None  # memory bound reached

    def _rebin_approx(self, old_min: float, old_max: float) -> None:
        counts = np.array(self.histogram.mem, copy=True)
        self.histogram.mem[...] = 0
        if not np.isfinite(old_min) or counts.sum() == 0:
            return
        old_hi = old_max if old_max > old_min else old_min + 1.0
        edges = np.linspace(old_min, old_hi, self.n_bins + 1)
        centers = 0.5 * (edges[:-1] + edges[1:])
        new_hi = (self.x_max if self.x_max > self.x_min
                  else self.x_min + 1.0)
        idx = np.clip(((centers - self.x_min) / (new_hi - self.x_min)
                       * self.n_bins).astype(np.int64), 0, self.n_bins - 1)
        np.add.at(self.histogram.mem, idx, counts)

    def _bin(self, v: np.ndarray) -> None:
        hi = self.x_max if self.x_max > self.x_min else self.x_min + 1.0
        counts, _ = np.histogram(v, bins=self.n_bins,
                                 range=(self.x_min, hi))
        self.histogram.mem += counts

    def run(self) -> None:
        if isinstance(self.input, Vector) and self.input:
            self.input.map_read()
            self.observe(np.asarray(self.input.mem))
