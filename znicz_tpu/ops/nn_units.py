"""NN base classes: Forward, GradientDescentBase, fwd↔bwd pairing.

Rebuilds the reference's ``znicz/nn_units.py``:

- :class:`Forward` — base of all forward units: ``input`` (linked),
  ``output``, ``weights``, ``bias`` Vectors; weight-init fill schemes;
- :class:`GradientDescentBase` — base of all backward units:
  ``err_output`` (from the next unit / evaluator), ``err_input`` (to
  the previous one), shared ``weights``/``bias``, learning rate,
  momentum (``gradient_moment``), L1/L2 decay (``weights_decay``,
  ``l1_vs_l2``), and momentum accumulators;
- the ``MatchingObject`` pairing: backward classes declare
  ``MATCHES = (ForwardClass, …)`` and a registry lets
  ``StandardWorkflow`` auto-build the backward chain
  (reference: the ``MatchingObject`` metaclass).

TPU-first deltas:

- weights are stored ``(in_features, out_features)`` so the forward
  GEMM is ``x @ W`` with no transpose (the reference stored
  ``(out, in)`` for its OpenCL tiles; XLA prefers plain layouts and
  fuses the rest);
- the parameter update runs on device inside the jit region, and the
  gradient is folded across the data-parallel mesh axis with
  ``lax.pmean`` exactly where the reference called
  ``generate_data_for_master``/``apply_data_from_slave``
  (see :mod:`znicz_tpu.parallel`).
"""

from __future__ import annotations

from typing import Type

import numpy as np

import jax
import jax.numpy as jnp

from znicz_tpu.accelerated_units import AcceleratedUnit
from znicz_tpu.memory import Vector
from znicz_tpu.parallel.axis import maybe_pmean
from znicz_tpu.utils import prng


# ----------------------------------------------------------------------
# fwd ↔ bwd pairing registry (reference: MatchingObject metaclass)
# ----------------------------------------------------------------------
_GD_FOR_FORWARD: dict[type, type] = {}


class MatchingObject(type):
    """Metaclass registering backward units against their forwards via
    a ``MATCHES`` tuple on the backward class."""

    def __init__(cls, name, bases, namespace) -> None:
        super().__init__(name, bases, namespace)
        for fwd_cls in namespace.get("MATCHES", ()):
            _GD_FOR_FORWARD[fwd_cls] = cls


def gd_for(forward_cls: type) -> Type["GradientDescentBase"]:
    """The backward class paired with ``forward_cls`` (walks the MRO so
    subclasses inherit their parent's pairing unless they override)."""
    for klass in forward_cls.__mro__:
        gd = _GD_FOR_FORWARD.get(klass)
        if gd is not None:
            return gd
    raise KeyError(f"no gradient unit registered for {forward_cls.__name__}")


# ----------------------------------------------------------------------
# Forward base
# ----------------------------------------------------------------------
class Forward(AcceleratedUnit):
    """Base forward unit (reference: ``znicz/nn_units.py`` Forward).

    Subclasses set ``self.output`` from ``self.input`` in their run
    methods; parameters live in ``weights``/``bias`` Vectors shared
    with the paired backward unit.
    """

    #: Vector attributes the exporter serializes; units with extra
    #: parameter pairs (attention's output projection) extend this
    EXPORT_PARAMS: tuple = ("weights", "bias")

    def __init__(self, workflow, name: str | None = None,
                 weights_filling: str = "uniform",
                 weights_stddev: float | None = None,
                 bias_filling: str = "uniform",
                 bias_stddev: float | None = None,
                 include_bias: bool = True,
                 **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.input: Vector | None = None  # usually replaced by link_attrs
        self.output = Vector(name=f"{self.name}.output", batch_major=True)
        self.weights = Vector(name=f"{self.name}.weights")
        self.bias = Vector(name=f"{self.name}.bias")
        self.weights_filling = weights_filling
        self.weights_stddev = weights_stddev
        self.bias_filling = bias_filling
        self.bias_stddev = bias_stddev
        self.include_bias = include_bias

    # -- weight init ----------------------------------------------------
    def fill_array(self, arr_shape, filling: str, stddev: float | None,
                   fan_in: int) -> np.ndarray:
        gen = prng.get()
        if stddev is None:
            stddev = 1.0 / max(1.0, np.sqrt(fan_in))
        if filling == "uniform":
            return gen.fill_uniform(arr_shape, -stddev, stddev,
                                    dtype=np.float32)
        if filling == "gaussian":
            return gen.fill_normal(arr_shape, 0.0, stddev, dtype=np.float32)
        if filling == "constant":
            return np.full(arr_shape, stddev, dtype=np.float32)
        # variance-preserving fillings (stddev argument ignored):
        # the reference's fixed-stddev fillings assume shallow nets or
        # ImageNet-scale horizons; deep ReLU stacks need fan-scaled
        # init to keep forward/backward variance O(1)
        if filling == "he":  # ReLU family
            return gen.fill_normal(arr_shape, 0.0,
                                   float(np.sqrt(2.0 / max(1, fan_in))),
                                   dtype=np.float32)
        if filling == "xavier":  # tanh/sigmoid/linear family
            return gen.fill_normal(arr_shape, 0.0,
                                   float(np.sqrt(1.0 / max(1, fan_in))),
                                   dtype=np.float32)
        raise ValueError(f"unknown filling '{filling}'")

    @property
    def current_batch(self) -> int:
        return self.input.shape[0]

    @property
    def output_store_dtype(self) -> np.dtype:
        """Storage dtype for this unit's ``output`` — the activation
        policy (:attr:`AcceleratedUnit.act_store_dtype`) unless a
        subclass pins f32 (e.g. softmax probabilities feeding the
        evaluator)."""
        return self.act_store_dtype

    def inherit_model_shard(self, *vectors) -> None:
        """Declare that same-shaped output vectors shard like the
        input.  Every shape-preserving (elementwise) forward should
        call this after allocating its outputs so tensor-parallel
        feature sharding passes through instead of silently degrading
        to replicated (which would make GSPMD all-gather the
        activations between a column and row layer every step).
        Declarative since round 17: each vector gets an exact-path
        rule in the workflow's partition table derived from the
        input's resolved placement (``partition.like``)."""
        from znicz_tpu.parallel import partition
        for vec in vectors:
            placement = partition.like(self.input,
                                       batch_major=vec.batch_major)
            partition.declare(self, vec, placement)


# ----------------------------------------------------------------------
# GradientDescent base
# ----------------------------------------------------------------------
class GradientDescentBase(AcceleratedUnit, metaclass=MatchingObject):
    """Base backward unit (reference: ``znicz/nn_units.py``
    GradientDescentBase).

    Update rule (matching the reference's momentum + L1/L2 decay, plus
    optional per-tensor gradient-norm clipping):

    .. code-block:: text

        ĝ   = dL/dW · min(1, gradient_clip / ‖dL/dW‖₂)      (clip > 0)
        g   = ĝ + weights_decay·((1−l1_vs_l2)·W + ½·l1_vs_l2·sign(W))
        acc = gradient_moment·acc − learning_rate·g
        W  += acc

    In data-parallel runs ``dL/dW`` is folded over the ``data`` mesh
    axis before the update — the synchronous SPMD replacement for the
    reference's master-side gradient fold.  On meshes with a data axis
    of size > 1 the fold+update pair runs **ZeRO-1 sharded** by
    default (``root.common.engine.zero1``, auto): gradients are
    reduce-scattered, the update and the STORED momentum state live on
    each chip's 1/N shard, and updated params are all-gathered back —
    same math, half the update-path comm bytes, optimizer memory cut
    by the mesh size (:meth:`_apply_param_zero1`).
    """

    MATCHES: tuple = ()
    #: subclasses that require a paired forward / a linked input set
    #: these to get the labeled error instead of a raw AttributeError
    REQUIRES_FORWARD_UNIT = False
    REQUIRES_INPUT = False

    def __init__(self, workflow, name: str | None = None,
                 learning_rate: float = 0.01,
                 learning_rate_bias: float | None = None,
                 weights_decay: float = 0.0,
                 weights_decay_bias: float = 0.0,
                 l1_vs_l2: float = 0.0,
                 gradient_moment: float = 0.0,
                 gradient_moment_bias: float | None = None,
                 gradient_clip: float = 0.0,
                 need_err_input: bool = True,
                 **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.learning_rate = learning_rate
        self.learning_rate_bias = (learning_rate if learning_rate_bias is None
                                   else learning_rate_bias)
        self.weights_decay = weights_decay
        self.weights_decay_bias = weights_decay_bias
        self.l1_vs_l2 = l1_vs_l2
        self.gradient_moment = gradient_moment
        self.gradient_moment_bias = (gradient_moment
                                     if gradient_moment_bias is None
                                     else gradient_moment_bias)
        #: max L2 norm per parameter tensor for the (mesh-folded) raw
        #: gradient; 0 disables.  Applied before decay, so the clip
        #: bounds the DATA term only — the regularizer stays exact.
        self.gradient_clip = gradient_clip
        self.need_err_input = need_err_input
        #: resolved at initialize (parallel.mesh.zero1_choice): True =
        #: the update runs ZeRO-1 sharded over the mesh's data axis
        self._zero1 = False
        self._grad_comms_bf16 = False
        #: anomaly-guard flag vector ([running_ok, loss_ok], linked by
        #: StandardWorkflow to the AnomalyGuard's step_flags); when
        #: set, every parameter update folds isfinite(‖grad‖²) into
        #: the running flag and applies through where(ok, new, old) —
        #: a non-finite step leaves weights and momentum untouched.
        #: None (the default for standalone units) = exact seed path.
        self.anomaly_flag: Vector | None = None
        #: round 19 SDC sentinel hooks (linked by StandardWorkflow to
        #: the guard's vectors): ``sdc_fingerprint`` receives this
        #: unit's sub-sampled gradient + post-update parameter
        #: checksums; ``sdc_inject`` is the chaos leaf arming the
        #: ``sdc.flip_param`` / ``sdc.flip_grad`` corruptions (an
        #: exact ×1.0 identity when disarmed — never recompiles).
        self.sdc_fingerprint: Vector | None = None
        self.sdc_inject: Vector | None = None
        #: exact Vector set the fingerprint fold covered, in fold
        #: order — the sentinel's host recompute and the shadow audit
        #: enumerate the SAME tensors from this (populated on both
        #: backends whether or not the fingerprint vector is linked)
        self._fp_folded: dict[int, Vector] = {}
        # linked from the paired forward unit by StandardWorkflow:
        self.input: Vector | None = None
        self.output: Vector | None = None
        self.weights: Vector | None = None
        self.bias: Vector | None = None
        # linked from the next backward unit / evaluator:
        self.err_output: Vector | None = None
        self.err_input = Vector(name=f"{self.name}.err_input",
                                batch_major=True)
        # momentum slots
        self.accumulated_gradient_weights = Vector(
            name=f"{self.name}.acc_grad_w")
        self.accumulated_gradient_bias = Vector(
            name=f"{self.name}.acc_grad_b")
        #: round 20 microbatch gradient-accumulation buffers, keyed by
        #: parameter Vector identity — allocated at initialize when
        #: ``root.common.engine.grad_accum > 1`` (f32, replicated; the
        #: ``acc_micro_*`` slot names ride the default ``acc_\w+``
        #: partition rule).  During an ``("accum", M)`` region phase
        #: every gradient sums in here instead of updating parameters;
        #: the ``("apply", M)`` phase folds the mean through the
        #: unchanged update path (see ``_apply_param_xla``).
        self._micro_accum: dict[int, Vector] = {}
        # device-resident [lr, lr_bias]; only populated when a
        # LearningRateAdjust unit schedules this GD unit — a region
        # leaf, so schedule changes never recompile the step program
        self.lr_state = Vector(name=f"{self.name}.lr_state")

    def initialize(self, device=None, **kwargs) -> None:
        if self.REQUIRES_FORWARD_UNIT \
                and getattr(self, "forward_unit", None) is None:
            raise ValueError(
                f"{self}: forward_unit not set — assign the paired "
                f"forward unit before initialize (link_attrs does not "
                f"do this)")
        if self.REQUIRES_INPUT and (self.input is None
                                    or not self.input):
            raise AttributeError(f"{self}: input not linked yet")
        super().initialize(device=device, **kwargs)
        # err_input allocation lives here (post-super, device resolved)
        # so its dtype can follow the activation storage policy
        if (self.need_err_input and self.input is not None
                and self.input and not self.err_input):
            self.err_input.reset(np.zeros(self.input.shape,
                                          dtype=self.act_store_dtype))
            # the error cotangent shards like the tensor it's the
            # gradient of (tensor parallelism: feature-sharded
            # activations get feature-sharded errors) — declared as a
            # rule derived from the input's resolved placement
            from znicz_tpu.parallel import partition
            partition.declare(self, self.err_input,
                              partition.like(self.input,
                                             batch_major=True),
                              slot="err_input")
        if not self.need_err_input and (self.weights is None
                                        or not self.weights):
            # weightless AND nothing upstream wants the error: the unit
            # has no observable effect — skip it entirely (scheduler
            # and jit region both honor gate_skip)
            from znicz_tpu.mutable import Bool
            self.gate_skip = Bool(True)
        from znicz_tpu.parallel.mesh import zero1_choice
        from znicz_tpu.utils.config import root
        self._zero1 = zero1_choice(self.device)
        # second convergence-gated comms lever: reduce-scatter the
        # weight gradients in bf16 (half the ICI bytes again).
        # Default OFF until a multi-chip A/B + convergence band lands
        # (BF16_CONVERGENCE.json, `bfloat16_gradcomms` arm).
        self._grad_comms_bf16 = (
            self._zero1
            and bool(root.common.engine.get("bf16_grad_comms", False)))
        # round 21: fp8 matmul lever (engine.fp8_matmul, default OFF
        # until the QUANT_BENCH fp8 convergence A/B and the FP8_TPU
        # chip arm clear it — same gating shape as bf16_grad_comms).
        # Forward/backward matmuls take float8_e4m3fn inputs via
        # mxu_dot (f32 accumulation) and the weight gradient
        # round-trips through fp8 before the optimizer sees it.
        self._fp8_matmul = bool(
            root.common.engine.get("fp8_matmul", False))
        if self.gradient_moment or self.gradient_moment_bias:
            if self.weights is not None and self.weights:
                self._alloc_accumulator(self.accumulated_gradient_weights,
                                        self.weights)
            if (self.bias is not None and self.bias
                    and self.gradient_moment_bias):
                self._alloc_accumulator(self.accumulated_gradient_bias,
                                        self.bias)
            self.init_vectors(self.accumulated_gradient_weights,
                              self.accumulated_gradient_bias)
        self._alloc_micro_accum()

    def _micro_accum_params(self) -> list:
        """``(suffix, parameter Vector)`` pairs covered by microbatch
        gradient accumulation; units with extra parameter pairs
        (attention's output projection) extend this the same way they
        extend ``EXPORT_PARAMS``."""
        return [("w", self.weights), ("b", self.bias)]

    def _alloc_micro_accum(self) -> None:
        """Allocate the round-20 microbatch gradient-accumulation
        buffers when ``root.common.engine.grad_accum > 1``: one f32
        zero buffer per parameter tensor, registered as a region leaf
        (the ``acc_micro_*`` attribute makes ``region_vectors`` pick
        it up) and mapped from the parameter's identity so
        ``_apply_param_xla`` finds it during accumulation phases.
        Replicated placement (the ``acc_\\w+`` default rule): the
        buffer holds the logically-global microbatch gradient sum;
        ZeRO-1's reduce-scatter engages once, at apply."""
        from znicz_tpu.utils.config import root
        n_micro = int(root.common.engine.get("grad_accum", 1) or 1)
        if (n_micro < 2 or self.device is None
                or self.device.is_host_only):
            return
        if self.weights is None or not self.weights:
            return  # weightless backward: nothing accumulates
        for suffix, param in self._micro_accum_params():
            if param is None or not param:
                continue
            attr = f"micro_accum_{suffix}"
            vec = getattr(self, attr, None)
            if vec is None:
                vec = Vector(name=f"{self.name}.acc_micro_{suffix}")
                setattr(self, attr, vec)
            if not vec:
                vec.reset(np.zeros(tuple(param.shape),
                                   dtype=np.float32))
            self._micro_accum[id(param)] = vec
            self.init_vectors(vec)

    def _alloc_accumulator(self, acc_vec: Vector, param_vec: Vector) -> None:
        """Allocate a momentum accumulator for ``param_vec``: storage
        dtype from the bf16-optimizer-state policy, model-axis sharding
        inherited, and — under ZeRO-1 — a data-sharded dim plus zero
        padding so each chip STORES only 1/N of the state.  The
        (dim, pad) choice is a RULE CONSEQUENCE now: the unit declares
        a :class:`~znicz_tpu.parallel.partition.Zero1` placement for
        the accumulator's leaf path and the engine derives the
        sharded layout from the logical shape; units with extra
        parameter pairs (attention's output projection) call this for
        their own accumulators so every lever composes identically."""
        from znicz_tpu.parallel import partition
        shape = tuple(param_vec.shape)
        from znicz_tpu.parallel.axis import MODEL_AXIS
        model_dim = getattr(param_vec, "model_shard_dim", None)
        model_axis = getattr(param_vec, "model_shard_axis",
                             MODEL_AXIS) or MODEL_AXIS
        if self._zero1:
            placement = partition.Zero1(model_dim)
        elif model_dim is None:
            placement = partition.REPLICATED
        else:
            placement = partition.model_sharded(model_dim,
                                                axis=model_axis)
        resolved = partition.declare(self, acc_vec, placement,
                                     logical_shape=shape)
        acc_vec.reset(np.zeros(resolved.padded_shape(),
                               dtype=self.opt_state_dtype))
        partition.stamp(self, acc_vec, resolved,
                        pad_applied=bool(resolved.data_shard_pad))

    @property
    def opt_state_dtype(self) -> np.dtype:
        """STORAGE dtype for the momentum accumulators.

        In bf16 mode the update fusions over the big FC state are
        bandwidth-bound on ~600 MB/step of optimizer-state traffic
        (PERF.md round 4: measured +1.0% img/s from halving it; round
        5 validated the precision against moving error curves —
        BF16_CONVERGENCE.json's ``bfloat16_optstate`` arm).  The
        momentum MATH stays f32 (the accumulator is upcast in the
        update expression; only its storage rounds) — same
        storage-vs-compute split as ``act_store_dtype``.  Opt out:
        ``root.common.engine.bf16_optimizer_state = False``.
        """
        from znicz_tpu.utils.config import root
        if (self.device is not None
                and not self.device.is_host_only
                and self.device.compute_dtype == np.dtype("bfloat16")
                and bool(root.common.engine.get("bf16_optimizer_state",
                                                True))):
            import jax.numpy as jnp
            return np.dtype(jnp.bfloat16)
        return np.dtype(np.float32)

    # -- learning-rate source (scheduled vector or static float) --------
    def _lr(self, xla: bool):
        if self.lr_state:
            return (self.lr_state.devmem[0] if xla
                    else float(self.lr_state.mem[0]))
        return self.learning_rate

    def _lr_bias(self, xla: bool):
        if self.lr_state:
            return (self.lr_state.devmem[1] if xla
                    else float(self.lr_state.mem[1]))
        return self.learning_rate_bias

    # -- shared update math (xp = np or jnp) ----------------------------
    def _regularized(self, xp, grad, weights, decay: float):
        if not decay:
            return grad
        l1 = self.l1_vs_l2
        reg = (1.0 - l1) * weights
        if l1:
            reg = reg + 0.5 * l1 * xp.sign(weights)
        return grad + decay * reg

    def _clipped(self, xp, grad):
        """Per-tensor L2 gradient-norm clipping (``gradient_clip``).
        The norm is a full-tensor reduction: under ZeRO-1 it runs on
        the scattered shard (partial sums + one scalar all-reduce),
        so clipping does not resurrect the full-gradient all-reduce."""
        clip = self.gradient_clip
        if not clip:
            return grad
        g32 = grad.astype(np.float32) if xp is np \
            else grad.astype(jnp.float32)
        norm = xp.sqrt(xp.sum(g32 * g32))
        scale = xp.minimum(1.0, clip / xp.maximum(norm, 1e-30))
        return grad * scale

    # -- round 19: SDC fingerprint fold + seeded corruption ------------
    def _fp_register(self, vec: Vector) -> None:
        """Record that ``vec`` is covered by the fingerprint fold (the
        sentinel's host recompute and the shadow audit enumerate
        exactly this set, in this order)."""
        self._fp_folded.setdefault(id(vec), vec)

    def _sdc_scales(self, xla: bool):
        """The armed ``(param_scale, grad_scale)`` multiplier deltas,
        or None when the chaos leaf is absent (the common case)."""
        inj = self.sdc_inject
        if inj is None or not inj:
            return None
        return inj.devmem if xla else inj.mem

    def _fold_fingerprint(self, xp, slot: int, value) -> None:
        """Fold one tensor's sub-sampled checksum into the guard's
        shared fingerprint (slot 0 = post-update params, slot 1 =
        folded gradients).  A no-op unless StandardWorkflow linked the
        vector — standalone units keep the exact seed path."""
        fpv = self.sdc_fingerprint
        if fpv is None or not fpv:
            return
        from znicz_tpu.resilience.integrity import tensor_fingerprint
        contrib = tensor_fingerprint(xp, value)
        if xp is np:
            fpv.mem[slot] += np.float32(contrib)
        else:
            fpv.devmem = fpv.devmem.at[slot].add(contrib)

    def _np_grad_ok(self, grad: np.ndarray) -> bool:
        """Numpy-path mirror of the guard's on-device finite check:
        AND this gradient's ‖g‖² finiteness into the shared flag and
        return whether the update may apply."""
        guard = self.anomaly_flag
        if guard is None or not guard:
            return True
        own = bool(np.isfinite(
            np.sum(np.square(grad, dtype=np.float64))))
        ok = own and guard.mem[0] > 0.5
        if not own:
            guard.mem[0] = 0.0
        return ok

    # ``vec``/``acc`` parameters let units with EXTRA parameter pairs
    # (e.g. attention's output projection) reuse the exact update rule
    # instead of copy-pasting the momentum/decay/clip math
    def _apply_weights_np(self, grad_w: np.ndarray, vec=None,
                          acc_vec=None) -> None:
        vec = vec if vec is not None else self.weights
        acc_vec = acc_vec if acc_vec is not None \
            else self.accumulated_gradient_weights
        self._fp_register(vec)
        self._fold_fingerprint(np, 2, vec.mem)
        sdc = self._sdc_scales(xla=False)
        if sdc is not None:
            grad_w = grad_w.copy()
            grad_w.ravel()[0] *= 1.0 + sdc[1]
        self._fold_fingerprint(np, 1, grad_w)
        if not self._np_grad_ok(grad_w):
            # skipped update: the claimed fp still covers the (kept)
            # value, or the next step's refold would false-alarm
            self._fold_fingerprint(np, 0, vec.mem)
            return  # anomaly guard: skip, don't poison
        w = vec.mem
        g = self._regularized(np, self._clipped(np, grad_w), w,
                              self.weights_decay)
        lr = self._lr(xla=False)
        if self.gradient_moment:
            acc = acc_vec.mem
            acc *= self.gradient_moment
            acc -= lr * g
            w += acc
        else:
            w -= lr * g
        self._fold_fingerprint(np, 0, w)

    def _apply_bias_np(self, grad_b: np.ndarray, vec=None,
                       acc_vec=None) -> None:
        vec = vec if vec is not None else self.bias
        acc_vec = acc_vec if acc_vec is not None \
            else self.accumulated_gradient_bias
        if vec is None or not vec:
            return
        self._fp_register(vec)
        self._fold_fingerprint(np, 2, vec.mem)
        self._fold_fingerprint(np, 1, grad_b)
        if not self._np_grad_ok(grad_b):
            self._fold_fingerprint(np, 0, vec.mem)
            return  # anomaly guard: skip, don't poison
        b = vec.mem
        g = self._regularized(np, self._clipped(np, grad_b), b,
                              self.weights_decay_bias)
        lr = self._lr_bias(xla=False)
        if self.gradient_moment_bias:
            acc = acc_vec.mem
            acc *= self.gradient_moment_bias
            acc -= lr * g
            b += acc
        else:
            b -= lr * g
        self._fold_fingerprint(np, 0, b)

    def _apply_weights_xla(self, grad_w, vec=None, acc_vec=None) -> None:
        vec = vec if vec is not None else self.weights
        acc_vec = acc_vec if acc_vec is not None \
            else self.accumulated_gradient_weights
        self._apply_param_xla(grad_w, vec, acc_vec, self.weights_decay,
                              self._lr(xla=True), self.gradient_moment)

    def _apply_bias_xla(self, grad_b, vec=None, acc_vec=None) -> None:
        vec = vec if vec is not None else self.bias
        acc_vec = acc_vec if acc_vec is not None \
            else self.accumulated_gradient_bias
        if vec is None or not vec:
            return
        self._apply_param_xla(grad_b, vec, acc_vec,
                              self.weights_decay_bias,
                              self._lr_bias(xla=True),
                              self.gradient_moment_bias)

    def _apply_param_xla(self, grad, vec: Vector, acc_vec, decay: float,
                         lr, moment: float) -> None:
        """One parameter tensor's update on the XLA path.

        Two forms, same math (``tests/test_zero1.py`` pins parity):

        - replicated (the historical path): the gradient is all-reduced
          (implicitly by GSPMD from the data-sharded contraction, or by
          ``maybe_pmean`` under an explicit mapped axis) and the
          identical momentum/decay/clip update runs on every chip;
        - ZeRO-1 (``engine.zero1``, auto-on for data axes > 1): see
          :meth:`_apply_param_zero1`.

        With :attr:`anomaly_flag` linked (the default under
        ``StandardWorkflow``'s anomaly guard) the whole update —
        either form — is applied through ``where(ok, new, old)``,
        where ``ok`` = the step's running flag (loss finite, every
        previously-checked gradient finite) AND ``isfinite(‖grad‖²)``
        of THIS tensor.  A non-finite step leaves the parameter and
        its momentum bitwise untouched; finite steps are bitwise
        identical to the unguarded path (``where`` with a true
        predicate selects the new value exactly).

        Round 20 — microbatch gradient accumulation: when the region
        body traces in an accumulation phase
        (:func:`~znicz_tpu.accelerated_units.current_accum_phase`),
        an ``("accum", M)`` microbatch only sums its raw gradient into
        the f32 micro-accumulation buffer and returns — no pmean, no
        fingerprint fold, no guard gate, no parameter write; the
        ``("apply", M)`` microbatch replaces its gradient with the
        buffered mean ``(Σ grads)/M`` and falls through to the
        UNCHANGED path below, then zeroes the buffer.  A non-finite
        gradient in ANY microbatch propagates through the sum, so the
        guard's finite check at apply skips the whole accumulated
        step; the buffer zeroing is unconditional so a skipped step
        cannot poison the next one.
        """
        from znicz_tpu.accelerated_units import current_accum_phase
        from znicz_tpu.parallel.axis import current_data_axis
        phase = current_accum_phase()
        if phase is not None:
            mode, n_micro = phase
            acc = self._micro_accum.get(id(vec))
            if acc is None or not acc:
                raise RuntimeError(
                    f"{self}: gradient accumulation phase {phase} but "
                    f"no micro-accumulation buffer for '{vec.name}' — "
                    f"set root.common.engine.grad_accum before "
                    f"initialize (and cover the tensor in "
                    f"_micro_accum_params for extra parameter pairs)")
            if mode == "accum":
                acc.devmem = acc.devmem + grad.astype(jnp.float32)
                return
            assert mode == "apply", phase
            grad = (acc.devmem + grad.astype(jnp.float32)) \
                / np.float32(n_micro)
            acc.devmem = jnp.zeros_like(acc.devmem)
        grad = maybe_pmean(grad)
        if getattr(self, "_fp8_matmul", False):
            # fp8 gradient round-trip (round 21): the optimizer sees
            # the gradient at the precision the fp8 training arm would
            # communicate/store it — applied BEFORE the fingerprint
            # fold so the SDC sentinel checks what is actually applied
            f8 = self.fp8_dtype
            if f8 is not None:
                grad = grad.astype(f8).astype(jnp.float32)
        self._fp_register(vec)
        # round 19: refold the STORED parameter before the update
        # (slot 2) — the guard compares it against last step's
        # post-update claimed fp, so a between-step memory mutation
        # (sdc.flip_param) self-identifies on the corrupting chip
        self._fold_fingerprint(jnp, 2, vec.devmem)
        # seeded gradient corruption (sdc.flip_grad) rides a device
        # leaf — ``×(1 + scale)`` is an exact identity when disarmed,
        # an exponent-scale flip of one element when armed; applied
        # to the unit's main weight gradient only.  (sdc.flip_param
        # is injected host-side between dispatches — see
        # AnomalyGuard._host_flip_param.)
        sdc = self._sdc_scales(xla=True)
        if sdc is not None and vec is self.weights:
            idx = (0,) * grad.ndim
            grad = grad.at[idx].multiply(1.0 + sdc[1])
        self._fold_fingerprint(jnp, 1, grad)
        guard = self.anomaly_flag \
            if self.anomaly_flag is not None and self.anomaly_flag else None
        if guard is not None:
            g32 = grad.astype(jnp.float32)
            own_ok = jnp.isfinite(jnp.sum(g32 * g32))
            flags = guard.devmem
            step_ok = (flags[0] > 0.5) & own_ok
            guard.devmem = flags.at[0].set(
                jnp.where(own_ok, flags[0], 0.0))
            w_before = vec.devmem
            acc_before = (acc_vec.devmem
                          if moment and acc_vec is not None and acc_vec
                          else None)
        if self._zero1 and current_data_axis() is None:
            self._apply_param_zero1(grad, vec, acc_vec, decay, lr, moment)
        else:
            w = vec.devmem
            g = self._regularized(jnp, self._clipped(jnp, grad), w, decay)
            if moment:
                # momentum math in f32 regardless of the accumulator's
                # STORAGE dtype (opt_state_dtype); the setter rounds
                # the store back down
                acc = moment * acc_vec.devmem.astype(jnp.float32) - lr * g
                acc_vec.devmem = acc
                vec.devmem = w + acc
            else:
                vec.devmem = w - lr * g
        if guard is not None:
            vec.devmem = jnp.where(step_ok, vec.devmem, w_before)
            if acc_before is not None:
                acc_vec.devmem = jnp.where(step_ok, acc_vec.devmem,
                                           acc_before)
        # the param fingerprint folds the COMMITTED value — a
        # between-step memory mutation (sdc.flip_param, host-injected)
        # makes the NEXT step's pre-update refold disagree with this
        # claimed checksum, which is what the guard's sticky
        # self-check detects
        self._fold_fingerprint(jnp, 0, vec.devmem)

    def _apply_param_zero1(self, grad, vec: Vector, acc_vec,
                           decay: float, lr, moment: float) -> None:
        """ZeRO-1 form of the update (Rajbhandari et al., 2020, stage
        1), expressed as GSPMD sharding constraints on the existing
        math so XLA derives the collectives:

        1. the weight gradient is constrained to the data-axis-sharded
           layout — GSPMD fuses the data-parallel reduction with the
           constraint into a reduce-scatter (half the bytes of the
           replicated path's all-reduce);
        2. momentum/decay/clip run on each chip's 1/N shard, and the
           momentum accumulator is STORED sharded (its Vector carries
           ``data_shard_dim`` — per-chip optimizer state shrinks by
           the data-axis size);
        3. the updated shard is constrained back to the gathered
           layout — one all-gather returns the params every forward
           expects.

        Indivisible dims are zero-padded to a multiple of the axis
        size (the accumulator is stored padded; grads/params pad and
        slice in flight — pad rows carry exact zeros through every
        step).  Model-axis sharding (TP) composes: the spec pair keeps
        ``model_shard_dim`` on the model axis in both layouts.
        """
        from jax.sharding import NamedSharding
        from znicz_tpu.parallel.mesh import zero1_partition, zero1_specs
        mesh = self.device.mesh
        model_dim = getattr(vec, "model_shard_dim", None)
        if acc_vec is not None and acc_vec \
                and acc_vec.data_shard_dim is not None:
            dim, pad = acc_vec.data_shard_dim, acc_vec.data_shard_pad
        else:
            dim, pad = zero1_partition(vec.shape,
                                       self.device.n_data_shards,
                                       model_dim)
        if dim is None:  # nothing shardable: keep the replicated form
            w = vec.devmem
            g = self._regularized(jnp, self._clipped(jnp, grad), w, decay)
            if moment:
                acc = moment * acc_vec.devmem.astype(jnp.float32) - lr * g
                acc_vec.devmem = acc
                vec.devmem = w + acc
            else:
                vec.devmem = w - lr * g
            return
        sharded_spec, gathered_spec = zero1_specs(
            mesh, len(vec.shape), dim, model_dim)
        sharded = NamedSharding(mesh, sharded_spec)
        gathered = NamedSharding(mesh, gathered_spec)
        w = vec.devmem
        if self._grad_comms_bf16:
            # the reduce-scatter moves bf16 bytes; shard math upcasts
            grad = grad.astype(jnp.bfloat16)
        if pad:
            widths = [(0, 0)] * grad.ndim
            widths[dim] = (0, pad)
            grad = jnp.pad(grad, widths)
            w = jnp.pad(w, widths)
        g = jax.lax.with_sharding_constraint(grad, sharded)
        g = g.astype(jnp.float32)
        w_shard = jax.lax.with_sharding_constraint(w, sharded)
        g = self._regularized(jnp, self._clipped(jnp, g), w_shard, decay)
        if moment:
            acc = moment * acc_vec.devmem.astype(jnp.float32) - lr * g
            acc_vec.devmem = jax.lax.with_sharding_constraint(acc, sharded)
            new_w = w_shard + acc
        else:
            new_w = w_shard - lr * g
        new_w = jax.lax.with_sharding_constraint(new_w, gathered)
        if pad:
            idx = [slice(None)] * new_w.ndim
            idx[dim] = slice(0, vec.shape[dim])
            new_w = new_w[tuple(idx)]
        vec.devmem = new_w


# ----------------------------------------------------------------------
# Weightless backward base
# ----------------------------------------------------------------------
class WeightlessGradientUnit(GradientDescentBase):
    """Base for backward units of weightless forwards (pooling, dropout,
    cutter, depooling, normalizers, joiners): no learning-rate state,
    ``err_output → err_input`` only.

    Handles the shared lifecycle: tolerating optimizer kwargs from
    ``"<-"`` configs, requiring a linked ``input``, allocating
    ``err_input`` to match it, and registering the standard region
    leaves.  Subclasses that need their paired forward at initialize
    time set ``REQUIRES_FORWARD_UNIT = True`` to get a labeled error
    instead of a mid-training ``NoneType`` crash.
    """

    REQUIRES_FORWARD_UNIT = True
    REQUIRES_INPUT = True

    def __init__(self, workflow, name=None, **kwargs):
        kwargs.pop("learning_rate", None)  # weightless; tolerate configs
        super().__init__(workflow, name=name, **kwargs)
        self.forward_unit = None  # set by link_gds / the sample

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        self.init_vectors(self.err_input, self.err_output, self.input,
                          self.output)
