"""NN base classes: Forward, GradientDescentBase, fwd↔bwd pairing.

Rebuilds the reference's ``znicz/nn_units.py``:

- :class:`Forward` — base of all forward units: ``input`` (linked),
  ``output``, ``weights``, ``bias`` Vectors; weight-init fill schemes;
- :class:`GradientDescentBase` — base of all backward units:
  ``err_output`` (from the next unit / evaluator), ``err_input`` (to
  the previous one), shared ``weights``/``bias``, learning rate,
  momentum (``gradient_moment``), L1/L2 decay (``weights_decay``,
  ``l1_vs_l2``), and momentum accumulators;
- the ``MatchingObject`` pairing: backward classes declare
  ``MATCHES = (ForwardClass, …)`` and a registry lets
  ``StandardWorkflow`` auto-build the backward chain
  (reference: the ``MatchingObject`` metaclass).

TPU-first deltas:

- weights are stored ``(in_features, out_features)`` so the forward
  GEMM is ``x @ W`` with no transpose (the reference stored
  ``(out, in)`` for its OpenCL tiles; XLA prefers plain layouts and
  fuses the rest);
- the parameter update runs on device inside the jit region, and the
  gradient is folded across the data-parallel mesh axis with
  ``lax.pmean`` exactly where the reference called
  ``generate_data_for_master``/``apply_data_from_slave``
  (see :mod:`znicz_tpu.parallel`).
"""

from __future__ import annotations

from typing import Type

import numpy as np

import jax.numpy as jnp

from znicz_tpu.accelerated_units import AcceleratedUnit
from znicz_tpu.memory import Vector
from znicz_tpu.parallel.axis import maybe_pmean
from znicz_tpu.utils import prng


# ----------------------------------------------------------------------
# fwd ↔ bwd pairing registry (reference: MatchingObject metaclass)
# ----------------------------------------------------------------------
_GD_FOR_FORWARD: dict[type, type] = {}


class MatchingObject(type):
    """Metaclass registering backward units against their forwards via
    a ``MATCHES`` tuple on the backward class."""

    def __init__(cls, name, bases, namespace) -> None:
        super().__init__(name, bases, namespace)
        for fwd_cls in namespace.get("MATCHES", ()):
            _GD_FOR_FORWARD[fwd_cls] = cls


def gd_for(forward_cls: type) -> Type["GradientDescentBase"]:
    """The backward class paired with ``forward_cls`` (walks the MRO so
    subclasses inherit their parent's pairing unless they override)."""
    for klass in forward_cls.__mro__:
        gd = _GD_FOR_FORWARD.get(klass)
        if gd is not None:
            return gd
    raise KeyError(f"no gradient unit registered for {forward_cls.__name__}")


# ----------------------------------------------------------------------
# Forward base
# ----------------------------------------------------------------------
class Forward(AcceleratedUnit):
    """Base forward unit (reference: ``znicz/nn_units.py`` Forward).

    Subclasses set ``self.output`` from ``self.input`` in their run
    methods; parameters live in ``weights``/``bias`` Vectors shared
    with the paired backward unit.
    """

    #: Vector attributes the exporter serializes; units with extra
    #: parameter pairs (attention's output projection) extend this
    EXPORT_PARAMS: tuple = ("weights", "bias")

    def __init__(self, workflow, name: str | None = None,
                 weights_filling: str = "uniform",
                 weights_stddev: float | None = None,
                 bias_filling: str = "uniform",
                 bias_stddev: float | None = None,
                 include_bias: bool = True,
                 **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.input: Vector | None = None  # usually replaced by link_attrs
        self.output = Vector(name=f"{self.name}.output", batch_major=True)
        self.weights = Vector(name=f"{self.name}.weights")
        self.bias = Vector(name=f"{self.name}.bias")
        self.weights_filling = weights_filling
        self.weights_stddev = weights_stddev
        self.bias_filling = bias_filling
        self.bias_stddev = bias_stddev
        self.include_bias = include_bias

    # -- weight init ----------------------------------------------------
    def fill_array(self, arr_shape, filling: str, stddev: float | None,
                   fan_in: int) -> np.ndarray:
        gen = prng.get()
        if stddev is None:
            stddev = 1.0 / max(1.0, np.sqrt(fan_in))
        if filling == "uniform":
            return gen.fill_uniform(arr_shape, -stddev, stddev,
                                    dtype=np.float32)
        if filling == "gaussian":
            return gen.fill_normal(arr_shape, 0.0, stddev, dtype=np.float32)
        if filling == "constant":
            return np.full(arr_shape, stddev, dtype=np.float32)
        # variance-preserving fillings (stddev argument ignored):
        # the reference's fixed-stddev fillings assume shallow nets or
        # ImageNet-scale horizons; deep ReLU stacks need fan-scaled
        # init to keep forward/backward variance O(1)
        if filling == "he":  # ReLU family
            return gen.fill_normal(arr_shape, 0.0,
                                   float(np.sqrt(2.0 / max(1, fan_in))),
                                   dtype=np.float32)
        if filling == "xavier":  # tanh/sigmoid/linear family
            return gen.fill_normal(arr_shape, 0.0,
                                   float(np.sqrt(1.0 / max(1, fan_in))),
                                   dtype=np.float32)
        raise ValueError(f"unknown filling '{filling}'")

    @property
    def current_batch(self) -> int:
        return self.input.shape[0]

    @property
    def output_store_dtype(self) -> np.dtype:
        """Storage dtype for this unit's ``output`` — the activation
        policy (:attr:`AcceleratedUnit.act_store_dtype`) unless a
        subclass pins f32 (e.g. softmax probabilities feeding the
        evaluator)."""
        return self.act_store_dtype

    def inherit_model_shard(self, *vectors) -> None:
        """Copy the input's model-axis sharding to same-shaped output
        vectors.  Every shape-preserving (elementwise) forward should
        call this after allocating its outputs so tensor-parallel
        feature sharding passes through instead of silently degrading
        to replicated (which would make GSPMD all-gather the
        activations between a column and row layer every step)."""
        model_dim = getattr(self.input, "model_shard_dim", None)
        for vec in vectors:
            vec.model_shard_dim = model_dim


# ----------------------------------------------------------------------
# GradientDescent base
# ----------------------------------------------------------------------
class GradientDescentBase(AcceleratedUnit, metaclass=MatchingObject):
    """Base backward unit (reference: ``znicz/nn_units.py``
    GradientDescentBase).

    Update rule (matching the reference's momentum + L1/L2 decay):

    .. code-block:: text

        g   = dL/dW + weights_decay·((1−l1_vs_l2)·W + ½·l1_vs_l2·sign(W))
        acc = gradient_moment·acc − learning_rate·g
        W  += acc

    In data-parallel runs ``dL/dW`` is ``pmean``-folded over the
    ``data`` mesh axis before the update — the synchronous SPMD
    replacement for the reference's master-side gradient fold.
    """

    MATCHES: tuple = ()
    #: subclasses that require a paired forward / a linked input set
    #: these to get the labeled error instead of a raw AttributeError
    REQUIRES_FORWARD_UNIT = False
    REQUIRES_INPUT = False

    def __init__(self, workflow, name: str | None = None,
                 learning_rate: float = 0.01,
                 learning_rate_bias: float | None = None,
                 weights_decay: float = 0.0,
                 weights_decay_bias: float = 0.0,
                 l1_vs_l2: float = 0.0,
                 gradient_moment: float = 0.0,
                 gradient_moment_bias: float | None = None,
                 need_err_input: bool = True,
                 **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.learning_rate = learning_rate
        self.learning_rate_bias = (learning_rate if learning_rate_bias is None
                                   else learning_rate_bias)
        self.weights_decay = weights_decay
        self.weights_decay_bias = weights_decay_bias
        self.l1_vs_l2 = l1_vs_l2
        self.gradient_moment = gradient_moment
        self.gradient_moment_bias = (gradient_moment
                                     if gradient_moment_bias is None
                                     else gradient_moment_bias)
        self.need_err_input = need_err_input
        # linked from the paired forward unit by StandardWorkflow:
        self.input: Vector | None = None
        self.output: Vector | None = None
        self.weights: Vector | None = None
        self.bias: Vector | None = None
        # linked from the next backward unit / evaluator:
        self.err_output: Vector | None = None
        self.err_input = Vector(name=f"{self.name}.err_input",
                                batch_major=True)
        # momentum slots
        self.accumulated_gradient_weights = Vector(
            name=f"{self.name}.acc_grad_w")
        self.accumulated_gradient_bias = Vector(
            name=f"{self.name}.acc_grad_b")
        # device-resident [lr, lr_bias]; only populated when a
        # LearningRateAdjust unit schedules this GD unit — a region
        # leaf, so schedule changes never recompile the step program
        self.lr_state = Vector(name=f"{self.name}.lr_state")

    def initialize(self, device=None, **kwargs) -> None:
        if self.REQUIRES_FORWARD_UNIT \
                and getattr(self, "forward_unit", None) is None:
            raise ValueError(
                f"{self}: forward_unit not set — assign the paired "
                f"forward unit before initialize (link_attrs does not "
                f"do this)")
        if self.REQUIRES_INPUT and (self.input is None
                                    or not self.input):
            raise AttributeError(f"{self}: input not linked yet")
        super().initialize(device=device, **kwargs)
        # err_input allocation lives here (post-super, device resolved)
        # so its dtype can follow the activation storage policy
        if (self.need_err_input and self.input is not None
                and self.input and not self.err_input):
            self.err_input.reset(np.zeros(self.input.shape,
                                          dtype=self.act_store_dtype))
            # the error cotangent shards like the tensor it's the
            # gradient of (tensor parallelism: feature-sharded
            # activations get feature-sharded errors)
            self.err_input.model_shard_dim = getattr(
                self.input, "model_shard_dim", None)
        if not self.need_err_input and (self.weights is None
                                        or not self.weights):
            # weightless AND nothing upstream wants the error: the unit
            # has no observable effect — skip it entirely (scheduler
            # and jit region both honor gate_skip)
            from znicz_tpu.mutable import Bool
            self.gate_skip = Bool(True)
        if self.gradient_moment or self.gradient_moment_bias:
            acc_dtype = self.opt_state_dtype
            if self.weights is not None and self.weights:
                self.accumulated_gradient_weights.reset(
                    np.zeros(self.weights.shape, dtype=acc_dtype))
                self.accumulated_gradient_weights.model_shard_dim = \
                    getattr(self.weights, "model_shard_dim", None)
            if (self.bias is not None and self.bias
                    and self.gradient_moment_bias):
                self.accumulated_gradient_bias.reset(
                    np.zeros(self.bias.shape, dtype=acc_dtype))
                self.accumulated_gradient_bias.model_shard_dim = \
                    getattr(self.bias, "model_shard_dim", None)
            self.init_vectors(self.accumulated_gradient_weights,
                              self.accumulated_gradient_bias)

    @property
    def opt_state_dtype(self) -> np.dtype:
        """STORAGE dtype for the momentum accumulators.

        In bf16 mode the update fusions over the big FC state are
        bandwidth-bound on ~600 MB/step of optimizer-state traffic
        (PERF.md round 4: measured +1.0% img/s from halving it; round
        5 validated the precision against moving error curves —
        BF16_CONVERGENCE.json's ``bfloat16_optstate`` arm).  The
        momentum MATH stays f32 (the accumulator is upcast in the
        update expression; only its storage rounds) — same
        storage-vs-compute split as ``act_store_dtype``.  Opt out:
        ``root.common.engine.bf16_optimizer_state = False``.
        """
        from znicz_tpu.utils.config import root
        if (self.device is not None
                and not self.device.is_host_only
                and self.device.compute_dtype == np.dtype("bfloat16")
                and bool(root.common.engine.get("bf16_optimizer_state",
                                                True))):
            import jax.numpy as jnp
            return np.dtype(jnp.bfloat16)
        return np.dtype(np.float32)

    # -- learning-rate source (scheduled vector or static float) --------
    def _lr(self, xla: bool):
        if self.lr_state:
            return (self.lr_state.devmem[0] if xla
                    else float(self.lr_state.mem[0]))
        return self.learning_rate

    def _lr_bias(self, xla: bool):
        if self.lr_state:
            return (self.lr_state.devmem[1] if xla
                    else float(self.lr_state.mem[1]))
        return self.learning_rate_bias

    # -- shared update math (xp = np or jnp) ----------------------------
    def _regularized(self, xp, grad, weights, decay: float):
        if not decay:
            return grad
        l1 = self.l1_vs_l2
        reg = (1.0 - l1) * weights
        if l1:
            reg = reg + 0.5 * l1 * xp.sign(weights)
        return grad + decay * reg

    # ``vec``/``acc`` parameters let units with EXTRA parameter pairs
    # (e.g. attention's output projection) reuse the exact update rule
    # instead of copy-pasting the momentum/decay math
    def _apply_weights_np(self, grad_w: np.ndarray, vec=None,
                          acc_vec=None) -> None:
        vec = vec if vec is not None else self.weights
        acc_vec = acc_vec if acc_vec is not None \
            else self.accumulated_gradient_weights
        w = vec.mem
        g = self._regularized(np, grad_w, w, self.weights_decay)
        lr = self._lr(xla=False)
        if self.gradient_moment:
            acc = acc_vec.mem
            acc *= self.gradient_moment
            acc -= lr * g
            w += acc
        else:
            w -= lr * g

    def _apply_bias_np(self, grad_b: np.ndarray, vec=None,
                       acc_vec=None) -> None:
        vec = vec if vec is not None else self.bias
        acc_vec = acc_vec if acc_vec is not None \
            else self.accumulated_gradient_bias
        if vec is None or not vec:
            return
        b = vec.mem
        g = self._regularized(np, grad_b, b, self.weights_decay_bias)
        lr = self._lr_bias(xla=False)
        if self.gradient_moment_bias:
            acc = acc_vec.mem
            acc *= self.gradient_moment_bias
            acc -= lr * g
            b += acc
        else:
            b -= lr * g

    def _apply_weights_xla(self, grad_w, vec=None, acc_vec=None) -> None:
        vec = vec if vec is not None else self.weights
        acc_vec = acc_vec if acc_vec is not None \
            else self.accumulated_gradient_weights
        grad_w = maybe_pmean(grad_w)
        w = vec.devmem
        g = self._regularized(jnp, grad_w, w, self.weights_decay)
        lr = self._lr(xla=True)
        if self.gradient_moment:
            # momentum math in f32 regardless of the accumulator's
            # STORAGE dtype (opt_state_dtype); the setter rounds the
            # store back down
            acc = self.gradient_moment \
                * acc_vec.devmem.astype(jnp.float32) - lr * g
            acc_vec.devmem = acc
            vec.devmem = w + acc
        else:
            vec.devmem = w - lr * g

    def _apply_bias_xla(self, grad_b, vec=None, acc_vec=None) -> None:
        vec = vec if vec is not None else self.bias
        acc_vec = acc_vec if acc_vec is not None \
            else self.accumulated_gradient_bias
        if vec is None or not vec:
            return
        grad_b = maybe_pmean(grad_b)
        b = vec.devmem
        g = self._regularized(jnp, grad_b, b, self.weights_decay_bias)
        lr = self._lr_bias(xla=True)
        if self.gradient_moment_bias:
            acc = self.gradient_moment_bias \
                * acc_vec.devmem.astype(jnp.float32) - lr * g
            acc_vec.devmem = acc
            vec.devmem = b + acc
        else:
            vec.devmem = b - lr * g


# ----------------------------------------------------------------------
# Weightless backward base
# ----------------------------------------------------------------------
class WeightlessGradientUnit(GradientDescentBase):
    """Base for backward units of weightless forwards (pooling, dropout,
    cutter, depooling, normalizers, joiners): no learning-rate state,
    ``err_output → err_input`` only.

    Handles the shared lifecycle: tolerating optimizer kwargs from
    ``"<-"`` configs, requiring a linked ``input``, allocating
    ``err_input`` to match it, and registering the standard region
    leaves.  Subclasses that need their paired forward at initialize
    time set ``REQUIRES_FORWARD_UNIT = True`` to get a labeled error
    instead of a mid-training ``NoneType`` crash.
    """

    REQUIRES_FORWARD_UNIT = True
    REQUIRES_INPUT = True

    def __init__(self, workflow, name=None, **kwargs):
        kwargs.pop("learning_rate", None)  # weightless; tolerate configs
        super().__init__(workflow, name=name, **kwargs)
        self.forward_unit = None  # set by link_gds / the sample

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        self.init_vectors(self.err_input, self.err_output, self.input,
                          self.output)
