"""Gradient unit for Deconv (reference: ``znicz/gd_deconv.py``).

XLA path: ``jax.vjp`` of :meth:`Deconv.xla_forward` — for a transposed
conv that is again a plain conv, lowered natively by XLA.  Numpy
oracle: the explicit transpose math (im2col of the incoming error),
independently implemented.
"""

from __future__ import annotations

import numpy as np

import jax

from znicz_tpu.ops.conv import im2col
from znicz_tpu.ops.deconv import Deconv
from znicz_tpu.ops.nn_units import GradientDescentBase


class GDDeconv(GradientDescentBase):
    MATCHES = (Deconv,)

    def __init__(self, workflow, name=None, **kwargs):
        super().__init__(workflow, name=name, **kwargs)
        self.forward_unit = None  # set by link_gds / the sample

    def initialize(self, device=None, **kwargs) -> None:
        if self.input is None or not self.input:
            raise AttributeError(f"{self}: input not linked yet")
        if self.need_err_input and not self.err_input:
            self.err_input.reset(np.zeros(self.input.shape,
                                          dtype=np.float32))
        super().initialize(device=device, **kwargs)
        self.init_vectors(self.err_input, self.err_output, self.input,
                          self.output, self.weights, self.bias)

    def numpy_run(self) -> None:
        fwd = self.forward_unit
        for vec in (self.err_output, self.input, self.output):
            vec.map_read()
        self.weights.map_write()
        x = self.input.mem.astype(np.float32)
        w = self.weights.mem
        n, ih, iw, k = x.shape
        w2d = w.reshape(-1, k)                       # (ky*kx*C, K)
        delta = self.err_output.mem * fwd.activation.derivative(
            np, self.output.mem, None)
        ecols = im2col(delta, fwd.ky, fwd.kx, *fwd.sliding, fwd.padding)
        ecols2d = ecols.reshape(-1, ecols.shape[-1])
        if self.need_err_input:
            self.err_input.map_invalidate()
            self.err_input.mem[...] = (
                ecols2d @ w2d).reshape(x.shape)
        grad_w = (ecols2d.T @ x.reshape(-1, k)).reshape(w.shape)
        self._apply_weights_np(grad_w)
        if self.bias is not None and self.bias:
            self.bias.map_write()
            self._apply_bias_np(delta.sum(axis=(0, 1, 2)))

    def xla_run(self) -> None:
        fwd = self.forward_unit
        x = self.input.devmem
        w = self.weights.devmem
        has_bias = self.bias is not None and self.bias
        b = self.bias.devmem if has_bias else None
        _, vjp = jax.vjp(lambda xx, ww, bb: fwd.xla_forward(xx, ww, bb),
                         x, w, b)
        grad_x, grad_w, grad_b = vjp(self.err_output.devmem)
        if self.need_err_input:
            self.err_input.devmem = grad_x
        self._apply_weights_xla(grad_w)
        if has_bias:
            self._apply_bias_xla(grad_b)
