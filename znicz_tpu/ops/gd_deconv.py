"""Gradient unit for Deconv (reference: ``znicz/gd_deconv.py``).

XLA path: explicit transposed gradients (``jax.linear_transpose`` of
``Deconv.deconv_raw`` for the weight grad, the paired forward conv for
the input grad, activation derivative from the saved output — no
forward re-evaluation; same design as ``gd_conv``).  For a transposed
conv that is again a plain conv, lowered natively by XLA.  Numpy
oracle: the explicit transpose math (im2col of the incoming error),
independently implemented.

Like every GD family, the gradients here only get PRODUCED — the
momentum/decay/clip update (and, on data-parallel meshes, its ZeRO-1
reduce-scatter / sharded-state / all-gather form) is the shared base
path in ``GradientDescentBase._apply_param_xla``.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from znicz_tpu.ops.conv import im2col
from znicz_tpu.ops.deconv import Deconv
from znicz_tpu.ops.nn_units import GradientDescentBase


class GDDeconv(GradientDescentBase):
    MATCHES = (Deconv,)

    def __init__(self, workflow, name=None, **kwargs):
        super().__init__(workflow, name=name, **kwargs)
        self.forward_unit = None  # set by link_gds / the sample

    def initialize(self, device=None, **kwargs) -> None:
        if self.input is None or not self.input:
            raise AttributeError(f"{self}: input not linked yet")
        super().initialize(device=device, **kwargs)
        self.init_vectors(self.err_input, self.err_output, self.input,
                          self.output, self.weights, self.bias)

    def numpy_run(self) -> None:
        fwd = self.forward_unit
        for vec in (self.err_output, self.input, self.output):
            vec.map_read()
        self.weights.map_write()
        x = self.input.mem.astype(np.float32)
        w = self.weights.mem
        n, ih, iw, k = x.shape
        w2d = w.reshape(-1, k)                       # (ky*kx*C, K)
        delta = self.err_output.mem * fwd.activation.derivative(
            np, self.output.mem, None)
        ecols = im2col(delta, fwd.ky, fwd.kx, *fwd.sliding, fwd.padding)
        ecols2d = ecols.reshape(-1, ecols.shape[-1])
        if self.need_err_input:
            self.err_input.map_invalidate()
            self.err_input.mem[...] = (
                ecols2d @ w2d).reshape(x.shape)
        grad_w = (ecols2d.T @ x.reshape(-1, k)).reshape(w.shape)
        self._apply_weights_np(grad_w)
        if self.bias is not None and self.bias:
            self.bias.map_write()
            self._apply_bias_np(delta.sum(axis=(0, 1, 2)))

    def xla_run(self) -> None:
        """Explicit gradients, no forward re-evaluation (same design
        as ``GradientDescentConv.xla_run``): activation derivative from
        the saved output; grad wrt x is the PAIRED FORWARD conv applied
        to delta (the transpose of a transposed conv); grad wrt w via
        ``jax.linear_transpose`` of ``deconv_raw`` in its weight
        argument."""
        fwd = self.forward_unit
        x = self.input.devmem
        w = self.weights.devmem
        y = self.output.devmem
        delta = self.err_output.devmem * fwd.activation.derivative(
            jnp, y, None)
        dt = fwd.mxu_dtype
        cotangent = delta if dt is None else delta.astype(dt)
        if self.need_err_input:
            grad_x = fwd.paired_conv_raw(cotangent, w)
            self.err_input.devmem = grad_x.astype(jnp.float32)
        t_w = jax.linear_transpose(
            lambda ww: fwd.deconv_raw(x, ww),
            jax.ShapeDtypeStruct(w.shape, w.dtype))
        (grad_w,) = t_w(cotangent)
        self._apply_weights_xla(grad_w.astype(jnp.float32))
        if self.bias is not None and self.bias:
            self._apply_bias_xla(
                delta.astype(jnp.float32).sum(axis=(0, 1, 2)))
