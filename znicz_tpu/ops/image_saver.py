"""ImageSaver: dumps misclassified sample images to disk per epoch
(reference: ``znicz/image_saver.py`` — wrongly-classified validation
samples written as image files named by true/predicted label so a
human can inspect what the net gets wrong).

Host-side unit: wire after the evaluator (per minibatch); it reads the
minibatch data + labels + the evaluator's argmax, converts offending
samples to PNG via PIL and writes them under
``root.common.dirs.images/<workflow>/epoch_<N>/``.  A per-epoch limit
bounds disk traffic.
"""

from __future__ import annotations

import os
import shutil

import numpy as np

from znicz_tpu.loader.base import TRAIN
from znicz_tpu.memory import Vector
from znicz_tpu.units import Unit
from znicz_tpu.utils.config import root


def to_image_array(sample: np.ndarray) -> np.ndarray:
    """Normalize one sample to an uint8 H×W or H×W×3 image array."""
    img = np.asarray(sample, dtype=np.float32)
    if img.ndim == 1:  # flat vector → square if possible
        side = int(np.sqrt(img.size))
        if side * side == img.size:
            img = img.reshape(side, side)
        else:
            img = img.reshape(1, -1)
    if img.ndim == 3 and img.shape[-1] == 1:
        img = img[..., 0]
    if img.ndim == 3 and img.shape[-1] not in (3,):
        img = img[..., :1][..., 0]  # first channel as grayscale
    lo, hi = float(img.min()), float(img.max())
    if hi > lo:
        img = (img - lo) / (hi - lo)
    else:  # constant sample: flat mid-gray, not a wrapped uint8 cast
        img = np.full_like(img, 0.5)
    return (img * 255.0 + 0.5).astype(np.uint8)


class ImageSaver(Unit):
    """Saves misclassified (or all-eval, see ``save_all``) samples.

    ``NEEDS_PER_STEP_MINIBATCHES``: consumes every minibatch's data —
    drivers that batch steps per dispatch (``run_chunked``) must fall
    back to per-step stepping when this unit is linked.

    File name: ``<n>_t<true>_p<pred>.png`` inside
    ``out_dir/epoch_<epoch>/``; at most ``limit`` files per epoch.
    """

    NEEDS_PER_STEP_MINIBATCHES = True

    def __init__(self, workflow, name: str | None = None,
                 out_dir: str | None = None, limit: int = 64,
                 save_all: bool = False, classes=(1, 0),
                 **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        wf_name = workflow.name if workflow is not None else "wf"
        self.out_dir = out_dir or os.path.join(
            str(root.common.dirs.images), wf_name)
        self.limit = int(limit)
        self.save_all = save_all
        self.classes = tuple(classes)  # minibatch classes to inspect
        # linked attrs (StandardWorkflow.link_image_saver wires these):
        self.input: Vector | None = None        # loader.minibatch_data
        self.labels: Vector | None = None       # loader.minibatch_labels
        self.max_idx: Vector | None = None      # softmax argmax
        self.indices: Vector | None = None      # loader.minibatch_indices
        self.minibatch_class = TRAIN
        self.minibatch_valid: Vector | None = None
        self.epoch_number = 0                   # linked from loader
        self._saved_this_epoch = 0
        self._last_epoch = -1

    def _epoch_dir(self) -> str:
        d = os.path.join(self.out_dir, f"epoch_{int(self.epoch_number)}")
        if self._last_epoch != int(self.epoch_number):
            self._last_epoch = int(self.epoch_number)
            self._saved_this_epoch = 0
            shutil.rmtree(d, ignore_errors=True)
        os.makedirs(d, exist_ok=True)
        return d

    def run(self) -> None:
        if int(self.minibatch_class) not in self.classes:
            return
        if self._saved_this_epoch >= self.limit \
                and self._last_epoch == int(self.epoch_number):
            return
        from PIL import Image

        for vec in (self.input, self.labels, self.max_idx,
                    self.minibatch_valid):
            if isinstance(vec, Vector) and vec:
                vec.map_read()
        data = np.asarray(self.input.mem)
        truth = np.asarray(self.labels.mem)
        pred = np.asarray(self.max_idx.mem)
        n_valid = (int(self.minibatch_valid.mem)
                   if isinstance(self.minibatch_valid, Vector)
                   and self.minibatch_valid else data.shape[0])
        if isinstance(self.indices, Vector) and self.indices:
            self.indices.map_read()
            sample_ids = np.asarray(self.indices.mem)
        else:
            sample_ids = np.arange(data.shape[0])
        wrong = np.nonzero((truth[:n_valid] != pred[:n_valid])
                           if not self.save_all
                           else np.ones(n_valid, dtype=bool))[0]
        if wrong.size == 0:
            return
        out = self._epoch_dir()
        for i in wrong:
            if self._saved_this_epoch >= self.limit:
                break
            img = to_image_array(data[i])
            mode = "RGB" if img.ndim == 3 else "L"
            path = os.path.join(
                out, f"{int(sample_ids[i])}_t{int(truth[i])}"
                     f"_p{int(pred[i])}.png")
            Image.fromarray(img, mode=mode).save(path)
            self._saved_this_epoch += 1
