"""Depooling ("unpooling") unit (reference: ``znicz/depooling.py``).

The reference's ``Depooling`` scattered its input back to the winner
offsets recorded by a paired max-pooling unit during *its* forward
pass (``input_offset``) — the decoder half of conv autoencoders.

TPU-first there are no recorded offsets in the hot path (SURVEY.md
§2.3: recompute-in-bwd); instead the XLA path is the **vjp of the
paired pooling unit's pure forward at the pooling's own input** —
for max pooling this scatters exactly to the winners, for avg pooling
it spreads uniformly, both matching the reference semantics.  The
numpy oracle recomputes winners per window explicitly.

Wiring: ``pooling_unit`` must be set to the paired
:class:`~znicz_tpu.ops.pooling.Pooling` instance; its ``input`` Vector
(still holding the encoder activations of the current minibatch)
defines the output shape and the winner positions.
"""

from __future__ import annotations

import numpy as np

import jax

from znicz_tpu.memory import Vector
from znicz_tpu.ops.nn_units import Forward, WeightlessGradientUnit
from znicz_tpu.ops.pooling import AvgPooling, MaxAbsPooling, MaxPooling


class Depooling(Forward):
    """Scatter input to the paired pooling's winner positions."""

    def __init__(self, workflow, name=None, **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.pooling_unit = None              # paired Pooling instance
        #: the pooling's input Vector (linked; defines output shape)
        self.pooling_input: Vector | None = None

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        if self.input is None or not self.input:
            raise AttributeError(f"{self}: input not linked yet")
        if self.pooling_unit is None:
            raise AttributeError(f"{self}: pooling_unit not set")
        if self.pooling_input is None or not self.pooling_input:
            self.pooling_input = self.pooling_unit.input
        if tuple(self.input.shape) != tuple(
                self.pooling_unit.output.shape):
            raise ValueError(
                f"{self}: input shape {self.input.shape} != paired "
                f"pooling output {self.pooling_unit.output.shape}")
        self.output.reset(np.zeros(self.pooling_input.shape,
                                   dtype=self.output_store_dtype))
        self.init_vectors(self.input, self.output, self.pooling_input)

    # winner scatter, shared with the backward's gather ----------------
    def _winner_idx_np(self, pool, px: np.ndarray):
        """Per-window argmax index (full-window coords) for max/maxabs
        pooling of the paired input ``px``."""
        n, h, w, c = px.shape
        idx = {}
        for oy, ox, y0, y1, x0, x1 in pool._windows(h, w):
            win = np.full((n, pool.ky, pool.kx, c), -np.inf,
                          dtype=px.dtype)
            win[:, :y1 - y0, :x1 - x0, :] = px[:, y0:y1, x0:x1, :]
            win = win.reshape(n, -1, c)
            key = np.abs(win) if isinstance(pool, MaxAbsPooling) else win
            key = np.where(np.isfinite(win), key, -np.inf)
            idx[(oy, ox)] = key.argmax(axis=1)
        return idx

    def numpy_run(self) -> None:
        pool = self.pooling_unit
        self.input.map_read()
        self.pooling_input.map_read()
        x = self.input.mem
        px = self.pooling_input.mem
        n, h, w, c = px.shape
        self.output.map_invalidate()
        out = self.output.mem
        out[...] = 0.0
        if isinstance(pool, AvgPooling):
            for oy, ox, y0, y1, x0, x1 in pool._windows(h, w):
                cnt = (y1 - y0) * (x1 - x0)
                out[:, y0:y1, x0:x1, :] += \
                    x[:, oy, ox, None, None, :] / cnt
            return
        if not isinstance(pool, (MaxPooling, MaxAbsPooling)):
            raise TypeError(f"{self}: unsupported pooling type "
                            f"{type(pool).__name__}")
        winners = self._winner_idx_np(pool, px)
        for oy, ox, y0, y1, x0, x1 in pool._windows(h, w):
            idx = winners[(oy, ox)]                    # (n, c)
            wy = y0 + idx // pool.kx
            wx = x0 + idx % pool.kx
            for s in range(n):
                for ch in range(c):
                    out[s, wy[s, ch], wx[s, ch], ch] += x[s, oy, ox, ch]

    def xla_forward(self, x, px):
        _, vjp = jax.vjp(self.pooling_unit.xla_forward, px)
        (out,) = vjp(x)
        return out

    def xla_run(self) -> None:
        self.output.devmem = self.xla_forward(
            self.input.devmem, self.pooling_input.devmem)


class GDDepooling(WeightlessGradientUnit):
    """Transpose of depooling = the pooling gather itself:
    ``err_input[o] = err_output[winner(o)]`` (max) / window mean (avg)."""

    MATCHES = (Depooling,)

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        self.init_vectors(self.forward_unit.pooling_input)

    def numpy_run(self) -> None:
        if not self.need_err_input:
            return
        fwd = self.forward_unit
        pool = fwd.pooling_unit
        self.err_output.map_read()
        fwd.pooling_input.map_read()
        err = self.err_output.mem
        px = fwd.pooling_input.mem
        n, h, w, c = px.shape
        self.err_input.map_invalidate()
        out = self.err_input.mem
        if isinstance(pool, AvgPooling):
            for oy, ox, y0, y1, x0, x1 in pool._windows(h, w):
                cnt = (y1 - y0) * (x1 - x0)
                out[:, oy, ox, :] = \
                    err[:, y0:y1, x0:x1, :].sum(axis=(1, 2)) / cnt
            return
        winners = fwd._winner_idx_np(pool, px)
        for oy, ox, y0, y1, x0, x1 in pool._windows(h, w):
            idx = winners[(oy, ox)]
            wy = y0 + idx // pool.kx
            wx = x0 + idx % pool.kx
            for s in range(n):
                for ch in range(c):
                    out[s, oy, ox, ch] = err[s, wy[s, ch], wx[s, ch], ch]

    def xla_run(self) -> None:
        fwd = self.forward_unit
        px = fwd.pooling_input.devmem
        _, vjp = jax.vjp(lambda xx: fwd.xla_forward(xx, px),
                         self.input.devmem)
        (grad_x,) = vjp(self.err_output.devmem)
        if self.need_err_input:
            self.err_input.devmem = grad_x
