"""ToSequence: flatten spatial positions into a token axis.

(B, H, W, C) → (B, H·W, C) — the ViT-style bridge from the conv
feature map to the sequence stack (attention / layer_norm consume
(batch, time, features)).  The 2015 reference predates attention
(SURVEY.md §5.7); this unit exists so conv front-ends and the
long-context op family compose in one workflow — e.g. the multichip
dryrun trains conv → attention in a single GSPMD program.

Backward is the exact reshape adjoint (a reshape), so the pair is
weightless and loss-free in both directions.
"""

from __future__ import annotations

import numpy as np

from znicz_tpu.ops.nn_units import Forward, WeightlessGradientUnit


class ToSequence(Forward):
    """Reshape (B, H, W, C) — or any (B, d1..dn, C) — to
    (B, Πdᵢ, C)."""

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        if self.input is None or not self.input:
            raise AttributeError(f"{self}: input not linked yet")
        shape = self.input.shape
        if len(shape) < 3:
            raise ValueError(f"{self}: need (batch, ..., features) "
                             f"rank ≥ 3, got {shape}")
        b, c = shape[0], shape[-1]
        t = int(np.prod(shape[1:-1]))
        self.output.reset(np.zeros((b, t, c),
                                   dtype=self.output_store_dtype))
        self.init_vectors(self.input, self.output)

    def numpy_run(self) -> None:
        self.input.map_read()
        self.output.map_invalidate()
        self.output.mem[...] = self.input.mem.reshape(self.output.shape)

    def xla_run(self) -> None:
        self.output.devmem = self.input.devmem.reshape(
            self.output.shape)


class GDToSequence(WeightlessGradientUnit):
    """Reshape the error back to the spatial shape."""

    MATCHES = (ToSequence,)

    def numpy_run(self) -> None:
        if not self.need_err_input:
            return
        self.err_output.map_read()
        self.err_input.map_invalidate()
        self.err_input.mem[...] = self.err_output.mem.reshape(
            self.err_input.shape)

    def xla_run(self) -> None:
        if self.need_err_input:
            self.err_input.devmem = self.err_output.devmem.reshape(
                self.err_input.shape)
