"""Sequence-axis reshape units.

``ToSequence``: (B, H, W, C) → (B, H·W, C) — the ViT-style bridge from
the conv feature map to the sequence stack (attention / layer_norm
consume (batch, time, features)).  The 2015 reference predates
attention (SURVEY.md §5.7); this unit exists so conv front-ends and the
long-context op family compose in one workflow — e.g. the multichip
dryrun trains conv → attention in a single GSPMD program.

``LastToken``: (B, T, D) → (B, D), the final position's features — the
bridge from a causal sequence stack to a position-independent LM head
(a ``softmax`` layer over the vocabulary).  Training a next-token
model through this unit is what makes the head's weights T-independent
and therefore reusable verbatim by the single-token decode path
(``serving.decode``), where the "sequence" is one position long.

Backwards are the exact adjoints (a reshape; a zero-pad scatter into
the last position), so both pairs are weightless and loss-free.
"""

from __future__ import annotations

import numpy as np

from znicz_tpu.ops.nn_units import Forward, WeightlessGradientUnit


class ToSequence(Forward):
    """Reshape (B, H, W, C) — or any (B, d1..dn, C) — to
    (B, Πdᵢ, C)."""

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        if self.input is None or not self.input:
            raise AttributeError(f"{self}: input not linked yet")
        shape = self.input.shape
        if len(shape) < 3:
            raise ValueError(f"{self}: need (batch, ..., features) "
                             f"rank ≥ 3, got {shape}")
        b, c = shape[0], shape[-1]
        t = int(np.prod(shape[1:-1]))
        self.output.reset(np.zeros((b, t, c),
                                   dtype=self.output_store_dtype))
        self.init_vectors(self.input, self.output)

    def numpy_run(self) -> None:
        self.input.map_read()
        self.output.map_invalidate()
        self.output.mem[...] = self.input.mem.reshape(self.output.shape)

    def xla_run(self) -> None:
        self.output.devmem = self.input.devmem.reshape(
            self.output.shape)


class LastToken(Forward):
    """Select the final time position: (B, T, D) → (B, D)."""

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        if self.input is None or not self.input:
            raise AttributeError(f"{self}: input not linked yet")
        shape = self.input.shape
        if len(shape) != 3:
            raise ValueError(f"{self}: need (batch, time, features), "
                             f"got {shape}")
        b, _, d = shape
        self.output.reset(np.zeros((b, d),
                                   dtype=self.output_store_dtype))
        self.init_vectors(self.input, self.output)

    def numpy_run(self) -> None:
        self.input.map_read()
        self.output.map_invalidate()
        self.output.mem[...] = self.input.mem[:, -1]

    def xla_run(self) -> None:
        self.output.devmem = self.input.devmem[:, -1]


class GDToSequence(WeightlessGradientUnit):
    """Reshape the error back to the spatial shape."""

    MATCHES = (ToSequence,)

    def numpy_run(self) -> None:
        if not self.need_err_input:
            return
        self.err_output.map_read()
        self.err_input.map_invalidate()
        self.err_input.mem[...] = self.err_output.mem.reshape(
            self.err_input.shape)

    def xla_run(self) -> None:
        if self.need_err_input:
            self.err_input.devmem = self.err_output.devmem.reshape(
                self.err_input.shape)


class GDLastToken(WeightlessGradientUnit):
    """Adjoint of the last-position select: scatter the error into
    position T-1, zeros elsewhere."""

    MATCHES = (LastToken,)

    def numpy_run(self) -> None:
        if not self.need_err_input:
            return
        self.err_output.map_read()
        self.err_input.map_invalidate()
        self.err_input.mem[...] = 0
        self.err_input.mem[:, -1] = self.err_output.mem

    def xla_run(self) -> None:
        if not self.need_err_input:
            return
        import jax.numpy as jnp
        err = jnp.zeros(self.err_input.shape, jnp.float32)
        self.err_input.devmem = err.at[:, -1].set(
            self.err_output.devmem.astype(jnp.float32))
