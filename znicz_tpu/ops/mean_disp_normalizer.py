"""MeanDispNormalizer (reference: ``znicz/mean_disp_normalizer.py``).

``y = (x − mean) · rdisp`` — per-feature input whitening using dataset
statistics computed by the loader (the reference shipped ``mean`` and
reciprocal-dispersion ``rdisp`` Vectors from its ImageNet loader).
Elementwise — XLA fuses it into the first conv's prologue; no Pallas
needed.
"""

from __future__ import annotations

import numpy as np

from znicz_tpu.memory import Vector
from znicz_tpu.ops.nn_units import Forward, WeightlessGradientUnit


class MeanDispNormalizer(Forward):
    """Weightless whitening unit; ``mean``/``rdisp`` usually linked
    from the loader (``link_attrs(loader, "mean", "rdisp")``)."""

    def __init__(self, workflow, name=None, **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.mean: Vector | None = None
        self.rdisp: Vector | None = None

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        if self.input is None or not self.input:
            raise AttributeError(f"{self}: input not linked yet")
        if self.mean is None or not self.mean:
            raise AttributeError(f"{self}: mean not linked/set")
        if self.rdisp is None or not self.rdisp:
            raise AttributeError(f"{self}: rdisp not linked/set")
        self.output.reset(np.zeros(self.input.shape,
                                   dtype=self.output_store_dtype))
        self.inherit_model_shard(self.output)
        self.init_vectors(self.input, self.output, self.mean, self.rdisp)

    def numpy_run(self) -> None:
        self.input.map_read()
        self.mean.map_read()
        self.rdisp.map_read()
        self.output.map_invalidate()
        self.output.mem[...] = (
            (self.input.mem.astype(np.float32) - self.mean.mem)
            * self.rdisp.mem)

    def xla_run(self) -> None:
        self.output.devmem = (
            (self.input.devmem - self.mean.devmem) * self.rdisp.devmem)


class GDMeanDispNormalizer(WeightlessGradientUnit):
    """``err_input = err_output · rdisp`` (linear unit transpose)."""

    MATCHES = (MeanDispNormalizer,)

    def numpy_run(self) -> None:
        if not self.need_err_input:
            return
        fwd = self.forward_unit
        self.err_output.map_read()
        fwd.rdisp.map_read()
        self.err_input.map_invalidate()
        self.err_input.mem[...] = self.err_output.mem * fwd.rdisp.mem

    def xla_run(self) -> None:
        if self.need_err_input:
            self.err_input.devmem = (
                self.err_output.devmem * self.forward_unit.rdisp.devmem)
