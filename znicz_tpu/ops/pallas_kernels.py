"""Pallas TPU kernels for irregular hot ops.

SURVEY.md §2.3 maps the reference's hand-written OpenCL/CUDA kernel
corpus onto XLA ops, with Pallas reserved for the fused/irregular
cases.  This module holds those kernels; the first resident is the
**cross-channel LRN** (AlexNet's normalization layer, reference:
``znicz/ocl|cuda`` normalization kernels):

- the forward fuses square → sliding channel-window sum → pow →
  multiply into one VMEM pass over the activations (the plain-XLA
  path now rides the MXU via a constant band-matrix matmul — see
  ``normalization._window_sum`` — which is why Pallas stays opt-in);
- the backward fuses the analytic gradient the same way (one pass,
  two window sums) instead of re-running the forward under ``jax.vjp``.

Both run on a 1-D grid over row tiles with the channel axis resident
in lanes; ``interpret=True`` runs them on CPU for the test oracle
comparison (tests force the cpu platform).

Gating: units call :func:`use_pallas` — True only on real TPU devices
and when ``root.common.engine.use_pallas`` is not disabled, so every
other platform keeps the plain-XLA path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# THE window-sum definition (shared with the numpy oracle and the jnp
# forward — one source of truth for the window/adjoint convention)
from znicz_tpu.ops.normalization import _window_sum as _window_sum_xp

#: jax renamed ``TPUCompilerParams`` → ``CompilerParams``; accept both
#: so the kernels run on 0.4.x and current jax alike
_COMPILER_PARAMS = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams

#: rows per grid step (sublane-aligned; channels ride the lane axis)
_TILE_ROWS = 512


def is_tpu_device(device) -> bool:
    """True when ``device`` fronts a real TPU (Pallas kernels can
    compile).  Accepts ``axon`` (this environment's TPU tunnel plugin
    reports its own platform name) and anything whose device_kind
    names a TPU."""
    jax_device = getattr(device, "jax_device", None)
    if jax_device is None:
        return False
    return (jax_device.platform in ("tpu", "axon")
            or "tpu" in getattr(jax_device, "device_kind", "").lower())


def use_pallas(device, op: str | None = None) -> bool:
    """Pallas path gate: TPU platform + config switch.

    **Default OFF** (``root.common.engine.use_pallas`` opts in —
    ``True`` enables every Pallas variant; a list/tuple/set of op
    names (``["dropout"]``) enables per-op, which is how the in-graph
    A/Bs isolate one kernel).  The standalone microbenchmark
    (PALLAS_BENCH.md) has the Pallas LRN ahead of the jnp composition,
    but IN-GRAPH the picture inverts: `pallas_call` pins its operand
    to a 2-D row-major layout, so XLA brackets every call with layout
    copies + reshapes of the (n,55,55,96) activations — profiled at
    ~40% of the AlexNet step (profiles/r03_b256), and the chip A/B
    measured plain XLA 24% faster end-to-end (7795 vs 6263 img/s,
    batch 256).  The fused-XLA LRN fuses into its conv/pool neighbors
    with no layout constraint.

    **Compile-time flag**: units resolve this ONCE at ``initialize``
    and bake the result into their traced program — flipping
    ``root.common.engine.use_pallas`` after a region compiled has no
    effect for that workflow's lifetime (re-initialize to re-decide).

    The platform check accepts ``axon`` (this environment's TPU tunnel
    plugin reports its own platform name, not ``tpu``) and anything
    whose device_kind names a TPU.
    """
    from znicz_tpu.utils.config import root
    if not is_tpu_device(device):
        return False
    val = root.common.engine.get("use_pallas", False)
    if isinstance(val, (list, tuple, set, frozenset)):
        return op is not None and op in val
    return bool(val)


# ----------------------------------------------------------------------
# LRN: d_i = k + α·Σ_{j∈win(i)} x_j² ;  y_i = x_i · d_i^{−β}
# ----------------------------------------------------------------------
def _window_sum(arr, n: int, half_low: int):
    """Sliding sum over the last (lane) axis — the shared xp-generic
    definition traced with jnp inside the kernel."""
    return _window_sum_xp(jnp, arr, n, half_low=half_low,
                          via_matmul=False)


def _lrn_fwd_kernel(x_ref, o_ref, *, alpha, beta, k, n):
    x = x_ref[:]
    d = k + alpha * _window_sum(x * x, n, n // 2)
    o_ref[:] = x * d ** (-beta)


def _lrn_bwd_kernel(x_ref, err_ref, o_ref, *, alpha, beta, k, n):
    # dy_i/dx_j = δ_ij·d_i^{−β} − 2αβ·x_i·x_j·d_i^{−β−1}·[j∈win(i)];
    # err_input_j = err_j·d_j^{−β} − 2αβ·x_j·Σ_{i: j∈win(i)} t_i with
    # t_i = err_i·x_i·d_i^{−β−1} — the second sum is the window
    # operator's ADJOINT (half_low mirrored; differs for even n)
    x = x_ref[:]
    err = err_ref[:]
    d = k + alpha * _window_sum(x * x, n, n // 2)
    t = err * x * d ** (-beta - 1.0)
    o_ref[:] = (err * d ** (-beta)
                - 2.0 * alpha * beta * x
                * _window_sum(t, n, n - 1 - n // 2))


def _row_tiled_call(kernel, out_like, *inputs, interpret=False):
    """Run an elementwise-rows kernel over (M, C) arrays on a 1-D row
    grid."""
    m, c = out_like.shape
    tile = min(_TILE_ROWS, m)
    spec = pl.BlockSpec((tile, c), lambda i: (i, 0))
    return pl.pallas_call(
        kernel,
        grid=(pl.cdiv(m, tile),),
        in_specs=[spec] * len(inputs),
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((m, c), out_like.dtype),
        interpret=interpret,
    )(*inputs)


# ----------------------------------------------------------------------
# Dropout: PRNG mask + apply in one VMEM pass (candidate; measured
# against the jax.random path by benchmarks/pallas_microbench.py)
# ----------------------------------------------------------------------
def _dropout_kernel(seed_ref, x_ref, o_ref, *, drop_ratio):
    pltpu.prng_seed(seed_ref[0] + pl.program_id(0))
    bits = pltpu.prng_random_bits(x_ref.shape)
    threshold = jnp.uint32(int(drop_ratio * (2 ** 32 - 1)))
    keep = bits.astype(jnp.uint32) > threshold
    scale = 1.0 / (1.0 - drop_ratio)
    o_ref[:] = jnp.where(keep, x_ref[:] * scale, 0.0)


def dropout_apply(x, seed, drop_ratio: float, interpret: bool = False):
    """Fused mask-generate + apply: TPU-core PRNG bits in VMEM instead
    of a materialized threefry mask array from ``jax.random``.

    ``seed``: int32 scalar array.  Inverted-dropout scaling matches
    ``ops/dropout.py`` (keep → ×1/(1−ratio))."""
    shape = x.shape
    x2d = x.reshape(-1, shape[-1])
    m, c = x2d.shape
    tile = min(_TILE_ROWS, m)
    spec = pl.BlockSpec((tile, c), lambda i: (i, 0))
    kernel = functools.partial(_dropout_kernel, drop_ratio=drop_ratio)
    out = pl.pallas_call(
        kernel,
        grid=(pl.cdiv(m, tile),),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM), spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((m, c), x.dtype),
        interpret=interpret,
    )(jnp.asarray(seed, jnp.int32).reshape(1), x2d)
    return out.reshape(shape)


# ----------------------------------------------------------------------
# LayerNorm: per-row statistics + scale/shift in one VMEM pass; the
# backward fuses dx with the cross-row γ/β grad accumulation (scratch
# accumulators over a sequential row-tile grid) — the XLA composition
# materializes xhat and the f32 upcasts between passes (profiled at
# ~8% of the T=2048 seq step, PERF.md round 5)
# ----------------------------------------------------------------------
def _ln_fwd_kernel(x_ref, g_ref, b_ref, o_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    mu = jnp.mean(x, axis=1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=1, keepdims=True)
    y = xc * jax.lax.rsqrt(var + eps) * g_ref[...] + b_ref[...]
    o_ref[...] = y.astype(o_ref.dtype)


def _ln_bwd_kernel(*refs, eps, m, tile, has_beta):
    if has_beta:
        (x_ref, e_ref, g_ref, dx_ref, gg_ref, gb_ref,
         gg_scr, gb_scr) = refs
    else:  # β-less layer norm: no grad_beta output/accumulator
        x_ref, e_ref, g_ref, dx_ref, gg_ref, gg_scr = refs
        gb_ref = gb_scr = None
    i = pl.program_id(0)
    n = pl.num_programs(0)

    @pl.when(i == 0)
    def _init():
        gg_scr[...] = jnp.zeros_like(gg_scr)
        if has_beta:
            gb_scr[...] = jnp.zeros_like(gb_scr)

    x = x_ref[...].astype(jnp.float32)
    err = e_ref[...].astype(jnp.float32)
    # tail tile: rows beyond m are UNDEFINED padding — zero BOTH
    # operands so the cross-row grad sums stay clean (masked err
    # alone wouldn't neutralize a non-finite x̂ from garbage x:
    # 0·NaN = NaN would poison the accumulators); per-row dx for
    # padded rows is garbage-in-garbage-out and its stores land out
    # of bounds, which Pallas drops
    rows = i * tile + jax.lax.broadcasted_iota(
        jnp.int32, err.shape, 0)
    valid = rows < m
    err = jnp.where(valid, err, 0.0)
    x = jnp.where(valid, x, 0.0)
    mu = jnp.mean(x, axis=1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = xc * rstd
    dxhat = err * g_ref[...]
    dx = (dxhat - jnp.mean(dxhat, axis=1, keepdims=True)
          - xhat * jnp.mean(dxhat * xhat, axis=1, keepdims=True)) \
        * rstd
    dx_ref[...] = dx.astype(dx_ref.dtype)
    gg_scr[...] += jnp.sum(err * xhat, axis=0, keepdims=True)
    if has_beta:
        gb_scr[...] += jnp.sum(err, axis=0, keepdims=True)

    @pl.when(i == n - 1)
    def _finish():
        gg_ref[...] = gg_scr[...]
        if has_beta:
            gb_ref[...] = gb_scr[...]


def _row_shard_axes(spec) -> tuple[str, ...]:
    """The mesh axes a kernel shard spec splits rows over — the psum
    axes for cross-row reductions (γ/β gradient sums)."""
    return tuple(
        name for entry in spec if entry is not None
        for name in ((entry,) if isinstance(entry, str)
                     else tuple(entry)))


def layer_norm_forward(x, gamma, beta, eps: float,
                       interpret: bool = False, mesh=None, spec=None):
    """Fused layer norm over (..., D): f32 statistics in VMEM, output
    stored at the input dtype.  ``beta`` may be None (no-shift).

    ``mesh``/``spec`` (a PartitionSpec over ``x``'s dims, from
    :func:`znicz_tpu.parallel.mesh.kernel_shard_spec`) run the kernel
    per-shard under ``shard_map`` — the mesh-native path; an opaque
    ``pallas_call`` under GSPMD would gather the operand onto every
    device.  The feature (last) axis must stay whole; row dims (batch
    over ``data``, a ring-sharded time axis over ``model``) may
    shard freely since every statistic is per-row.
    """
    if mesh is not None and spec is not None \
            and any(a is not None for a in spec):
        if spec[len(x.shape) - 1] is not None:
            raise ValueError(
                f"layer_norm shard spec {spec} shards the feature "
                f"axis — statistics reduce over it; rows must stay "
                f"whole")
        from jax.sharding import PartitionSpec as P
        from znicz_tpu.parallel.mesh import shard_map_unchecked
        rep = P()
        if beta is None:
            fn = shard_map_unchecked(
                lambda xs, g: layer_norm_forward(
                    xs, g, None, eps, interpret=interpret),
                mesh, in_specs=(spec, rep), out_specs=spec)
            return fn(x, gamma)
        fn = shard_map_unchecked(
            lambda xs, g, bb: layer_norm_forward(
                xs, g, bb, eps, interpret=interpret),
            mesh, in_specs=(spec, rep, rep), out_specs=spec)
        return fn(x, gamma, beta)
    shape = x.shape
    x2d = x.reshape(-1, shape[-1])
    m, d = x2d.shape
    if beta is None:
        beta = jnp.zeros((), jnp.float32)
    tile = min(_TILE_ROWS, m)
    spec = pl.BlockSpec((tile, d), lambda i: (i, 0))
    pspec = pl.BlockSpec((1, d), lambda i: (0, 0))
    out = pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps=eps),
        grid=(pl.cdiv(m, tile),),
        in_specs=[spec, pspec, pspec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((m, d), x.dtype),
        interpret=interpret,
    )(x2d, gamma.reshape(1, d).astype(jnp.float32),
      jnp.broadcast_to(beta, (1, d)).astype(jnp.float32))
    return out.reshape(shape)


def layer_norm_backward(x, err, gamma, eps: float,
                        with_beta: bool = True,
                        interpret: bool = False, mesh=None, spec=None):
    """Fused layer-norm backward: per-row dx plus the cross-row γ (and
    β when ``with_beta``) gradient sums, one pass.  Returns
    (dx, grad_gamma, grad_beta-or-None) with the grads in f32 shape
    (D,).

    ``mesh``/``spec``: the mesh-native path (same contract as
    :func:`layer_norm_forward`); dx stays sharded like ``err`` while
    the γ/β partial sums — per-shard rows only — are ``psum``'d over
    every row-sharding mesh axis, landing replicated exactly like the
    GSPMD reduction the XLA fallback path gets for free.
    """
    if mesh is not None and spec is not None \
            and any(a is not None for a in spec):
        if spec[len(x.shape) - 1] is not None:
            raise ValueError(
                f"layer_norm shard spec {spec} shards the feature "
                f"axis — statistics reduce over it; rows must stay "
                f"whole")
        from jax.sharding import PartitionSpec as P
        from znicz_tpu.parallel.mesh import shard_map_unchecked
        reduce_axes = _row_shard_axes(spec)

        def body(xs, es, g):
            dx, gg, gb = layer_norm_backward(
                xs, es, g, eps, with_beta=with_beta,
                interpret=interpret)
            gg = jax.lax.psum(gg, reduce_axes)
            if gb is not None:
                gb = jax.lax.psum(gb, reduce_axes)
            return (dx, gg, gb) if with_beta else (dx, gg)

        rep = P()
        fn = shard_map_unchecked(
            body, mesh, in_specs=(spec, spec, rep),
            out_specs=(spec, rep, rep) if with_beta else (spec, rep))
        if with_beta:
            return fn(x, err, gamma)
        dx, gg = fn(x, err, gamma)
        return dx, gg, None
    shape = x.shape
    d = shape[-1]

    x2d = x.reshape(-1, d)
    e2d = err.reshape(-1, d)
    m = x2d.shape[0]
    tile = min(_TILE_ROWS, m)
    spec = pl.BlockSpec((tile, d), lambda i: (i, 0))
    pspec = pl.BlockSpec((1, d), lambda i: (0, 0))
    out_specs = [spec, pspec] + ([pspec] if with_beta else [])
    out_shape = [jax.ShapeDtypeStruct((m, d), err.dtype),
                 jax.ShapeDtypeStruct((1, d), jnp.float32)] \
        + ([jax.ShapeDtypeStruct((1, d), jnp.float32)]
           if with_beta else [])
    scratch = [pltpu.VMEM((1, d), jnp.float32)
               for _ in range(2 if with_beta else 1)]
    out = pl.pallas_call(
        functools.partial(_ln_bwd_kernel, eps=eps, m=m, tile=tile,
                          has_beta=with_beta),
        grid=(pl.cdiv(m, tile),),
        in_specs=[spec, spec, pspec],
        out_specs=tuple(out_specs),
        out_shape=tuple(out_shape),
        scratch_shapes=scratch,
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(x2d, e2d, gamma.reshape(1, d).astype(jnp.float32))
    gb = out[2][0] if with_beta else None
    return out[0].reshape(shape), out[1][0], gb


# ----------------------------------------------------------------------
# Softmax (+ argmax): one row pass — max, exp, sum, divide, argmax
# fused in VMEM (candidate; the XLA composition is 3-4 HBM passes)
# ----------------------------------------------------------------------
def _softmax_argmax_kernel(v_ref, y_ref, idx_ref):
    v = v_ref[:]
    m = jnp.max(v, axis=1, keepdims=True)
    e = jnp.exp(v - m)
    y_ref[:] = e / jnp.sum(e, axis=1, keepdims=True)
    idx_ref[:] = jnp.argmax(v, axis=1, keepdims=True).astype(jnp.int32)


def softmax_argmax(v, interpret: bool = False):
    """Row softmax + winner index in one pass over (batch, n_classes).

    Returns ``(probs, max_idx)`` matching ``All2AllSoftmax``'s
    stabilized softmax + ``max_idx`` contract."""
    m, c = v.shape
    tile = min(_TILE_ROWS, m)
    spec = pl.BlockSpec((tile, c), lambda i: (i, 0))
    idx_spec = pl.BlockSpec((tile, 1), lambda i: (i, 0))
    probs, idx = pl.pallas_call(
        _softmax_argmax_kernel,
        grid=(pl.cdiv(m, tile),),
        in_specs=[spec],
        out_specs=(spec, idx_spec),
        out_shape=(jax.ShapeDtypeStruct((m, c), v.dtype),
                   jax.ShapeDtypeStruct((m, 1), jnp.int32)),
        interpret=interpret,
    )(v)
    return probs, idx[:, 0]


def lrn_forward(x, alpha: float, beta: float, k: float, n: int,
                interpret: bool = False):
    """Fused LRN forward over an ND array whose LAST axis is channels."""
    shape = x.shape
    x2d = x.reshape(-1, shape[-1])
    kernel = functools.partial(_lrn_fwd_kernel, alpha=alpha, beta=beta,
                               k=k, n=n)
    return _row_tiled_call(kernel, x2d, x2d,
                           interpret=interpret).reshape(shape)


def lrn_backward(x, err_output, alpha: float, beta: float, k: float,
                 n: int, interpret: bool = False):
    """Fused LRN analytic gradient (one pass, two window sums)."""
    shape = x.shape
    x2d = x.reshape(-1, shape[-1])
    err2d = err_output.reshape(-1, shape[-1])
    kernel = functools.partial(_lrn_bwd_kernel, alpha=alpha, beta=beta,
                               k=k, n=n)
    return _row_tiled_call(kernel, x2d, x2d, err2d,
                           interpret=interpret).reshape(shape)
