"""Kohonen self-organizing map units (reference: ``znicz/kohonen.py``
— ``KohonenForward`` + ``KohonenTrainer`` driving the Kohonen/
DemoKohonen samples).

- :class:`KohonenForward`: winner neuron per sample,
  ``argmin ||x − w_i||²`` over an ``sy×sx`` neuron grid; accumulates
  per-neuron hit counts on device (feeds the KohonenHits plotter).
- :class:`KohonenTrainer`: classic SOM batch update with Gaussian
  neighborhood and exponentially decaying radius/learning-rate:

  .. code-block:: text

      h_bi  = exp(−‖grid(win_b) − grid(i)‖² / (2σ(t)²))
      W    += lr(t)/n · Σ_b h_bi (x_b − w_i)

TPU-first: the distance matrix is one GEMM
(‖x‖² − 2xWᵀ + ‖w‖²) on the MXU; the neighborhood update is two more
GEMMs (Hᵀx and column sums) — no scatter, fully deterministic, so
numpy and XLA agree bit-for-bit up to float tolerance.  The decay
clock ``time`` lives in a device scalar so the whole trainer stays
inside the jit region (the reference kept it host-side).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from znicz_tpu.accelerated_units import AcceleratedUnit
from znicz_tpu.memory import Vector
from znicz_tpu.ops.nn_units import Forward
from znicz_tpu.utils import prng


def grid_coords(sy: int, sx: int) -> np.ndarray:
    """(sy*sx, 2) float grid coordinates, row-major."""
    yy, xx = np.mgrid[0:sy, 0:sx]
    return np.stack([yy.ravel(), xx.ravel()], axis=1).astype(np.float32)


class KohonenForward(Forward):
    """Winner lookup (weightless output; weights shared with the
    trainer)."""

    def __init__(self, workflow, shape: tuple[int, int], name=None,
                 **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.shape_grid = (int(shape[0]), int(shape[1]))
        self.winners = Vector(name=f"{self.name}.winners",
                              batch_major=True)
        self.hits = Vector(name=f"{self.name}.hits")  # per-epoch counts

    @property
    def n_neurons(self) -> int:
        return self.shape_grid[0] * self.shape_grid[1]

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        if self.input is None or not self.input:
            raise AttributeError(f"{self}: input not linked yet")
        n = self.input.shape[0]
        features = self.input.sample_size
        if not self.weights:
            self.weights.reset(self.fill_array(
                (self.n_neurons, features), self.weights_filling,
                self.weights_stddev, fan_in=features))
        # output = squared distance to winner (the SOM's quantization
        # error contribution); winners = indices
        self.output.reset(np.zeros((n,), dtype=np.float32))
        self.winners.reset(np.zeros((n,), dtype=np.int32))
        if not self.hits:
            self.hits.reset(np.zeros(self.n_neurons, dtype=np.int32))
        self.init_vectors(self.input, self.output, self.weights,
                          self.winners, self.hits)

    @staticmethod
    def distances(xp, x, w):
        """(n, n_neurons) squared euclidean distances via one GEMM."""
        x2 = (x * x).sum(axis=1)[:, None]
        w2 = (w * w).sum(axis=1)[None, :]
        return x2 - 2.0 * (x @ w.T) + w2

    def numpy_run(self) -> None:
        self.input.map_read()
        self.weights.map_read()
        n = self.input.shape[0]
        x = self.input.mem.reshape(n, -1).astype(np.float32)
        d = self.distances(np, x, self.weights.mem)
        win = d.argmin(axis=1)
        self.winners.map_invalidate()
        self.winners.mem[...] = win.astype(np.int32)
        self.output.map_invalidate()
        self.output.mem[...] = d[np.arange(n), win]
        self.hits.map_write()
        np.add.at(self.hits.mem, win, 1)

    def xla_run(self) -> None:
        x = self.input.devmem
        n = x.shape[0]
        x = x.reshape(n, -1)
        d = self.distances(jnp, x, self.weights.devmem)
        win = d.argmin(axis=1).astype(jnp.int32)
        self.winners.devmem = win
        self.output.devmem = jnp.take_along_axis(
            d, win[:, None].astype(jnp.int32), axis=1)[:, 0]
        self.hits.devmem = self.hits.devmem.at[win].add(1)


class KohonenTrainer(AcceleratedUnit):
    """Batch SOM update (reference: ``KohonenTrainer``)."""

    SNAPSHOT_ATTRS = ("learning_rate", "sigma0", "sigma_inf",
                      "decay_steps")

    def __init__(self, workflow, name=None,
                 learning_rate: float = 0.5,
                 sigma0: float | None = None,
                 sigma_inf: float = 0.5,
                 decay_steps: int = 1000,
                 **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.learning_rate = learning_rate
        self.sigma0 = sigma0          # default: half the grid diagonal
        self.sigma_inf = sigma_inf
        self.decay_steps = int(decay_steps)
        self.forward_mode = "train"   # usually linked from loader
        self.input: Vector | None = None     # (n, features) linked
        self.weights: Vector | None = None   # shared with forward
        self.winners: Vector | None = None   # linked from forward
        self.time = Vector(name=f"{self.name}.time")  # device clock
        self._coords = Vector(name=f"{self.name}.coords")
        self.shape_grid: tuple[int, int] | None = None  # from forward

    def region_key(self) -> tuple:
        return (self.forward_mode,)

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        for vec, nm in ((self.input, "input"), (self.weights, "weights"),
                        (self.winners, "winners")):
            if vec is None or not vec:
                raise AttributeError(f"{self}: {nm} not linked yet")
        if self.shape_grid is None:
            raise ValueError(f"{self}: shape_grid not set (assign the "
                             f"paired KohonenForward's grid shape)")
        sy, sx = self.shape_grid
        if self.sigma0 is None:
            self.sigma0 = max(sy, sx) / 2.0
        self._coords.reset(grid_coords(sy, sx))
        if not self.time:
            self.time.reset(np.zeros((), dtype=np.float32))
        self.init_vectors(self.input, self.weights, self.winners,
                          self.time, self._coords)

    # -- decayed schedule ----------------------------------------------
    def _schedule(self, xp, t):
        frac = xp.minimum(t / float(self.decay_steps), 1.0)
        sigma = self.sigma0 * (self.sigma_inf / self.sigma0) ** frac
        lr = self.learning_rate * (0.01) ** frac
        return sigma, lr

    def _update(self, xp, x, w, win, coords, t):
        sigma, lr = self._schedule(xp, t)
        n = x.shape[0]
        winc = coords[win]                       # (n, 2)
        d2 = ((winc[:, None, :] - coords[None, :, :]) ** 2).sum(-1)
        h = xp.exp(-d2 / (2.0 * sigma * sigma))  # (n, n_neurons)
        num = h.T @ x                            # (n_neurons, features)
        den = h.sum(axis=0)[:, None]             # (n_neurons, 1)
        return w + lr / n * (num - den * w)

    def numpy_run(self) -> None:
        if self.forward_mode != "train":
            return
        for vec in (self.input, self.winners, self._coords):
            vec.map_read()
        self.weights.map_write()
        self.time.map_write()
        n = self.input.shape[0]
        x = self.input.mem.reshape(n, -1).astype(np.float32)
        self.weights.mem[...] = self._update(
            np, x, self.weights.mem, self.winners.mem, self._coords.mem,
            float(self.time.mem))
        self.time.mem[...] += 1.0

    def xla_run(self) -> None:
        if self.forward_mode != "train":
            return
        x = self.input.devmem
        n = x.shape[0]
        x = x.reshape(n, -1)
        self.weights.devmem = self._update(
            jnp, x, self.weights.devmem, self.winners.devmem,
            self._coords.devmem, self.time.devmem)
        self.time.devmem = self.time.devmem + 1.0


def init_som_weights(shape: tuple[int, int], features: int,
                     scale: float = 1.0) -> np.ndarray:
    """Seeded uniform init helper for samples/tests."""
    gen = prng.get()
    return gen.fill_uniform((shape[0] * shape[1], features),
                            -scale, scale, dtype=np.float32)
