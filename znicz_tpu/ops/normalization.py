"""Local response normalization, AlexNet-style across-channel
(reference: ``znicz/normalization.py`` — ``LRNormalizerForward`` /
``LRNormalizerBackward``).

.. code-block:: text

    d_i = (k + α·Σ_{j∈window(i)} x_j²)        (window = n channels)
    y_i = x_i · d_i^{−β}

Defaults match the reference/AlexNet: α=1e-4, β=0.75, k=2, n=5.

The backward unit uses the exact analytic gradient on both paths
(numpy oracle and XLA) — XLA fuses the elementwise/window-sum chain
into the jit region, which benchmarking in the reference survey flags
as the right first choice before reaching for a Pallas kernel
(SURVEY.md §2.3; PALLAS_BENCH.md records the in-graph measurement
that made plain XLA the default here).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from znicz_tpu.ops.nn_units import Forward, GradientDescentBase


def _band_matrix(c: int, n: int, half_low: int) -> np.ndarray:
    """(C, C) 0/1 matrix with ``M[j, i] = 1`` iff channel j is in
    channel i's window — ``arr @ M`` IS the sliding window sum."""
    idx = np.arange(c)
    lo = idx - half_low
    hi = idx + (n - 1 - half_low)
    j = idx[:, None]
    return ((j >= lo[None, :]) & (j <= hi[None, :])).astype(np.float32)


def _window_sum(xp, arr, n: int, half_low: int | None = None,
                via_matmul: bool = True):
    """Sliding sum over the LAST (channel) axis:
    ``out_i = Σ_{k=i−half_low}^{i+(n−1−half_low)} arr_k`` (zero-padded).

    Default ``half_low = n//2`` (the forward's centered window).  The
    operator's adjoint — needed by the backward for even ``n``, where
    the window is asymmetric — is the same sum with
    ``half_low = n−1−n//2``.

    XLA path: the window is a matmul with the constant (C, C) band
    matrix — it rides the MXU in the conv-native layout instead of
    lowering to a sublane-crossing shifted-add chain (the profiled
    ~44%-of-step LRN fusions, profiles/r03_b384; at C=96 the GEMM is
    ~0.1 ms where the shift chain marshalled for milliseconds).  The
    numpy oracle keeps the explicit shifted-add form — an independent
    spec the matmul is tested against."""
    c = arr.shape[-1]
    if half_low is None:
        half_low = n // 2
    if xp is jnp and via_matmul:
        # (Pallas kernels pass via_matmul=False: inside pallas_call
        # the traced jnp is not plain XLA and keeps the shift form.)
        # engine.lrn_band_bf16 feeds the GEMM bf16 operands (f32
        # accumulate) — the band sum is bandwidth-bound (2·C FLOP per
        # element read), so halving the read traffic is the lever;
        # the contribution is α-damped (~1e-4) in d and 2αβ-damped in
        # the backward term, far inside the convergence band.  A/B
        # lever, default follows PERF.md round-4 measurements.
        from znicz_tpu.utils.config import root
        dt = jnp.bfloat16 if root.common.engine.get(
            "lrn_band_bf16", False) else None
        band = jnp.asarray(_band_matrix(c, n, half_low))
        if dt is not None:
            arr, band = arr.astype(dt), band.astype(dt)
        return jnp.matmul(arr, band,
                          preferred_element_type=jnp.float32)
    half_high = n - 1 - half_low
    padded = xp.concatenate(
        [xp.zeros(arr.shape[:-1] + (half_low,), arr.dtype), arr,
         xp.zeros(arr.shape[:-1] + (half_high,), arr.dtype)], axis=-1)
    out = xp.zeros_like(arr)
    for off in range(n):
        out = out + padded[..., off:off + c]
    return out


def _pow_neg_beta(xp, d, beta: float):
    """``d ** (-beta)`` with sqrt/rsqrt chains for the quarter-power
    betas (0.25/0.5/0.75/1.0 — AlexNet's is 0.75).  The generic pow
    lowers to an exp·log chain on the TPU VPU; profiling the AlexNet
    step (profiles/r03_b384) put the LRN fusions at 0.2–0.4 effective
    TF/s, transcendental-bound.  sqrt and reciprocal are single fast
    VPU ops, and the chain is mathematically exact (same value up to
    rounding)."""
    if beta == 0.75:
        return (d * xp.sqrt(d)) ** -0.5 if xp is np \
            else jax.lax.rsqrt(d * xp.sqrt(d))
    if beta == 0.5:
        return d ** -0.5 if xp is np else jax.lax.rsqrt(d)
    if beta == 0.25:
        return xp.sqrt(d) ** -0.5 if xp is np \
            else jax.lax.rsqrt(xp.sqrt(d))
    if beta == 1.0:
        return 1.0 / d
    return d ** (-beta)


def _store_d(xp, d):
    """STORAGE cast for the LRN denominator tensor.

    The round-5 profile (profiles/bench_default) shows the four LRN
    band fusions at 27% of the AlexNet step, dominated by the f32
    ``d`` tensors XLA materializes and shares between forward and
    backward (446 MB + 287 MB at batch 384, written once, read once
    ≈ 1.5 GB/step at the bandwidth roof).  ``engine.lrn_d_bf16``
    stores them bf16 (the upcast fuses in-register): d = k + α·Σx²
    with k = 2 dominating, so bf16 rounding perturbs y by ≲ β·2⁻⁹ —
    the same order as the (already convergence-validated) bf16
    activation storage.  A/B lever; default follows the PERF.md
    round-5 measurement + BF16_CONVERGENCE band."""
    if xp is not jnp:
        return d
    from znicz_tpu.utils.config import root
    flag = root.common.engine.get("lrn_d_bf16", None)
    if flag is None:  # auto: ride the configured mixed-precision mode
        flag = str(root.common.precision_type) == "bfloat16"
    if not flag:
        return d
    return d.astype(jnp.bfloat16).astype(jnp.float32)


class LRNormalizerForward(Forward):
    """Across-channel LRN (weightless forward)."""

    def __init__(self, workflow, alpha: float = 1e-4, beta: float = 0.75,
                 k: float = 2.0, n: int = 5, name=None, **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.alpha = float(alpha)
        self.beta = float(beta)
        self.k = float(k)
        self.n = int(n)

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        if self.input is None or not self.input:
            raise AttributeError(f"{self}: input not linked yet")
        self.output.reset(np.zeros(self.input.shape,
                                   dtype=self.output_store_dtype))
        self.inherit_model_shard(self.output)
        self.init_vectors(self.input, self.output)
        from znicz_tpu.ops import pallas_kernels
        self._use_pallas = pallas_kernels.use_pallas(self.device, "lrn")

    def _forward(self, xp, x):
        d = self.k + self.alpha * _window_sum(xp, x * x, self.n)
        d = _store_d(xp, d)
        return x * _pow_neg_beta(xp, d, self.beta)

    def numpy_run(self) -> None:
        self.input.map_read()
        self.output.map_invalidate()
        self.output.mem[...] = self._forward(np, self.input.mem)

    def xla_run(self) -> None:
        # math in f32 even when activations are stored bf16: d is
        # k + tiny·Σx², all resolution is in low-order bits.  The
        # upcast fuses (in-register), costs no HBM traffic; the
        # devmem setter casts the result back to the storage dtype.
        x = self.input.devmem.astype(jnp.float32)
        if self._use_pallas:  # resolved once at initialize
            from znicz_tpu.ops import pallas_kernels
            self.output.devmem = pallas_kernels.lrn_forward(
                x, self.alpha, self.beta, self.k, self.n)
            return
        self.output.devmem = self._forward(jnp, x)


class LRNormalizerBackward(GradientDescentBase):
    MATCHES = (LRNormalizerForward,)

    def __init__(self, workflow, name=None, **kwargs):
        kwargs.pop("learning_rate", None)  # weightless
        super().__init__(workflow, name=name, **kwargs)
        self.forward_unit: LRNormalizerForward | None = None

    def initialize(self, device=None, **kwargs) -> None:
        if self.input is None or not self.input:
            raise AttributeError(f"{self}: input not linked yet")
        super().initialize(device=device, **kwargs)
        self.init_vectors(self.err_input, self.err_output, self.input,
                          self.output)
        from znicz_tpu.ops import pallas_kernels
        self._use_pallas = pallas_kernels.use_pallas(self.device, "lrn")

    def numpy_run(self) -> None:
        """Analytic gradient (the oracle/spec):

        dy_i/dx_j = δ_ij·d_i^{−β} − 2αβ·x_i·x_j·d_i^{−β−1}·[j∈win(i)]
        """
        fwd = self.forward_unit
        for vec in (self.err_output, self.input):
            vec.map_read()
        x = self.input.mem.astype(np.float32)
        err = self.err_output.mem
        d = fwd.k + fwd.alpha * _window_sum(np, x * x, fwd.n)
        dmb = d ** (-fwd.beta)
        # t_i = err_i · x_i · d_i^{−β−1}; err_input_j gets
        # −2αβ·x_j·Σ_{i: j∈win(i)} t_i — the window operator's ADJOINT
        # (identical to the forward sum only for odd n)
        t = err * x * d ** (-fwd.beta - 1.0)
        self.err_input.map_invalidate()
        self.err_input.mem[...] = (
            err * dmb - 2.0 * fwd.alpha * fwd.beta * x
            * _window_sum(np, t, fwd.n, half_low=fwd.n - 1 - fwd.n // 2))

    def xla_run(self) -> None:
        fwd = self.forward_unit
        # f32 math on bf16-stored operands — see the forward's note
        x = self.input.devmem.astype(jnp.float32)
        err = self.err_output.devmem.astype(jnp.float32)
        if self._use_pallas:  # resolved once at initialize
            from znicz_tpu.ops import pallas_kernels
            self.err_input.devmem = pallas_kernels.lrn_backward(
                x, err, fwd.alpha, fwd.beta, fwd.k, fwd.n)
            return
        d = fwd.k + fwd.alpha * _window_sum(jnp, x * x, fwd.n)
        d = _store_d(jnp, d)  # identical expression to the forward's
        # — XLA CSE shares ONE materialized d between fwd and bwd
        p = _pow_neg_beta(jnp, d, fwd.beta)
        t = err * x * (p / d)  # d^{−β−1} without a second pow
        self.err_input.devmem = (
            err * p - 2.0 * fwd.alpha * fwd.beta * x
            * _window_sum(jnp, t, fwd.n, half_low=fwd.n - 1 - fwd.n // 2))
