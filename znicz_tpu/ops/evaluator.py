"""Evaluators: turn network output + ground truth into the backward
chain's seed error and host-readable quality metrics
(reference: ``znicz/evaluator.py``).

``EvaluatorSoftmax`` consumes the softmax output and emits

- ``err_output = (p − onehot(t)) / n_valid`` — the combined
  softmax+cross-entropy derivative w.r.t. the logits, masked over
  padded tail samples (static-shape minibatches, see loader);
- ``n_err`` — mispredictions among valid samples (device scalar the
  Decision unit reads per step);
- ``confusion_matrix`` — optional (n_classes², accumulated per epoch
  host-side by Decision).

``EvaluatorMSE`` serves regression / autoencoder targets:
``err_output = (y − target)·2/n_valid`` and per-step summed squared
error.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from znicz_tpu.accelerated_units import AcceleratedUnit
from znicz_tpu.loader.base import TRAIN
from znicz_tpu.memory import Vector


class EvaluatorBase(AcceleratedUnit):
    def __init__(self, workflow, name: str | None = None, **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.output: Vector | None = None        # link from last forward
        self.minibatch_valid: Vector | None = None  # link from loader
        self.err_output = Vector(name=f"{self.name}.err_output",
                                 batch_major=True)
        # anomaly guard hooks, linked by StandardWorkflow when the
        # guard is on (resilience.guard): step_flags is seeded here
        # ([running_ok, loss_ok] = isfinite(step loss)); fault_inject
        # is the chaos harness's [loss_add, grad_add] leaf (None
        # unless a fault plan configures a train site)
        self.step_flags: Vector | None = None
        self.fault_inject: Vector | None = None
        # round 19: the guard-hosted [param_fp, grad_fp] SDC
        # fingerprint — zero-seeded here on TRAIN steps only (the
        # static minibatch_class is already part of the region key),
        # so validation steps keep the last train step's fingerprint
        # for the sentinel's vote to read
        self.sdc_fingerprint: Vector | None = None

    def _valid_mask(self, xp, n_rows):
        valid = self.minibatch_valid.devmem if xp is jnp \
            else self.minibatch_valid.mem
        return (xp.arange(n_rows) < valid), valid

    def _inject(self, xp, idx: int):
        """The chaos leaf's additive term (0.0 normally, NaN on an
        injected step); 0.0 when no fault plan is configured."""
        inj = self.fault_inject
        if inj is None or not inj:
            return None
        return inj.devmem[idx] if xp is jnp else inj.mem[idx]

    def _seed_step_flags(self, xp, loss_ok) -> None:
        """Write [running_ok, loss_ok]; the backward chain ANDs its
        gradient-finiteness into slot 0 and the AnomalyGuard commits
        the verdict at the end of the step.

        Under gradient accumulation (round 20) the flags span ALL
        microbatches of one accumulated step: accumulation-phase
        bodies AND their loss verdict into the running flags instead
        of overwriting (the guard resets them to ones after each
        apply-phase commit, so the first microbatch starts from a
        clean [1, 1]) — one non-finite microbatch loss poisons the
        whole step's verdict, matching the fused-batch semantics."""
        flags = self.step_flags
        if flags is None or not flags:
            return
        from znicz_tpu.accelerated_units import current_accum_phase
        phase = current_accum_phase()
        if xp is jnp:
            f = loss_ok.astype(jnp.float32)
            if phase is not None:
                flags.devmem = flags.devmem * f
            else:
                flags.devmem = jnp.stack([f, f])
        else:
            f = np.float32(1.0 if loss_ok else 0.0)
            flags.mem[...] = [f, f]
        if phase is None or phase[0] == "apply":
            # the SDC per-step slots reset once per OPTIMIZER step —
            # accumulation microbatches fold no fingerprints
            self._seed_fingerprint(xp)

    def _seed_fingerprint(self, xp) -> None:
        """Zero the SDC fingerprint's per-step slots (claimed param
        fp, grad fp, pre-update refold) at the top of a TRAIN step so
        the GD units fold this step's checksums into a fresh slate;
        the sticky self-check count and the previous claimed fp (slots
        3/4) persist.  The branch is static: ``minibatch_class`` is in
        the region key."""
        fp = self.sdc_fingerprint
        if fp is None or not fp or int(self.minibatch_class) != TRAIN:
            return
        if xp is jnp:
            fp.devmem = fp.devmem.at[:3].set(0.0)
        else:
            fp.mem[:3] = 0.0


class EvaluatorSoftmax(EvaluatorBase):
    """Softmax cross-entropy evaluator (reference:
    ``EvaluatorSoftmax``)."""

    def __init__(self, workflow, name: str | None = None,
                 compute_confusion: bool = False, **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.labels: Vector | None = None      # link from loader
        self.max_idx: Vector | None = None     # link from All2AllSoftmax
        self.minibatch_class = TRAIN           # usually linked from loader
        self.n_err = Vector(name=f"{self.name}.n_err")
        # per-class error counts for the WHOLE epoch, accumulated on
        # device so Decision syncs host-side once per epoch instead of
        # once per step (a TPU-first change: the per-step device→host
        # scalar fetch dominated step time through the PJRT tunnel)
        self.epoch_n_err = Vector(name=f"{self.name}.epoch_n_err")
        # optional (3, C, C) confusion counts, same epoch-accumulation
        # scheme (reference: EvaluatorSoftmax confusion matrix)
        self.compute_confusion = compute_confusion
        self.confusion_matrix = Vector(name=f"{self.name}.confusion")
        # summed cross-entropy −log p(true) per class, accumulated on
        # device like epoch_n_err (read once per epoch; the loss curve
        # the bf16-vs-f32 convergence artifact tracks)
        self.epoch_loss = Vector(name=f"{self.name}.epoch_loss")

    def region_key(self) -> tuple:
        # minibatch_class indexes the on-device accumulator statically
        return (int(self.minibatch_class),)

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        if self.output is None or not self.output:
            raise AttributeError(f"{self}: output not linked yet")
        self.err_output.reset(np.zeros(self.output.shape, dtype=np.float32))
        self.n_err.reset(np.zeros((), dtype=np.int32))
        if not self.epoch_n_err:
            self.epoch_n_err.reset(np.zeros(3, dtype=np.int32))
        if not self.epoch_loss:
            self.epoch_loss.reset(np.zeros(3, dtype=np.float32))
        if self.compute_confusion and not self.confusion_matrix:
            c = self.n_classes
            self.confusion_matrix.reset(np.zeros((3, c, c), dtype=np.int32))
        self.init_vectors(self.err_output, self.n_err, self.epoch_n_err,
                          self.epoch_loss, self.confusion_matrix,
                          self.output, self.labels, self.max_idx,
                          self.minibatch_valid)

    @property
    def n_classes(self) -> int:
        return self.output.shape[1]

    def numpy_run(self) -> None:
        for vec in (self.output, self.labels, self.max_idx,
                    self.minibatch_valid):
            vec.map_read()
        p = self.output.mem
        t = self.labels.mem
        mask, valid = self._valid_mask(np, p.shape[0])
        onehot = np.zeros_like(p)
        onehot[np.arange(p.shape[0]), t] = 1.0
        err = mask[:, None] * (p - onehot) / max(int(valid), 1)
        grad_inj = self._inject(np, 1)
        if grad_inj is not None:
            err = err + grad_inj
        self.err_output.map_invalidate()
        self.err_output.mem[...] = err
        self.n_err.map_invalidate()
        n_err = int(np.sum((self.max_idx.mem != t) & mask))
        self.n_err.mem[...] = n_err
        self.epoch_n_err.map_write()
        self.epoch_n_err.mem[int(self.minibatch_class)] += n_err
        self.epoch_loss.map_write()
        p_true = np.maximum(p[np.arange(p.shape[0]), t], 1e-30)
        loss_sum = np.float32(np.sum(mask * -np.log(p_true)))
        loss_inj = self._inject(np, 0)
        if loss_inj is not None:
            loss_sum = loss_sum + np.float32(loss_inj)
        loss_ok = bool(np.isfinite(loss_sum))
        # a non-finite step must not poison the epoch accumulator —
        # the guard skips its update; the accumulator skips its sample
        self.epoch_loss.mem[int(self.minibatch_class)] += float(
            loss_sum if loss_ok else 0.0)
        self._seed_step_flags(np, loss_ok)
        if self.compute_confusion:
            self.confusion_matrix.map_write()
            cm = self.confusion_matrix.mem[int(self.minibatch_class)]
            pred = self.max_idx.mem
            np.add.at(cm, (t[mask], pred[mask]), 1)

    def xla_run(self) -> None:
        p = self.output.devmem
        t = self.labels.devmem
        mask, valid = self._valid_mask(jnp, p.shape[0])
        onehot = jax_onehot(t, p.shape[1], p.dtype)
        denom = jnp.maximum(valid, 1).astype(p.dtype)
        err = mask[:, None] * (p - onehot) / denom
        grad_inj = self._inject(jnp, 1)
        if grad_inj is not None:
            err = err + grad_inj.astype(err.dtype)
        self.err_output.devmem = err
        n_err = jnp.sum((self.max_idx.devmem != t) & mask).astype(jnp.int32)
        self.n_err.devmem = n_err
        self.epoch_n_err.devmem = self.epoch_n_err.devmem.at[
            int(self.minibatch_class)].add(n_err)
        p_true = jnp.maximum(p[jnp.arange(p.shape[0]), t], 1e-30)
        loss_sum = jnp.sum(mask * -jnp.log(p_true)).astype(jnp.float32)
        loss_inj = self._inject(jnp, 0)
        if loss_inj is not None:
            loss_sum = loss_sum + loss_inj
        loss_ok = jnp.isfinite(loss_sum)
        # a non-finite step must not poison the epoch accumulator —
        # the guard skips its update; the accumulator skips its sample
        self.epoch_loss.devmem = self.epoch_loss.devmem.at[
            int(self.minibatch_class)].add(
                jnp.where(loss_ok, loss_sum, 0.0))
        self._seed_step_flags(jnp, loss_ok)
        if self.compute_confusion:
            # masked rows contribute 0; duplicate (t, pred) pairs
            # accumulate via scatter-add
            cls = int(self.minibatch_class)
            self.confusion_matrix.devmem = self.confusion_matrix.devmem.at[
                cls, t, self.max_idx.devmem].add(mask.astype(jnp.int32))


class EvaluatorMSE(EvaluatorBase):
    """Mean-squared-error evaluator for regression / autoencoders
    (reference: ``EvaluatorMSE``)."""

    def __init__(self, workflow, name: str | None = None,
                 root_metric: bool = True, **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.target: Vector | None = None  # link from loader
        self.minibatch_class = TRAIN       # usually linked from loader
        self.metrics = Vector(name=f"{self.name}.metrics")  # summed sq err
        self.epoch_sse = Vector(name=f"{self.name}.epoch_sse")

    def region_key(self) -> tuple:
        return (int(self.minibatch_class),)

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        if self.output is None or not self.output:
            raise AttributeError(f"{self}: output not linked yet")
        self.err_output.reset(np.zeros(self.output.shape, dtype=np.float32))
        self.metrics.reset(np.zeros((), dtype=np.float32))
        if not self.epoch_sse:
            self.epoch_sse.reset(np.zeros(3, dtype=np.float32))
        self.init_vectors(self.err_output, self.metrics, self.epoch_sse,
                          self.output, self.target, self.minibatch_valid)

    def numpy_run(self) -> None:
        for vec in (self.output, self.target, self.minibatch_valid):
            vec.map_read()
        y = self.output.mem
        batch = y.shape[0]
        t = self.target.mem.reshape(batch, -1).astype(np.float32)
        y2 = y.reshape(batch, -1)
        mask, valid = self._valid_mask(np, batch)
        diff = mask[:, None] * (y2 - t)
        err = (diff * (2.0 / max(int(valid), 1))).reshape(y.shape)
        grad_inj = self._inject(np, 1)
        if grad_inj is not None:
            err = err + grad_inj
        self.err_output.map_invalidate()
        self.err_output.mem[...] = err
        self.metrics.map_invalidate()
        sse = np.float32(np.sum(diff * diff))
        loss_inj = self._inject(np, 0)
        if loss_inj is not None:
            sse = sse + np.float32(loss_inj)
        self.metrics.mem[...] = sse
        loss_ok = bool(np.isfinite(sse))
        self.epoch_sse.map_write()
        self.epoch_sse.mem[int(self.minibatch_class)] += \
            sse if loss_ok else 0.0
        self._seed_step_flags(np, loss_ok)

    def xla_run(self) -> None:
        # f32 math regardless of the activation storage dtype: the SSE
        # reduction over the whole minibatch would swamp small terms in
        # bf16, and the decision unit selects models on this number
        y = self.output.devmem.astype(jnp.float32)
        batch = y.shape[0]
        t = self.target.devmem.reshape(batch, -1).astype(jnp.float32)
        y2 = y.reshape(batch, -1)
        mask, valid = self._valid_mask(jnp, batch)
        diff = mask[:, None] * (y2 - t)
        denom = jnp.maximum(valid, 1).astype(y.dtype)
        err = (diff * (2.0 / denom)).reshape(y.shape)
        grad_inj = self._inject(jnp, 1)
        if grad_inj is not None:
            err = err + grad_inj.astype(err.dtype)
        self.err_output.devmem = err
        sse = jnp.sum(diff * diff)
        loss_inj = self._inject(jnp, 0)
        if loss_inj is not None:
            sse = sse + loss_inj
        self.metrics.devmem = sse
        loss_ok = jnp.isfinite(sse)
        self.epoch_sse.devmem = self.epoch_sse.devmem.at[
            int(self.minibatch_class)].add(jnp.where(loss_ok, sse, 0.0))
        self._seed_step_flags(jnp, loss_ok)


def jax_onehot(labels, n_classes: int, dtype):
    return (labels[:, None] ==
            jnp.arange(n_classes)[None, :]).astype(dtype)
