"""Token embedding — completes the sequence family (beyond the 2015
reference, which has no discrete-token models; SURVEY.md §5.7 marks
sequence machinery as this framework's extension).

``y[b, t] = W[tokens[b, t]]`` with a learned (V, D) table.  The loader
feeds token ids through the regular float ``minibatch_data`` path (the
unit rounds-and-casts to indices), so every existing loader works
unchanged.  Forward is a gather (XLA lowers to a dynamic-gather that
pipelines well on TPU); the backward is the adjoint scatter-add into
the table gradient, with the standard momentum/decay update riding the
GD base's ``weights`` machinery.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from znicz_tpu.ops.nn_units import Forward, GradientDescentBase


class Embedding(Forward):
    """Learned lookup table: int-valued (B, T) input → (B, T, D)."""

    def __init__(self, workflow, vocab_size: int, dim: int, name=None,
                 **kwargs) -> None:
        kwargs.setdefault("weights_filling", "gaussian")
        kwargs.setdefault("weights_stddev", 0.02)
        kwargs.setdefault("include_bias", False)
        super().__init__(workflow, name=name, **kwargs)
        self.vocab_size = int(vocab_size)
        self.dim = int(dim)

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        if self.input is None or not self.input:
            raise AttributeError(f"{self}: input not linked yet")
        if len(self.input.shape) != 2:
            raise ValueError(f"{self}: expected (batch, time) token "
                             f"input, got {self.input.shape}")
        # token ids ride the loader's float minibatch path — the
        # storage dtype must represent every id EXACTLY (bf16's 8-bit
        # mantissa corrupts integers above 256 silently)
        max_exact = {2: 256, 4: 2 ** 24, 8: 2 ** 53}.get(
            np.dtype(self.input.dtype).itemsize, 2 ** 24)
        if self.vocab_size - 1 > max_exact:
            raise ValueError(
                f"{self}: vocab_size {self.vocab_size} exceeds the "
                f"largest integer the input storage dtype "
                f"{self.input.dtype} represents exactly ({max_exact}) "
                f"— disable bf16 activation storage "
                f"(root.common.engine.bf16_activations=False) or use "
                f"a smaller vocabulary")
        if not self.weights:
            self.weights.reset(self.fill_array(
                (self.vocab_size, self.dim), self.weights_filling,
                self.weights_stddev, fan_in=self.dim))
        b, t = self.input.shape
        self.output.reset(np.zeros((b, t, self.dim),
                                   dtype=self.output_store_dtype))
        self.init_vectors(self.input, self.output, self.weights)

    def _tokens(self, xp, x):
        """Loader data arrives as floats; round to table indices and
        clip into range (out-of-vocab ids clamp to the last row)."""
        idx = xp.round(xp.asarray(x).astype(xp.float32)).astype(xp.int32)
        return xp.clip(idx, 0, self.vocab_size - 1)

    def numpy_run(self) -> None:
        self.input.map_read()
        self.weights.map_read()
        self.output.map_invalidate()
        tokens = self._tokens(np, self.input.mem)
        self.output.mem[...] = self.weights.mem[tokens]

    def xla_run(self) -> None:
        tokens = self._tokens(jnp, self.input.devmem)
        self.output.devmem = jnp.take(self.weights.devmem, tokens,
                                      axis=0)

    # -- autoregressive decode (round 12, serving.decode) ---------------
    def xla_embed(self, w, x):
        """Pure gather for the decode path: token ids (any float/int
        array, any shape) → table rows of shape ``x.shape + (D,)``.
        Same rounding/clipping contract as the training forward, so a
        decode engine feeding raw sampled ids sees identical
        embeddings."""
        return jnp.take(w, self._tokens(jnp, x), axis=0)


class GDEmbedding(GradientDescentBase):
    """Embedding backward: scatter-add of the error into the table
    gradient (the gather's adjoint).  First-layer unit — there is no
    err_input (token ids have no gradient)."""

    MATCHES = (Embedding,)
    REQUIRES_FORWARD_UNIT = True
    REQUIRES_INPUT = True

    def __init__(self, workflow, name=None, **kwargs):
        kwargs.setdefault("need_err_input", False)
        super().__init__(workflow, name=name, **kwargs)
        self.forward_unit: Embedding | None = None

    def initialize(self, device=None, **kwargs) -> None:
        if self.need_err_input:
            # token ids have no gradient — a layer wired BEFORE an
            # embedding would silently receive the zeros err_input the
            # base allocates; fail loudly instead
            raise ValueError(
                f"{self}: embedding must be the first trainable layer "
                f"(need_err_input=True was requested but token ids "
                f"have no gradient)")
        super().initialize(device=device, **kwargs)
        self.init_vectors(self.err_output, self.input, self.weights)

    def numpy_run(self) -> None:
        fwd = self.forward_unit
        for vec in (self.err_output, self.input):
            vec.map_read()
        self.weights.map_write()
        tokens = fwd._tokens(np, self.input.mem).reshape(-1)
        err = np.asarray(self.err_output.mem,
                         np.float32).reshape(len(tokens), -1)
        grad_w = np.zeros_like(self.weights.mem)
        np.add.at(grad_w, tokens, err)
        self._apply_weights_np(grad_w)

    def xla_run(self) -> None:
        fwd = self.forward_unit
        tokens = fwd._tokens(jnp, self.input.devmem).reshape(-1)
        err = self.err_output.devmem.astype(jnp.float32)
        err = err.reshape(tokens.shape[0], -1)
        grad_w = jnp.zeros(fwd.weights.shape, jnp.float32)
        grad_w = grad_w.at[tokens].add(err)
        self._apply_weights_xla(grad_w)
