"""Gradient-descent backward units for the fully-connected family
(reference: ``znicz/gd.py``).

Math (weights stored ``(in, out)``; see ``nn_units.py``):

.. code-block:: text

    δ_act       = err_output ⊙ act'(output)
    err_input   = δ_act @ Wᵀ
    dL/dW       = xᵀ @ δ_act          (GEMM on MXU)
    dL/db       = Σ_batch δ_act

followed by the shared momentum/decay/clip update in
:class:`~znicz_tpu.ops.nn_units.GradientDescentBase`.  The evaluator
emits ``err_output`` already normalized by batch size, so no ``1/N``
appears here.  On data-parallel meshes the update path (gradient fold
included) runs ZeRO-1 sharded over the data axis by default — the
family units only PRODUCE ``dL/dW``; the reduce-scatter / sharded
momentum / all-gather plumbing lives entirely in the base's
``_apply_param_xla``.

``GDSoftmax`` is the linear case: ``EvaluatorSoftmax`` produces the
combined softmax+cross-entropy derivative (``p − t``), exactly as the
reference's evaluator does.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from znicz_tpu.ops import activations_math
from znicz_tpu.ops.all2all import (
    All2All,
    All2AllRELU,
    All2AllSigmoid,
    All2AllSoftmax,
    All2AllStrictRELU,
    All2AllTanh,
)
from znicz_tpu.ops.nn_units import GradientDescentBase


class GradientDescent(GradientDescentBase):
    """Backward for linear ``All2All`` (reference: ``GradientDescent``)."""

    MATCHES = (All2All,)
    ACTIVATION = "linear"

    def __init__(self, workflow, name=None, **kwargs):
        super().__init__(workflow, name=name, **kwargs)
        self.activation = activations_math.get(self.ACTIVATION)

    def initialize(self, device=None, **kwargs) -> None:
        if self.input is None or not self.input:
            raise AttributeError(f"{self}: input not linked yet")
        super().initialize(device=device, **kwargs)
        self.init_vectors(self.err_input, self.err_output, self.input,
                          self.output, self.weights, self.bias)

    # -- shared math ----------------------------------------------------
    def _delta(self, xp, err_output, output, x2d):
        """Activation-derivative folding: δ_act over flat (N, out)."""
        batch = err_output.shape[0]
        d = err_output.reshape(batch, -1)
        y = output.reshape(batch, -1)
        deriv = self.activation.derivative(
            xp, y, x2d if self.activation.needs_input else None)
        return d * deriv

    def numpy_run(self) -> None:
        for vec in (self.err_output, self.input, self.output):
            vec.map_read()
        self.weights.map_write()
        x = self.input.mem.astype(np.float32)
        batch = x.shape[0]
        x2d = x.reshape(batch, -1)
        delta = self._delta(np, self.err_output.mem, self.output.mem, x2d)
        if self.need_err_input:
            self.err_input.map_invalidate()
            ei = delta @ self.weights.mem.T
            self.err_input.mem[...] = ei.reshape(self.input.shape)
        grad_w = x2d.T @ delta
        self._apply_weights_np(grad_w)
        if self.bias is not None and self.bias:
            self.bias.map_write()
            self._apply_bias_np(delta.sum(axis=0))

    def xla_run(self) -> None:
        x = self.input.devmem
        batch = x.shape[0]
        x2d = x.reshape(batch, -1)
        w = self.weights.devmem
        delta = self._delta(jnp, self.err_output.devmem, self.output.devmem,
                            x2d)
        if self.need_err_input:
            self.err_input.devmem = self.mxu_dot(
                jnp, delta, w.T).reshape(x.shape)
        grad_w = self.mxu_dot(jnp, x2d.T, delta)
        self._apply_weights_xla(grad_w)
        if self.bias is not None and self.bias:
            self._apply_bias_xla(
                delta.astype(jnp.float32).sum(axis=0))


class GDTanh(GradientDescent):
    MATCHES = (All2AllTanh,)
    ACTIVATION = "tanh"


class GDRELU(GradientDescent):
    MATCHES = (All2AllRELU,)
    ACTIVATION = "relu"


class GDStrictRELU(GradientDescent):
    MATCHES = (All2AllStrictRELU,)
    ACTIVATION = "strict_relu"


class GDSigmoid(GradientDescent):
    MATCHES = (All2AllSigmoid,)
    ACTIVATION = "sigmoid"


class GDSoftmax(GradientDescent):
    """Linear backward: evaluator already folded the softmax+CE
    derivative into ``err_output`` (reference: ``GDSoftmax``)."""
    MATCHES = (All2AllSoftmax,)
    ACTIVATION = "linear"
