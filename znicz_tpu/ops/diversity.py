"""Filter-similarity diagnostics (reference: ``znicz/diversity.py`` —
helpers measuring how similar a layer's learned kernels are, used to
spot wasted capacity: near-duplicate filters mean the layer effectively
has fewer features than weights).

TPU-first shape: the whole pairwise-similarity computation is ONE
normalized Gram matrix — ``W_n @ W_n.T`` on unit-normalized, centered
filter rows — so it rides the MXU in a single ``jnp.dot`` instead of
the reference's per-pair host loops.  Grouping near-duplicates is a
tiny host-side union-find over the (n_filters × n_filters) matrix,
which is control-plane work by nature.

Both a functional API (:func:`filter_similarity`,
:func:`similar_kernel_groups`, :func:`diversity_score`) and a workflow
unit (:class:`FilterDiversityReporter`) are provided; the unit logs the
per-layer diversity each validation epoch the way the reference's
plotters consumed the helpers.
"""

from __future__ import annotations

import numpy as np

from znicz_tpu.memory import Vector
from znicz_tpu.units import Unit


def _as_filter_rows(weights: np.ndarray) -> np.ndarray:
    """(… , n_filters_last) conv kernels or (n_in, n_out) FC weights →
    (n_filters, fan_in) rows.

    Convention: conv weights are HWIO (ky, kx, c_in, n_kernels) — the
    layout ``ops/conv.py`` trains; FC weights are (in, out).  In both,
    the LAST axis indexes filters.
    """
    arr = np.asarray(weights, dtype=np.float32)
    if arr.ndim < 2:
        raise ValueError(f"weights must be ≥2-D, got {arr.shape}")
    return arr.reshape(-1, arr.shape[-1]).T


def filter_similarity(weights, xp=np) -> np.ndarray:
    """Pairwise Pearson correlation of a layer's filters.

    Returns an (n_filters, n_filters) symmetric matrix with unit
    diagonal.  ``xp=jnp`` keeps the Gram product on the accelerator
    (one MXU matmul) and expects pre-shaped 2-D filter rows; the
    default runs the numpy oracle on any weights layout.
    """
    rows = _as_filter_rows(weights) if xp is np else weights
    centered = rows - rows.mean(axis=1, keepdims=True)
    norms = xp.sqrt((centered ** 2).sum(axis=1, keepdims=True))
    unit = centered / xp.maximum(norms, 1e-12)
    return xp.dot(unit, unit.T)


def similar_kernel_groups(weights, threshold: float = 0.85
                          ) -> list[list[int]]:
    """Groups of near-duplicate filters: connected components of the
    |correlation| ≥ threshold graph, singletons dropped (reference
    semantics: report only the redundant clusters)."""
    sim = filter_similarity(weights)
    n = sim.shape[0]
    parent = list(range(n))

    def find(i: int) -> int:
        while parent[i] != i:
            parent[i] = parent[parent[i]]
            i = parent[i]
        return i

    for i in range(n):
        for j in range(i + 1, n):
            if abs(sim[i, j]) >= threshold:
                parent[find(i)] = find(j)
    groups: dict[int, list[int]] = {}
    for i in range(n):
        groups.setdefault(find(i), []).append(i)
    return sorted((g for g in groups.values() if len(g) > 1),
                  key=lambda g: (-len(g), g[0]))


def diversity_score(weights, threshold: float = 0.85,
                    groups: list[list[int]] | None = None) -> float:
    """Fraction of filters NOT in any near-duplicate group — 1.0 means
    every filter is distinct, 0.0 means total redundancy.  Pass
    precomputed ``groups`` to skip recomputing the similarity matrix."""
    arr = _as_filter_rows(weights)
    n = arr.shape[0]
    if n == 0:
        return 1.0
    if groups is None:
        groups = similar_kernel_groups(weights, threshold)
    redundant = sum(len(g) for g in groups)
    return 1.0 - redundant / n


class FilterDiversityReporter(Unit):
    """Logs per-layer filter diversity when the decision unit reports
    an improved validation epoch (the hook the reference's diversity
    plotters used).

    Link pattern::

        rep = FilterDiversityReporter(wf)
        rep.weights_list = [fwd.weights for fwd in wf.forwards[:-1]]
        rep.link_from(wf.decision)
        rep.gate_skip = ~wf.decision.improved   # only on improvement
    """

    def __init__(self, workflow, name: str | None = None,
                 threshold: float = 0.85, **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.threshold = float(threshold)
        self.weights_list: list[Vector] = []
        #: last computed {layer_name: (score, n_groups)}
        self.last_report: dict[str, tuple[float, int]] = {}

    def run(self) -> None:
        self.last_report = {}
        for vec in self.weights_list:
            if not isinstance(vec, Vector) or not vec:
                continue
            vec.map_read()
            weights = np.array(vec.mem)
            groups = similar_kernel_groups(weights, self.threshold)
            score = diversity_score(weights, self.threshold,
                                    groups=groups)
            self.last_report[vec.name] = (score, len(groups))
            self.info("%s: diversity %.3f (%d duplicate groups)",
                      vec.name, score, len(groups))
