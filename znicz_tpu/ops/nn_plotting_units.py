"""NN-specific plotters: weights-as-images, SOM hit maps, MSE
histograms (reference: ``znicz/nn_plotting_units.py`` — ``Weights2D``,
``KohonenHits``, ``MSEHistogram``)."""

from __future__ import annotations

import numpy as np

from znicz_tpu.memory import Vector
from znicz_tpu.plotting_units import Plotter


def tile_filters(weights: np.ndarray, sample_shape=None,
                 max_tiles: int = 64) -> np.ndarray:
    """Arrange per-output-unit weight rows as a grid of 2-D tiles.

    ``weights`` is ``(in_features, out_features)`` (this framework's
    layout) — each column is one unit's receptive field, reshaped to
    ``sample_shape`` (H×W or H×W×C; inferred square if omitted).
    """
    w = np.asarray(weights)
    if w.ndim == 4:  # conv (kx, ky, c_in, n_kernels) → tile per kernel
        kx, ky, c_in, n_k = w.shape
        cols = w.reshape(kx * ky * c_in, n_k)
        sample_shape = (kx, ky, c_in)
        w = cols
    n_in, n_out = w.shape
    if sample_shape is None:
        side = int(np.sqrt(n_in))
        if side * side != n_in:
            side = 1
        sample_shape = (side, max(1, n_in // side))
    n = min(n_out, max_tiles)
    grid = int(np.ceil(np.sqrt(n)))
    h, wd = sample_shape[0], sample_shape[1]
    channels = sample_shape[2] if len(sample_shape) > 2 else 1
    canvas = np.zeros((grid * (h + 1) + 1, grid * (wd + 1) + 1, channels),
                      dtype=np.float32)
    for i in range(n):
        tile = w[:, i].reshape(h, wd, channels)
        lo, hi = tile.min(), tile.max()
        if hi > lo:
            tile = (tile - lo) / (hi - lo)
        r, c = divmod(i, grid)
        canvas[1 + r * (h + 1):1 + r * (h + 1) + h,
               1 + c * (wd + 1):1 + c * (wd + 1) + wd] = tile
    if channels == 1:
        return canvas[..., 0]
    if channels == 3:
        return canvas
    # imshow can only draw 1/3/4-channel images — collapse the rest
    return canvas.mean(axis=-1)


class Weights2D(Plotter):
    """Renders a layer's weight columns as a tiled image (reference:
    ``Weights2D`` — 'filters as pictures')."""

    def __init__(self, workflow, name: str | None = None,
                 sample_shape=None, max_tiles: int = 64, **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.input: Vector | None = None  # link to a weights Vector
        self.sample_shape = sample_shape
        self.max_tiles = max_tiles

    def make_payload(self) -> dict | None:
        vec = self.input
        if not isinstance(vec, Vector) or not vec:
            return None
        vec.map_read()
        img = tile_filters(np.array(vec.mem), self.sample_shape,
                           self.max_tiles)
        return {"kind": "image", "data": img, "cmap": "gray",
                "title": f"{self.name} ({vec.shape})"}


class KohonenHits(Plotter):
    """SOM winner-hit map as a heatmap over the neuron grid
    (reference: ``KohonenHits``)."""

    def __init__(self, workflow, name: str | None = None, **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.input: Vector | None = None   # KohonenForward.hits
        self.shape_grid: tuple[int, int] | None = None

    def make_payload(self) -> dict | None:
        vec = self.input
        if not isinstance(vec, Vector) or not vec \
                or self.shape_grid is None:
            return None
        vec.map_read()
        sy, sx = self.shape_grid
        return {"kind": "matrix", "data": np.array(vec.mem).reshape(sy, sx),
                "cmap": "hot", "title": f"{self.name} hits"}


class MSEHistogram(Plotter):
    """Histogram of per-sample squared error for the last minibatch
    (reference: ``MSEHistogram``)."""

    def __init__(self, workflow, name: str | None = None,
                 n_bins: int = 20, **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.output: Vector | None = None   # net output
        self.target: Vector | None = None   # ground truth
        self.n_bins = n_bins

    def make_payload(self) -> dict | None:
        if not (isinstance(self.output, Vector) and self.output
                and isinstance(self.target, Vector) and self.target):
            return None
        self.output.map_read()
        self.target.map_read()
        y = np.asarray(self.output.mem, dtype=np.float32)
        t = np.asarray(self.target.mem, dtype=np.float32).reshape(y.shape)
        per_sample = ((y - t) ** 2).reshape(y.shape[0], -1).sum(axis=1)
        counts, edges = np.histogram(per_sample, bins=self.n_bins)
        centers = 0.5 * (edges[:-1] + edges[1:])
        return {"kind": "hist", "data": counts, "bin_centers": centers,
                "bar_width": float(edges[1] - edges[0]) * 0.9,
                "ylabel": "samples", "title": f"{self.name} mse"}
