"""Multi-head attention units — the long-context op family.

The 2015 reference has no attention (SURVEY.md §5.7), but this
framework treats long-context machinery as first-class: this module
makes the :mod:`znicz_tpu.parallel.ring_attention` primitive
consumable from the unit graph.

``MultiHeadAttention`` maps (B, T, D) → (B, T, D):

.. code-block:: text

    qkv  = x @ W_qkv + b_qkv          (D, 3·D) packed projection
    q,k,v split → (B, T, H, D/H)
    o    = softmax(q·kᵀ/√dₕ [+causal]) · v
    y    = concat(o) @ W_out + b_out   (D, D)

``seq_parallel=True`` runs the attention core **blockwise around the
ICI ring** over the device mesh's ``model`` axis (K/V shards rotate
via ``ppermute``, online-softmax accumulation; no device materializes
the (T, T) score matrix) — sequences longer than one chip's HBM shard
over the mesh exactly like the scaling-book recipe.  The unit's
output Vector carries ``model_shard_dim=1`` (the time axis) so the
sharding annotation flows through the graph.

Backward (``GDMultiHeadAttention``): ``jax.vjp`` of the forward on
the XLA path — this differentiates THROUGH the shard_map/ppermute
ring, so sequence-parallel training needs no hand-written collective
gradients — validated against the explicit analytic numpy oracle.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from znicz_tpu.memory import Vector
from znicz_tpu.ops.nn_units import Forward, GradientDescentBase
from znicz_tpu.parallel.axis import DATA_AXIS, MODEL_AXIS, SEQ_AXIS


def _split_heads(qkv, n_heads: int):
    """(B, T, 3D) → three (B, T, H, D/H) (pure slicing/reshape —
    backend-agnostic)."""
    b, t, d3 = qkv.shape
    d = d3 // 3
    dh = d // n_heads
    q, k, v = qkv[..., :d], qkv[..., d:2 * d], qkv[..., 2 * d:]
    reshape = (b, t, n_heads, dh)
    return q.reshape(reshape), k.reshape(reshape), v.reshape(reshape)


def _local_attention_np(q, k, v, causal: bool):
    """Numpy oracle core (mirrors parallel.ring_attention's
    local_attention)."""
    d = q.shape[-1]
    s = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        mask = np.arange(tq)[:, None] >= np.arange(tk)[None, :]
        s = np.where(mask[None, None], s, -1e30)
    s = s - s.max(axis=-1, keepdims=True)
    p = np.exp(s)
    p = p / p.sum(axis=-1, keepdims=True)
    o = np.einsum("bhqk,bkhd->bqhd", p, v)
    return o, p


class MultiHeadAttention(Forward):
    """Weighted multi-head self-attention layer."""

    EXPORT_PARAMS = ("weights", "bias", "weights_out", "bias_out")

    def __init__(self, workflow, n_heads: int, causal: bool = False,
                 seq_parallel: bool = False,
                 flash_block_k: int | None = None,
                 name=None, **kwargs) -> None:
        # attention defaults to fan-scaled init (the reference's
        # fixed-stddev fillings predate attention entirely)
        kwargs.setdefault("weights_filling", "xavier")
        super().__init__(workflow, name=name, **kwargs)
        self.n_heads = int(n_heads)
        self.causal = bool(causal)
        #: flash-style blocked local attention: scan over K/V blocks
        #: of this size with the ring's online-softmax fold, so the
        #: (T, T) score matrix never materializes in HBM (None = the
        #: plain form; long sequences want T×T HBM traffic gone —
        #: measured A/B in SEQ_BENCH.json)
        self.flash_block_k = (None if flash_block_k is None
                              else int(flash_block_k))
        #: ring attention over the mesh's model axis (time-sharded).
        #: This is the CONFIGURED request and is never mutated;
        #: :attr:`ring_active` is the per-initialize resolution (a mesh
        #: without a model axis falls back to local attention, but
        #: re-initializing on a capable mesh re-engages the ring).
        self.seq_parallel = bool(seq_parallel)
        self._ring_active = False
        #: pullback stashed by xla_run for the GD pair (same trace;
        #: transient — never pickled, cleared by the consumer)
        self._traced_vjp = None
        self.weights_out = Vector(name=f"{self.name}.weights_out")
        self.bias_out = Vector(name=f"{self.name}.bias_out")

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        if self.input is None or not self.input:
            raise AttributeError(f"{self}: input not linked yet")
        if len(self.input.shape) != 3:
            raise ValueError(f"{self}: expected (batch, time, features) "
                             f"input, got {self.input.shape}")
        b, t, d = self.input.shape
        if d % self.n_heads:
            raise ValueError(f"{self}: features {d} not divisible by "
                             f"{self.n_heads} heads")
        if self.flash_block_k and t % self.flash_block_k:
            raise ValueError(
                f"{self}: time axis {t} not divisible by "
                f"flash_block_k {self.flash_block_k}")
        if not self.weights:
            self.weights.reset(self.fill_array(
                (d, 3 * d), self.weights_filling,
                self.weights_stddev, fan_in=d))
        if not self.weights_out:
            self.weights_out.reset(self.fill_array(
                (d, d), self.weights_filling,
                self.weights_stddev, fan_in=d))
        if self.include_bias:
            if not self.bias:
                self.bias.reset(np.zeros(3 * d, np.float32))
            if not self.bias_out:
                self.bias_out.reset(np.zeros(d, np.float32))
        self.output.reset(np.zeros((b, t, d),
                                   dtype=self.output_store_dtype))
        from jax.sharding import PartitionSpec as P
        from znicz_tpu.parallel import partition
        mesh = getattr(self.device, "mesh", None)
        self._ring_active = False
        #: mesh axis the ring rotates over: a 3-D (data × model × seq)
        #: mesh gives sequence parallelism its OWN axis so DP × TP ×
        #: SP compose; 2-D meshes keep the historical model-axis ring
        self._ring_axis = (SEQ_AXIS if mesh is not None
                           and mesh.shape.get(SEQ_AXIS, 1) > 1
                           else MODEL_AXIS)
        # the default (non-ring) placement replaces any stale
        # time-sharding rule from a prior ring-engaged initialize;
        # the ring branch below re-declares when it actually engages
        self.partition_leaf("output", partition.BATCH)
        if self.seq_parallel:
            ring_n = 1 if mesh is None \
                else mesh.shape.get(self._ring_axis, 1)
            if ring_n < 2:
                # no ring to ride — fall back to local attention (the
                # math is identical; seq_parallel is a layout choice).
                # The configured flag stays intact so a later
                # re-initialize on a capable mesh engages the ring.
                pass
            else:
                if t % ring_n:
                    raise ValueError(
                        f"{self}: time axis {t} not divisible by the "
                        f"{self._ring_axis}-axis size {ring_n}")
                self._ring_active = True
                # time rides the ring: declared, not hand-set
                self.partition_leaf(
                    "output", P(DATA_AXIS, self._ring_axis))
        # fused flash-attention Pallas kernel (ops/pallas_attention):
        # DEFAULT ON for real TPU devices — the measured winner at
        # every T (chip A/B in PERF.md round 5 / SEQ_BENCH.json:
        # 2.51M vs 1.63M tokens/s at T=2048, and the only form that
        # runs T≥8k on one chip at speed).  Opt out with
        # ``root.common.engine.flash_attention = False``; resolved
        # ONCE here like every engine flag.  Since round 6 the RING
        # path folds with the same kernel per hop
        # (``engine.ring_pallas_fold``, auto = TPU/interpret); shapes
        # the kernel's tiling cannot cover fall back to the XLA cores
        # (local) or the scan fold (ring).
        from znicz_tpu.ops import pallas_attention, pallas_kernels
        from znicz_tpu.parallel.mesh import kernel_shard_spec, \
            spec_divides
        from znicz_tpu.utils.config import root
        flag = root.common.engine.get("flash_attention", "auto")
        if flag == "auto":
            flag = pallas_kernels.is_tpu_device(self.device)
        # interpret-mode lever: lets the virtual CPU mesh run the REAL
        # kernels (shard_map oracle tests / dryruns); never default
        interpret = bool(root.common.engine.get("pallas_interpret",
                                                False))
        dh = d // self.n_heads
        tpu_capable = (pallas_kernels.is_tpu_device(self.device)
                       or interpret)
        # head packing (round 6, ``engine.flash_head_pack``): pairs of
        # dh≤64 heads ride one 128-lane kernel program — exact
        # per-head math, kernel-boundary reshape only.  OPT-IN pending
        # the chip A/B (the decision rule: kept only if it moves
        # toward the head_dim-128 MFU-0.405 ceiling — PERF.md).
        head_pack = pallas_attention.resolve_head_pack(
            root.common.engine.get("flash_head_pack", False),
            self.n_heads, dh)
        #: which fold the ring runs ("pallas"/"scan"; None = no ring)
        #: — the multichip dryrun attests this
        self._ring_fold = None
        self._ring_block_q = None
        self._ring_block_k = self.flash_block_k
        self._ring_pack = 1
        if self._ring_active:
            from znicz_tpu.parallel.ring_attention import \
                ring_fold_choice
            rflag = root.common.engine.get("ring_pallas_fold", "auto")
            if rflag == "auto":
                rflag = tpu_capable
            self._ring_fold, self._ring_block_q, self._ring_block_k \
                = ring_fold_choice(
                    mesh, (b, t, self.n_heads, dh),
                    axis_name=self._ring_axis,
                    block_k=self.flash_block_k,
                    pallas_fold=bool(rflag), head_pack=head_pack)
            self._ring_pack = (head_pack
                               if self._ring_fold == "pallas" else 1)
        bq = min(pallas_attention.BLOCK_Q, t)
        bk = min(self.flash_block_k or pallas_attention.BLOCK_K, t)
        if self.causal and not self._ring_active:
            # causal block auto-pick (round 6, verdict item 3): at
            # small T the default 1024² tiles leave a 2×2 grid with
            # one skippable tile, so causal paid non-causal step time.
            # ``engine.flash_causal_block``: "auto" = deepen the grid
            # to ≥4 K-tiles (causal_block_for), int = force that
            # block.  Default OFF until the chip A/B lands (no chip in
            # this container — the SEQ_CBLOCK bench arm is the hook).
            cblk = root.common.engine.get("flash_causal_block", None)
            if cblk == "auto":
                bq, bk = pallas_attention.causal_block_for(t, bq, bk)
            elif cblk and t % int(cblk) == 0:
                bq = bk = min(int(cblk), t)
        self._flash_pack = head_pack
        self._flash_block_q, self._flash_block_k = bq, bk
        engaged = (
            bool(flag)
            and tpu_capable
            and not self._ring_active
            # T must tile evenly and the head dim must be lane-legal
            # (dh % 8 — e.g. dh=1 via a to_sequence net would crash
            # Mosaic at trace instead of falling back; ADVICE round 5)
            and pallas_attention.kernel_legal(t, t, dh, bq, bk))
        self._flash_interpret = interpret
        self._flash_mesh = None
        self._flash_spec = None
        if engaged and mesh is not None and mesh.size > 1:
            # mesh-native path: the opaque pallas_call has no GSPMD
            # sharding rule — un-shard_mapped on a multi-device mesh
            # it would replicate-and-gather the batch-sharded operands
            # onto every device.  Run it per-shard under shard_map
            # with the batch riding the data axis instead;
            # ``engine.pallas_shard_map = False`` restores the
            # conservative single-device gate (kernel off on meshes —
            # the safe fallback, mirroring _pallas_ln's old guard).
            spec, _ = kernel_shard_spec(mesh, 4)
            engaged = (
                bool(root.common.engine.get("pallas_shard_map", True))
                and getattr(self.input, "model_shard_dim", None) is None
                and spec_divides(mesh, (b, t, self.n_heads, dh), spec))
            if engaged:
                self._flash_mesh, self._flash_spec = mesh, spec
        self._flash_pallas = engaged
        self.init_vectors(self.input, self.output, self.weights,
                          self.bias, self.weights_out, self.bias_out)

    @property
    def ring_active(self) -> bool:
        """True when THIS initialization actually rides the ring
        (``seq_parallel`` requested AND the mesh has a model axis)."""
        return self._ring_active

    # -- pure forward (jnp; the backward vjp's this) --------------------
    def xla_forward(self, x, w_qkv, b_qkv, w_out, b_out):
        b, t, d = x.shape
        x32 = x.astype(jnp.float32)
        qkv = self.mxu_dot(jnp, x32.reshape(b * t, d), w_qkv)
        if b_qkv is not None:
            qkv = qkv + b_qkv
        # attention-core GEMM/storage dtype: the repo-wide bf16-inputs/
        # f32-accumulation convention (profiled: the core's (T, T)
        # tensors are the step's HBM-bandwidth sink — PERF.md round 5).
        # Cast ONCE here so q/k/v reach the core (and the flash
        # kernel's layout transposes) at half width.
        dot_dtype = self.mxu_dtype
        if dot_dtype is not None:
            qkv = qkv.astype(dot_dtype)
        q, k, v = _split_heads(qkv.reshape(b, t, 3 * d), self.n_heads)
        if self.ring_active:
            from znicz_tpu.parallel.ring_attention import \
                sequence_sharded_attention
            o = sequence_sharded_attention(
                self.device.mesh, q, k, v, causal=self.causal,
                axis_name=getattr(self, "_ring_axis", MODEL_AXIS),
                dot_dtype=dot_dtype,
                block_k=self.flash_block_k,
                # round 6: the per-hop fold is the flash KERNEL when
                # the gate resolved it legal (initialize); the scan
                # fold is the gated fallback
                pallas_fold=(getattr(self, "_ring_fold", None)
                             == "pallas"),
                pallas_interpret=getattr(self, "_flash_interpret",
                                         False),
                pallas_block_q=getattr(self, "_ring_block_q", None),
                head_pack=getattr(self, "_ring_pack", 1))
        elif getattr(self, "_flash_pallas", False):
            from znicz_tpu.ops import pallas_attention
            # (a head-major fast path — contracting the kernel's
            # native (B, H, T, Dh) output directly with a reshaped
            # W_out to skip the boundary transposes — was measured
            # NEUTRAL within the ±2–4% run band and reverted per the
            # decision rule: neutral keeps the simpler path.  PERF.md
            # round 5.)
            o = pallas_attention.flash_attention(
                q, k, v, causal=self.causal,
                block_q=getattr(self, "_flash_block_q",
                                pallas_attention.BLOCK_Q),
                block_k=getattr(self, "_flash_block_k",
                                self.flash_block_k
                                or pallas_attention.BLOCK_K),
                dot_dtype=dot_dtype,
                interpret=getattr(self, "_flash_interpret", False),
                mesh=getattr(self, "_flash_mesh", None),
                spec=getattr(self, "_flash_spec", None),
                head_pack=getattr(self, "_flash_pack", 1))
        elif self.flash_block_k:
            from znicz_tpu.parallel.ring_attention import \
                local_attention_blocked
            o = local_attention_blocked(q, k, v, causal=self.causal,
                                        block_k=self.flash_block_k,
                                        dot_dtype=dot_dtype)
        else:
            from znicz_tpu.parallel.ring_attention import local_attention
            o = local_attention(q, k, v, causal=self.causal,
                                dot_dtype=dot_dtype)
        y = self.mxu_dot(jnp, o.reshape(b * t, d), w_out)
        if b_out is not None:
            y = y + b_out
        return y.reshape(b, t, d)

    def xla_run(self) -> None:
        args = (self.input.devmem, self.weights.devmem,
                self.bias.devmem if self.include_bias else None,
                self.weights_out.devmem,
                self.bias_out.devmem if self.include_bias else None)
        if not self.output._tracing:
            # eager (non-region) execution: plain forward.  Stashing a
            # pullback here would pin the forward residuals — for the
            # plain core that includes the (B, H, T, T) probability
            # tensor — in HBM across steps of forward-only workflows.
            self._traced_vjp = None
            self.output.devmem = self.xla_forward(*args)
            return
        # region trace: compute through jax.vjp and STASH the pullback
        # for this unit's GD pair — both are traced into one program,
        # and re-deriving the vjp there would re-run the forward.  XLA
        # CSE merges the duplicated einsums of the plain core, but an
        # opaque pallas_call (the fused flash kernel) is never CSE'd,
        # so the kernel executed twice per step (measured +3.4 ms at
        # T=2048 — PERF.md round 5).  In eval-mode region variants the
        # unused pullback is dead code and XLA drops it.
        out, self._traced_vjp = jax.vjp(
            lambda x, wq, bq, wo, bo: self.xla_forward(
                x, wq, bq, wo, bo), *args)
        self.output.devmem = out

    # -- autoregressive decode (round 12, serving.decode) ---------------
    # Pure functions of their arguments (weights ride in as leaves, no
    # Vector state) so the decode engine can AOT-compile them exactly
    # like export's forward programs.  Math is plain f32 einsum — the
    # decode-side GEMMs are (B,1,·) slivers where the flash kernel's
    # tiling has nothing to win, and f32 keeps the incremental path
    # numerically aligned with the full-forward oracle.
    def xla_prefill(self, x, w_qkv, b_qkv, w_out, b_out):
        """Causal forward over a (possibly right-padded) prompt that
        also returns the per-position K/V: (B, T, D) →
        ``(y, k, v)`` with k/v shaped (B, T, H, Dh) for the cache.

        Padded tail positions produce garbage k/v rows — harmless by
        construction: causal masking keeps them out of every real
        position's softmax here, and the decode step overwrites row
        ``pos`` before its mask (``<= pos``) ever admits it.
        """
        b, t, d = x.shape
        qkv = x.astype(jnp.float32).reshape(b * t, d) @ w_qkv
        if b_qkv is not None:
            qkv = qkv + b_qkv
        q, k, v = _split_heads(qkv.reshape(b, t, 3 * d), self.n_heads)
        dh = d // self.n_heads
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(
            jnp.float32(dh))
        if self.causal:
            mask = (jnp.arange(t)[:, None] >= jnp.arange(t)[None, :])
            s = jnp.where(mask[None, None], s, -1e30)
        s = s - s.max(axis=-1, keepdims=True)
        p = jnp.exp(s)
        p = p / p.sum(axis=-1, keepdims=True)
        o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
        y = o.reshape(b * t, d) @ w_out
        if b_out is not None:
            y = y + b_out
        return y.reshape(b, t, d), k, v

    def xla_decode_step(self, x, k_cache, v_cache, pos,
                        w_qkv, b_qkv, w_out, b_out):
        """One incremental token: write this position's K/V into the
        cache, attend the new query over the cached prefix.

        ``x``: (B, 1, D) current-token features; ``k_cache``/
        ``v_cache``: (B, Tmax, H, Dh) per-sequence cache pages;
        ``pos``: (B,) int32 position index of THIS token per sequence
        (ragged — sequences in one decode batch sit at different
        depths).  Returns ``(y, k_cache, v_cache)`` with the caches
        functionally updated at ``pos`` — under input donation the
        update is in-place in HBM, so a warmed decode loop allocates
        nothing per token and compiles nothing (shapes pinned by the
        live-batch bucket).
        """
        b, one, d = x.shape
        t_max = k_cache.shape[1]
        qkv = x.astype(jnp.float32).reshape(b, d) @ w_qkv
        if b_qkv is not None:
            qkv = qkv + b_qkv
        q, k, v = _split_heads(qkv.reshape(b, 1, 3 * d), self.n_heads)
        dh = d // self.n_heads
        rows = jnp.arange(b)
        k_cache = k_cache.at[rows, pos].set(k[:, 0])
        v_cache = v_cache.at[rows, pos].set(v[:, 0])
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k_cache) / jnp.sqrt(
            jnp.float32(dh))
        # length mask: the prefix [0, pos] is live, everything beyond
        # is stale garbage from a prior tenant of the slot or the
        # prefill's padded tail — never admitted
        mask = jnp.arange(t_max)[None, :] <= pos[:, None]
        s = jnp.where(mask[:, None, None, :], s, -1e30)
        s = s - s.max(axis=-1, keepdims=True)
        p = jnp.exp(s)
        p = p / p.sum(axis=-1, keepdims=True)
        o = jnp.einsum("bhqk,bkhd->bqhd", p, v_cache)
        y = o.reshape(b, d) @ w_out
        if b_out is not None:
            y = y + b_out
        return y.reshape(b, 1, d), k_cache, v_cache

    # -- paged decode (round 15, serving.decode) ------------------------
    # Same math as the flat steps above, but K/V live in a shared page
    # POOL (P, ptok, H, Dh) addressed through a per-sequence block
    # table instead of per-slot (maxT, H, Dh) strips.  Three wins the
    # flat layout cannot express: (1) attention reads only the pages a
    # sequence actually occupies (the nb block bucket), not the full
    # maxT reservation; (2) full pages are SHARED between sequences
    # with a common prompt prefix (refcounted, copy-on-write at
    # divergence — host-side, serving/decode.py); (3) live capacity is
    # bounded by tokens, not slots.  Tables carry nb+1 entries: the
    # last is the trash page, where padded lanes/positions scatter
    # their garbage writes.
    def _project_qkv(self, x, w_qkv, b_qkv):
        """(B, W, D) → q, k, v each (B, W, H, Dh)."""
        b, w, d = x.shape
        qkv = x.astype(jnp.float32).reshape(b * w, d) @ w_qkv
        if b_qkv is not None:
            qkv = qkv + b_qkv
        return _split_heads(qkv.reshape(b, w, 3 * d), self.n_heads)

    def _out_proj(self, o, w_out, b_out):
        b, w, h, dh = o.shape
        y = o.reshape(b * w, h * dh) @ w_out
        if b_out is not None:
            y = y + b_out
        return y.reshape(b, w, h * dh)

    def _kv_quantize(self, rows):
        """(B, W, H, Dh) f32 K/V rows → ``(q int8, scale f32
        (B, W, H))`` — symmetric absmax over each row's head vector
        (round 21).  Dequantization ``q.astype(f32) * s`` is exact on
        representable values, so the quantize/dequantize pair adds one
        rounding step per element and nothing else."""
        s = jnp.maximum(jnp.max(jnp.abs(rows), axis=-1), 1e-8) / 127.0
        q = jnp.clip(jnp.round(rows / s[..., None]),
                     -127, 127).astype(jnp.int8)
        return q, s

    def _paged_attend(self, q, k_pool, v_pool, tables, q_pos,
                      k_scale=None, v_scale=None):
        """Attend (B, W, H, Dh) queries at global positions ``q_pos``
        (B, W) over the pages in ``tables`` (B, nb+1; last = trash).
        Key position ``p`` is admitted iff ``p <= q_pos`` — stale rows
        from a prior page tenant and this window's padded tail sit
        beyond every real query's position by construction.

        With ``k_scale``/``v_scale`` pools (round 21) the K/V pools
        hold int8 rows dequantized on gather — the HBM-resident cache
        is int8 + one f32 scale per (token, head)."""
        nb = tables.shape[1] - 1
        ptok = k_pool.shape[1]
        dh = q.shape[-1]
        # (B, nb, ptok, H, Dh) → (B, nb·ptok, H, Dh): the gather is
        # bounded by the BLOCK BUCKET nb, not maxT — a short sequence
        # attends over exactly the pages it occupies
        k_rows = k_pool[tables[:, :nb]].reshape(
            q.shape[0], nb * ptok, self.n_heads, dh)
        v_rows = v_pool[tables[:, :nb]].reshape(
            q.shape[0], nb * ptok, self.n_heads, dh)
        if k_scale is not None:
            ks = k_scale[tables[:, :nb]].reshape(
                q.shape[0], nb * ptok, self.n_heads)
            vs = v_scale[tables[:, :nb]].reshape(
                q.shape[0], nb * ptok, self.n_heads)
            k_rows = k_rows.astype(jnp.float32) * ks[..., None]
            v_rows = v_rows.astype(jnp.float32) * vs[..., None]
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k_rows) / jnp.sqrt(
            jnp.float32(dh))
        mask = jnp.arange(nb * ptok)[None, None, :] \
            <= q_pos[:, :, None]
        s = jnp.where(mask[:, None], s, -1e30)
        s = s - s.max(axis=-1, keepdims=True)
        p = jnp.exp(s)
        p = p / p.sum(axis=-1, keepdims=True)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v_rows)

    def _paged_write(self, pool, rows, tables, positions, live):
        """Scatter (B, W, H, Dh) K or V rows through the block table at
        global ``positions`` (B, W); lanes/positions with ``live``
        False write into the trash page (last table entry)."""
        ptok = pool.shape[1]
        nb = tables.shape[1] - 1
        block = positions // ptok
        # trash entry for dead lanes/positions AND for live positions
        # past the table — an overflow (host bookkeeping slip) must
        # discard the write, not overwrite the last allocated page
        block = jnp.where(live & (block < nb), block, nb)
        page = jnp.take_along_axis(tables, block, axis=1)
        off = jnp.where(live, positions % ptok, 0)
        return pool.at[page, off].set(rows.astype(pool.dtype))

    def _paged_update(self, k, v, k_pool, v_pool, k_scale, v_scale,
                      tables, positions, live):
        """Scatter this window's K/V through the table; when scale
        pools ride along (int8 pages, round 21) the rows quantize on
        WRITE — scale rows share the page index/offset/trash
        semantics of their data rows (``_paged_write`` is generic
        over trailing dims), so COW and the trash page need no new
        code."""
        if k_scale is not None:
            k, ks = self._kv_quantize(k)
            v, vs = self._kv_quantize(v)
            k_scale = self._paged_write(k_scale, ks, tables,
                                        positions, live)
            v_scale = self._paged_write(v_scale, vs, tables,
                                        positions, live)
        k_pool = self._paged_write(k_pool, k, tables, positions, live)
        v_pool = self._paged_write(v_pool, v, tables, positions, live)
        return k_pool, v_pool, k_scale, v_scale

    def xla_prefill_paged(self, x, k_pool, v_pool, table, start,
                          length, w_qkv, b_qkv, w_out, b_out,
                          k_scale=None, v_scale=None):
        """Causal forward over a prompt WINDOW against the paged
        cache: ``x`` (1, W, D) features of positions
        ``start..start+W-1`` (right-padded past ``length`` real
        tokens), ``table`` (nb+1,) the sequence's block row.  Writes
        the window's K/V through the table, attends each window
        position over the cached prefix PLUS the window itself
        (``<= q_pos``), returns ``(y, k_pool, v_pool)``.

        ``start=0`` is a fresh prefill; ``start>0`` is the tail
        prefill after a prefix-cache hit — the shared pages below
        ``start`` are read, never written (the window's writes begin
        at ``start``, past every shared full block)."""
        one, w, d = x.shape
        q, k, v = self._project_qkv(x, w_qkv, b_qkv)
        idx = jnp.arange(w)
        positions = (start + idx)[None, :]
        live = (idx < length)[None, :]
        tables = table[None, :]
        k_pool, v_pool, k_scale, v_scale = self._paged_update(
            k, v, k_pool, v_pool, k_scale, v_scale, tables, positions,
            live)
        o = self._paged_attend(q, k_pool, v_pool, tables, positions,
                               k_scale, v_scale)
        y = self._out_proj(o, w_out, b_out)
        if k_scale is not None:
            return y, k_pool, v_pool, k_scale, v_scale
        return y, k_pool, v_pool

    def xla_decode_step_paged(self, x, k_pool, v_pool, tables, pos,
                              w_qkv, b_qkv, w_out, b_out,
                              k_scale=None, v_scale=None):
        """One incremental token through the page table: ``x``
        (B, 1, D), ``tables`` (B, nb+1), ``pos`` (B,) the position of
        THIS token per lane (padded lanes carry the trash table and
        write harmlessly there)."""
        q, k, v = self._project_qkv(x, w_qkv, b_qkv)
        positions = pos[:, None]
        live = jnp.ones_like(positions, bool)
        k_pool, v_pool, k_scale, v_scale = self._paged_update(
            k, v, k_pool, v_pool, k_scale, v_scale, tables, positions,
            live)
        o = self._paged_attend(q, k_pool, v_pool, tables, positions,
                               k_scale, v_scale)
        y = self._out_proj(o, w_out, b_out)
        if k_scale is not None:
            return y, k_pool, v_pool, k_scale, v_scale
        return y, k_pool, v_pool

    def xla_window_paged(self, x, k_pool, v_pool, tables, pos,
                         lengths, w_qkv, b_qkv, w_out, b_out,
                         k_scale=None, v_scale=None):
        """Batched multi-token WINDOW through the page table — the op
        behind both speculative verification (window = last accepted
        token + K drafts, ``lengths`` = K+1 everywhere) and batched
        tail prefill (window = each lane's unshared prompt tail,
        right-padded; admission coalescing for prefix-hit traffic).

        ``x`` (B, W, D) window features starting at per-lane position
        ``pos`` (B,); positions past ``lengths`` (B,) write into the
        trash page.  Writes all live K/V, attends each window
        position causally over prefix+window in ONE batched forward.
        Stale/overflow rows beyond a lane's real positions sit past
        the position mask exactly like a reused slot's rows."""
        b, w, d = x.shape
        q, k, v = self._project_qkv(x, w_qkv, b_qkv)
        idx = jnp.arange(w)[None, :]
        positions = pos[:, None] + idx
        live = idx < lengths[:, None]
        k_pool, v_pool, k_scale, v_scale = self._paged_update(
            k, v, k_pool, v_pool, k_scale, v_scale, tables, positions,
            live)
        o = self._paged_attend(q, k_pool, v_pool, tables, positions,
                               k_scale, v_scale)
        y = self._out_proj(o, w_out, b_out)
        if k_scale is not None:
            return y, k_pool, v_pool, k_scale, v_scale
        return y, k_pool, v_pool

    # -- numpy oracle ---------------------------------------------------
    def _forward_np(self, x):
        b, t, d = x.shape
        qkv = x.reshape(b * t, d) @ self.weights.mem
        if self.include_bias:
            qkv = qkv + self.bias.mem
        q, k, v = _split_heads(qkv.reshape(b, t, 3 * d), self.n_heads)
        o, p = _local_attention_np(q, k, v, self.causal)
        y = o.reshape(b * t, d) @ self.weights_out.mem
        if self.include_bias:
            y = y + self.bias_out.mem
        return y.reshape(b, t, d), (qkv, q, k, v, o, p)

    def numpy_run(self) -> None:
        self.input.map_read()
        self.weights.map_read()
        self.weights_out.map_read()
        if self.include_bias:
            self.bias.map_read()
            self.bias_out.map_read()
        y, _ = self._forward_np(self.input.mem.astype(np.float32))
        self.output.map_invalidate()
        self.output.mem[...] = y


class GDMultiHeadAttention(GradientDescentBase):
    """Attention backward: analytic numpy oracle vs ``jax.vjp`` of the
    forward (which differentiates through the ring when
    ``seq_parallel``)."""

    MATCHES = (MultiHeadAttention,)
    REQUIRES_FORWARD_UNIT = True
    REQUIRES_INPUT = True

    def __init__(self, workflow, name=None, **kwargs):
        super().__init__(workflow, name=name, **kwargs)
        self.forward_unit: MultiHeadAttention | None = None
        self.accumulated_gradient_weights_out = Vector(
            name=f"{self.name}.acc_gw_out")
        self.accumulated_gradient_bias_out = Vector(
            name=f"{self.name}.acc_gb_out")

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        fwd = self.forward_unit
        # the shared allocator (not a bare reset) so the output
        # projection's momentum gets the same bf16-storage + ZeRO-1
        # data-sharding treatment as the base pair
        if self.gradient_moment:
            self._alloc_accumulator(self.accumulated_gradient_weights_out,
                                    fwd.weights_out)
        if self.gradient_moment_bias and fwd.include_bias:
            self._alloc_accumulator(self.accumulated_gradient_bias_out,
                                    fwd.bias_out)
        self.init_vectors(self.err_input, self.err_output, self.input,
                          self.output, self.weights, self.bias,
                          fwd.weights_out, fwd.bias_out,
                          self.accumulated_gradient_weights_out,
                          self.accumulated_gradient_bias_out)

    def _micro_accum_params(self):
        # round 20: the output projection pair accumulates too — the
        # base enumeration only covers the fused QKV weights/bias
        pairs = super()._micro_accum_params()
        fwd = self.forward_unit
        if fwd is not None:
            pairs.extend([("wo", fwd.weights_out), ("bo", fwd.bias_out)])
        return pairs

    def region_vectors(self):
        vecs = super().region_vectors()
        seen = {id(v) for v in vecs}
        fwd = self.forward_unit
        for vec in (fwd.weights_out, fwd.bias_out,
                    self.accumulated_gradient_weights_out,
                    self.accumulated_gradient_bias_out):
            if vec and id(vec) not in seen:
                vecs.append(vec)
        return vecs

    def xla_run(self) -> None:
        fwd = self.forward_unit
        has_bias = fwd.include_bias
        # consume the stashed pullback ONLY when this GD is tracing
        # into the same region program the forward just traced into
        # (the region schedules forward before backward, and the
        # forward overwrites the stash at the top of every trace, so a
        # tracing consumer can never see a stale trace's closure); an
        # EAGER backward must rebuild — a stash from some earlier
        # trace would hold escaped tracers
        vjp = fwd._traced_vjp if self.err_output._tracing else None
        fwd._traced_vjp = None   # single-use: never reuse stale state
        if vjp is None:          # forward ran outside this trace
            args = (self.input.devmem, self.weights.devmem,
                    self.bias.devmem if has_bias else None,
                    fwd.weights_out.devmem,
                    fwd.bias_out.devmem if has_bias else None)
            _, vjp = jax.vjp(
                lambda x, wq, bq, wo, bo: fwd.xla_forward(
                    x, wq, bq, wo, bo),
                *args)
        gx, gwq, gbq, gwo, gbo = vjp(
            self.err_output.devmem.astype(jnp.float32))
        if self.need_err_input:
            self.err_input.devmem = gx
        self._apply_weights_xla(gwq)
        if has_bias:
            self._apply_bias_xla(gbq)
        # second pair through the SAME parameterized base update rule
        self._apply_weights_xla(
            gwo, vec=fwd.weights_out,
            acc_vec=self.accumulated_gradient_weights_out)
        if has_bias:
            self._apply_bias_xla(
                gbo, vec=fwd.bias_out,
                acc_vec=self.accumulated_gradient_bias_out)

    def numpy_run(self) -> None:
        """Analytic attention backward (the oracle/spec)."""
        fwd = self.forward_unit
        for vec in (self.err_output, self.input):
            vec.map_read()
        self.weights.map_write()
        fwd.weights_out.map_write()
        if fwd.include_bias:
            self.bias.map_write()
            fwd.bias_out.map_write()
        x = self.input.mem.astype(np.float32)
        b, t, d = x.shape
        h = fwd.n_heads
        dh = d // h
        _, (qkv, q, k, v, o, p) = fwd._forward_np(x)
        dy = self.err_output.mem.astype(np.float32).reshape(b * t, d)
        # output projection
        grad_wo = o.reshape(b * t, d).T @ dy
        grad_bo = dy.sum(axis=0)
        do = (dy @ fwd.weights_out.mem.T).reshape(b, t, h, dh)
        # attention core: dv, softmax jacobian, dq/dk
        dv = np.einsum("bhqk,bqhd->bkhd", p, do)
        dp = np.einsum("bqhd,bkhd->bhqk", do, v)
        ds = p * (dp - (dp * p).sum(axis=-1, keepdims=True))
        ds = ds / np.sqrt(dh)
        dq = np.einsum("bhqk,bkhd->bqhd", ds, k)
        dk = np.einsum("bhqk,bqhd->bkhd", ds, q)
        dqkv = np.concatenate(
            [a.reshape(b, t, d) for a in (dq, dk, dv)],
            axis=-1).reshape(b * t, 3 * d)
        # input projection
        grad_wq = x.reshape(b * t, d).T @ dqkv
        grad_bq = dqkv.sum(axis=0)
        if self.need_err_input:
            self.err_input.map_invalidate()
            self.err_input.mem[...] = (
                dqkv @ self.weights.mem.T).reshape(b, t, d)
        self._apply_weights_np(grad_wq)
        if fwd.include_bias:
            self._apply_bias_np(grad_bq)
        self._apply_weights_np(
            grad_wo, vec=fwd.weights_out,
            acc_vec=self.accumulated_gradient_weights_out)
        if fwd.include_bias:
            self._apply_bias_np(
                grad_bo, vec=fwd.bias_out,
                acc_vec=self.accumulated_gradient_bias_out)
