"""Cutter: crop a spatial region (reference: ``znicz/cutter.py``).

``Cutter(padding=(left, top, right, bottom))`` removes that many
pixels from each border of an NHWC tensor; :class:`GDCutter` zero-pads
the error back.  On TPU both are static ``lax.slice`` / ``jnp.pad`` —
offsets are compile-time constants (SURVEY.md §2.3:
"lax.dynamic_slice"; static slices compile tighter, and the
reference's crop geometry is fixed per instantiation anyway).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from znicz_tpu.ops.nn_units import Forward, WeightlessGradientUnit


class Cutter(Forward):
    """Crop ``padding=(left, top, right, bottom)`` pixels off NHWC
    (an int means the same crop on every border, as in Conv)."""

    def __init__(self, workflow, padding=(0, 0, 0, 0), name=None,
                 **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        if isinstance(padding, (int, np.integer)):
            padding = (padding,) * 4
        self.padding = tuple(int(p) for p in padding)
        if len(self.padding) != 4:
            raise ValueError("padding must be (left, top, right, bottom)")

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        if self.input is None or not self.input:
            raise AttributeError(f"{self}: input not linked yet")
        n, h, w, c = self.input.shape
        lf, tp, rt, bt = self.padding
        oh, ow = h - tp - bt, w - lf - rt
        if oh <= 0 or ow <= 0:
            raise ValueError(f"{self}: crop {self.padding} leaves "
                             f"nothing of {h}x{w}")
        self.output.reset(np.zeros((n, oh, ow, c),
                                   dtype=self.output_store_dtype))
        self.init_vectors(self.input, self.output)

    def _crop(self, x):
        lf, tp, rt, bt = self.padding
        n, h, w, c = self.input.shape
        return x[:, tp:h - bt, lf:w - rt, :]

    def numpy_run(self) -> None:
        self.input.map_read()
        self.output.map_invalidate()
        self.output.mem[...] = self._crop(self.input.mem)

    def xla_run(self) -> None:
        self.output.devmem = self._crop(self.input.devmem)


class GDCutter(WeightlessGradientUnit):
    """Zero-pad the error back to the uncropped shape."""

    MATCHES = (Cutter,)

    def _pad_spec(self):
        lf, tp, rt, bt = self.forward_unit.padding
        return ((0, 0), (tp, bt), (lf, rt), (0, 0))

    def numpy_run(self) -> None:
        if not self.need_err_input:
            return
        self.err_output.map_read()
        self.err_input.map_invalidate()
        self.err_input.mem[...] = np.pad(self.err_output.mem,
                                         self._pad_spec())

    def xla_run(self) -> None:
        if self.need_err_input:
            self.err_input.devmem = jnp.pad(self.err_output.devmem,
                                            self._pad_spec())
