"""Restricted Boltzmann machine units (reference:
``znicz/rbm_units.py`` — the MnistRBM sample's pretraining stack:
``Binarization``, ``BatchWeights``, ``GradientRBM``, ``EvaluatorRBM``).

Training is CD-k (contrastive divergence):

.. code-block:: text

    h0 = σ(v0·W + hb)            (All2AllSigmoid — the encoder)
    s0 = bernoulli(h0)           (Binarization)
    v1 = σ(s0·Wᵀ + vb)           (reconstruction; probabilities)
    h1 = σ(v1·W + hb)
    ΔW = (v0ᵀh0 − v1ᵀh1)/n;  Δhb = mean(h0−h1);  Δvb = mean(v0−v1)

TPU-first: the whole Gibbs chain is a handful of MXU GEMMs +
elementwise σ inside one jit region; sampling uses the unit's
device-resident PRNG key chain (``take_key``) so the chain stays
compiled (reference: custom CUDA/OpenCL sampling kernels).  The numpy
oracle uses the seeded host PRNG — RNG streams differ across backends
by design; parity is statistical (SURVEY.md §2.3 PRNG note).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from znicz_tpu.accelerated_units import AcceleratedUnit
from znicz_tpu.memory import Vector
from znicz_tpu.ops.evaluator import EvaluatorMSE
from znicz_tpu.ops.nn_units import Forward
from znicz_tpu.utils import prng


def _sigmoid(xp, x):
    return 1.0 / (1.0 + xp.exp(-x))


class Binarization(Forward):
    """Bernoulli-sample a probability tensor: ``out = 1[u < p]``
    (reference: ``Binarization`` — feeds sampled hidden states into
    the CD chain)."""

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        if self.input is None or not self.input:
            raise AttributeError(f"{self}: input not linked yet")
        self.output.reset(np.zeros(self.input.shape, dtype=np.float32))
        self.inherit_model_shard(self.output)
        self.init_vectors(self.input, self.output)
        self.init_rng()

    def numpy_run(self) -> None:
        self.input.map_read()
        self.output.map_invalidate()
        u = prng.get().numpy.uniform(size=self.input.shape)
        self.output.mem[...] = (u < self.input.mem).astype(np.float32)

    def xla_run(self) -> None:
        p = self.input.devmem
        u = jax.random.uniform(self.take_key(), p.shape, dtype=p.dtype)
        self.output.devmem = (u < p).astype(p.dtype)


class BatchWeights(AcceleratedUnit):
    """Batch outer product ``vᵀh / n`` plus column means — the
    sufficient statistics of one CD phase (reference:
    ``BatchWeights``; ``GradientRBM`` composes two of these)."""

    def __init__(self, workflow, name=None, **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.v: Vector | None = None        # (n, nv) linked
        self.h: Vector | None = None        # (n, nh) linked
        self.weights_batch = Vector(name=f"{self.name}.weights_batch")
        self.v_mean = Vector(name=f"{self.name}.v_mean")
        self.h_mean = Vector(name=f"{self.name}.h_mean")

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        for vec, nm in ((self.v, "v"), (self.h, "h")):
            if vec is None or not vec:
                raise AttributeError(f"{self}: {nm} not linked yet")
        nv, nh = self.v.shape[1], self.h.shape[1]
        self.weights_batch.reset(np.zeros((nv, nh), dtype=np.float32))
        self.v_mean.reset(np.zeros(nv, dtype=np.float32))
        self.h_mean.reset(np.zeros(nh, dtype=np.float32))
        self.init_vectors(self.v, self.h, self.weights_batch,
                          self.v_mean, self.h_mean)

    @staticmethod
    def stats(xp, v, h):
        n = v.shape[0]
        return v.T @ h / n, v.mean(axis=0), h.mean(axis=0)

    def numpy_run(self) -> None:
        self.v.map_read()
        self.h.map_read()
        w, vm, hm = self.stats(np, self.v.mem, self.h.mem)
        for vec, val in ((self.weights_batch, w), (self.v_mean, vm),
                         (self.h_mean, hm)):
            vec.map_invalidate()
            vec.mem[...] = val

    def xla_run(self) -> None:
        w, vm, hm = self.stats(jnp, self.v.devmem, self.h.devmem)
        self.weights_batch.devmem = w
        self.v_mean.devmem = vm
        self.h_mean.devmem = hm


class GradientRBM(AcceleratedUnit):
    """CD-k weight update + reconstruction (reference:
    ``GradientRBM``).

    Links: ``input`` = v0 (data), ``hidden`` = h0 probabilities,
    ``hidden_sample`` = binarized h0, shared ``weights`` (nv, nh) and
    ``hbias`` with the encoder All2AllSigmoid; owns ``vbias``.
    ``forward_mode`` (linked from the loader) gates the update: eval
    minibatches only compute the reconstruction.
    """

    SNAPSHOT_ATTRS = ("learning_rate", "gradient_moment")

    def __init__(self, workflow, name=None, learning_rate: float = 0.1,
                 gradient_moment: float = 0.0, cd_k: int = 1,
                 **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.learning_rate = learning_rate
        self.gradient_moment = gradient_moment
        self.cd_k = int(cd_k)
        self.forward_mode = "train"     # usually linked from loader
        self.input: Vector | None = None
        self.hidden: Vector | None = None
        self.hidden_sample: Vector | None = None
        self.weights: Vector | None = None
        self.hbias: Vector | None = None
        self.vbias = Vector(name=f"{self.name}.vbias")
        self.reconstruction = Vector(name=f"{self.name}.reconstruction",
                                     batch_major=True)
        self._acc_w = Vector(name=f"{self.name}.acc_w")
        self._acc_vb = Vector(name=f"{self.name}.acc_vb")
        self._acc_hb = Vector(name=f"{self.name}.acc_hb")

    def region_key(self) -> tuple:
        return (self.forward_mode,)

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        for vec, nm in ((self.input, "input"), (self.hidden, "hidden"),
                        (self.hidden_sample, "hidden_sample"),
                        (self.weights, "weights"), (self.hbias, "hbias")):
            if vec is None or not vec:
                raise AttributeError(f"{self}: {nm} not linked yet")
        nv = self.input.sample_size
        if not self.vbias:
            self.vbias.reset(np.zeros(nv, dtype=np.float32))
        self.reconstruction.reset(
            np.zeros((self.input.shape[0], nv), dtype=np.float32))
        if self.gradient_moment:
            self._acc_w.reset(np.zeros(self.weights.shape,
                                       dtype=np.float32))
            self._acc_vb.reset(np.zeros(nv, dtype=np.float32))
            self._acc_hb.reset(np.zeros(self.hbias.shape,
                                        dtype=np.float32))
        self.init_vectors(self.input, self.hidden, self.hidden_sample,
                          self.weights, self.hbias, self.vbias,
                          self.reconstruction, self._acc_w,
                          self._acc_vb, self._acc_hb)
        self.init_rng()

    # -- the CD chain (xp-generic except sampling) ----------------------
    def _gibbs(self, xp, v0, h0, s0, w, hb, vb, sample):
        """One CD-k chain from sampled h; returns (v1, h1)."""
        s = s0
        for _ in range(self.cd_k):
            v1 = _sigmoid(xp, s @ w.T + vb)
            h1 = _sigmoid(xp, v1 @ w + hb)
            if self.cd_k > 1:
                s = sample(h1)
        return v1, h1

    def numpy_run(self) -> None:
        for vec in (self.input, self.hidden, self.hidden_sample,
                    self.weights, self.hbias, self.vbias):
            vec.map_read()
        n = self.input.shape[0]
        v0 = self.input.mem.reshape(n, -1).astype(np.float32)
        h0 = self.hidden.mem
        s0 = self.hidden_sample.mem
        w = self.weights.mem
        rnd = prng.get().numpy

        def sample(p):
            return (rnd.uniform(size=p.shape) < p).astype(np.float32)

        v1, h1 = self._gibbs(np, v0, h0, s0, w, self.hbias.mem,
                             self.vbias.mem, sample)
        self.reconstruction.map_invalidate()
        self.reconstruction.mem[...] = v1
        if self.forward_mode != "train":
            return
        pos_w, pos_v, pos_h = BatchWeights.stats(np, v0, h0)
        neg_w, neg_v, neg_h = BatchWeights.stats(np, v1, h1)
        self.weights.map_write()
        self.hbias.map_write()
        self.vbias.map_write()
        self._apply_np(self.weights.mem, pos_w - neg_w, self._acc_w)
        self._apply_np(self.vbias.mem, pos_v - neg_v, self._acc_vb)
        self._apply_np(self.hbias.mem, pos_h - neg_h, self._acc_hb)

    def _apply_np(self, param, grad, acc_vec) -> None:
        if self.gradient_moment:
            acc_vec.map_write()
            acc = acc_vec.mem
            acc *= self.gradient_moment
            acc += self.learning_rate * grad
            param += acc
        else:
            param += self.learning_rate * grad

    def xla_run(self) -> None:
        n = self.input.devmem.shape[0]
        v0 = self.input.devmem.reshape(n, -1)
        h0 = self.hidden.devmem
        s0 = self.hidden_sample.devmem
        w = self.weights.devmem
        hb = self.hbias.devmem
        vb = self.vbias.devmem

        def sample(p):
            u = jax.random.uniform(self.take_key(), p.shape,
                                   dtype=p.dtype)
            return (u < p).astype(p.dtype)

        v1, h1 = self._gibbs(jnp, v0, h0, s0, w, hb, vb, sample)
        self.reconstruction.devmem = v1
        if self.forward_mode != "train":
            return
        pos_w, pos_v, pos_h = BatchWeights.stats(jnp, v0, h0)
        neg_w, neg_v, neg_h = BatchWeights.stats(jnp, v1, h1)
        lr = self.learning_rate
        if self.gradient_moment:
            m = self.gradient_moment
            acc_w = m * self._acc_w.devmem + lr * (pos_w - neg_w)
            acc_vb = m * self._acc_vb.devmem + lr * (pos_v - neg_v)
            acc_hb = m * self._acc_hb.devmem + lr * (pos_h - neg_h)
            self._acc_w.devmem = acc_w
            self._acc_vb.devmem = acc_vb
            self._acc_hb.devmem = acc_hb
            self.weights.devmem = w + acc_w
            self.vbias.devmem = vb + acc_vb
            self.hbias.devmem = hb + acc_hb
        else:
            self.weights.devmem = w + lr * (pos_w - neg_w)
            self.vbias.devmem = vb + lr * (pos_v - neg_v)
            self.hbias.devmem = hb + lr * (pos_h - neg_h)


class EvaluatorRBM(EvaluatorMSE):
    """Reconstruction-error evaluator (reference: ``EvaluatorRBM``):
    MSE between ``GradientRBM.reconstruction`` and the input data.
    The err_output it emits is unused — an RBM has no backward chain —
    but the epoch-accumulated metric drives DecisionMSE unchanged."""
