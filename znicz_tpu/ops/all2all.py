"""Fully-connected forward units (reference: ``znicz/all2all.py``).

``y = act(x @ W + b)`` — the GEMM rides the MXU via
``jnp.dot``/``lax.dot_general`` (the reference hand-tiled this in
OpenCL/CUDA; on TPU XLA owns the tiling, SURVEY.md §2.3).  Activation
flavors are fused into the same jit region, so the elementwise tail
costs no extra HBM round-trip.

``All2AllSoftmax`` also produces ``max_idx`` (argmax per sample) like
the reference — used by the evaluator and image-saver units.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from znicz_tpu.memory import Vector
from znicz_tpu.ops import activations_math
from znicz_tpu.ops.nn_units import Forward


class All2All(Forward):
    """Linear fully-connected layer.

    ``output_sample_shape`` is the per-sample output shape (an int or
    tuple), mirroring the reference's constructor.
    """

    ACTIVATION = "linear"

    def __init__(self, workflow, output_sample_shape, name=None, **kwargs):
        super().__init__(workflow, name=name, **kwargs)
        if isinstance(output_sample_shape, (int, np.integer)):
            output_sample_shape = (int(output_sample_shape),)
        self.output_sample_shape = tuple(output_sample_shape)
        self.activation = activations_math.get(self.ACTIVATION)

    @property
    def neurons(self) -> int:
        return int(np.prod(self.output_sample_shape))

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        if self.input is None or not self.input:
            raise AttributeError(f"{self}: input not linked/allocated yet")
        n_in = self.input.sample_size
        n_out = self.neurons
        if not self.weights:
            self.weights.reset(self.fill_array(
                (n_in, n_out), self.weights_filling, self.weights_stddev,
                fan_in=n_in))
        if self.include_bias and not self.bias:
            self.bias.reset(self.fill_array(
                (n_out,), self.bias_filling, self.bias_stddev, fan_in=n_in))
        batch = self.input.shape[0]
        self.output.reset(np.zeros((batch,) + self.output_sample_shape,
                                   dtype=self.output_store_dtype))
        self.init_vectors(self.input, self.output, self.weights, self.bias)

    # -- math (shared shape logic; xp-generic) --------------------------
    def _forward(self, xp, x, w, b):
        batch = x.shape[0]
        y = self.mxu_dot(xp, x.reshape(batch, -1), w)
        if b is not None:
            y = y + b
        y = self.activation.fwd(xp, y)
        return y.reshape((batch,) + self.output_sample_shape)

    def numpy_run(self) -> None:
        self.input.map_read()
        self.weights.map_read()
        x = self.input.mem.astype(np.float32)
        b = None
        if self.include_bias:
            self.bias.map_read()
            b = self.bias.mem
        self.output.map_invalidate()
        self.output.mem[...] = self._forward(np, x, self.weights.mem, b)

    def xla_run(self) -> None:
        x = self.input.devmem
        w = self.weights.devmem
        b = self.bias.devmem if self.include_bias else None
        self.output.devmem = self._forward(jnp, x, w, b)


class All2AllTanh(All2All):
    """Fused scaled-tanh flavor (reference: ``All2AllTanh``)."""
    ACTIVATION = "tanh"


class All2AllRELU(All2All):
    """Fused smooth-RELU (softplus) flavor (reference: ``All2AllRELU``)."""
    ACTIVATION = "relu"


class All2AllStrictRELU(All2All):
    """Fused max(x,0) flavor (reference: ``All2AllStrictRELU``)."""
    ACTIVATION = "strict_relu"


class All2AllSigmoid(All2All):
    """Fused sigmoid flavor (reference: ``All2AllSigmoid``)."""
    ACTIVATION = "sigmoid"


class All2AllSoftmax(All2All):
    """Softmax output layer; also computes per-sample argmax
    (reference: ``All2AllSoftmax`` with its ``max_idx`` kernel)."""

    ACTIVATION = "linear"  # softmax applied over the linear output

    #: probabilities stay f32 — they feed the evaluator's CE/log and
    #: are tiny (batch × n_classes) next to the conv activations
    output_store_dtype = np.dtype(np.float32)

    def __init__(self, workflow, output_sample_shape, name=None, **kwargs):
        super().__init__(workflow, output_sample_shape, name=name, **kwargs)
        self.max_idx = Vector(name=f"{self.name}.max_idx", batch_major=True)

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        self.max_idx.reset(np.zeros(self.output.shape[0], dtype=np.int32))
        self.init_vectors(self.max_idx)

    def _softmax(self, xp, logits):
        m = logits.max(axis=1, keepdims=True)
        e = xp.exp(logits - m)
        return e / e.sum(axis=1, keepdims=True)

    def _logits(self, xp, x, w, b):
        y = self.mxu_dot(xp, x.reshape(x.shape[0], -1), w)
        return y if b is None else y + b

    def numpy_run(self) -> None:
        self.input.map_read()
        self.weights.map_read()
        b = None
        if self.include_bias:
            self.bias.map_read()
            b = self.bias.mem
        x = self.input.mem.astype(np.float32)
        logits = self._logits(np, x, self.weights.mem, b)
        self.output.map_invalidate()
        self.max_idx.map_invalidate()
        self.output.mem[...] = self._softmax(np, logits)
        self.max_idx.mem[...] = np.argmax(logits, axis=1).astype(np.int32)

    def xla_run(self) -> None:
        b = self.bias.devmem if self.include_bias else None
        logits = self._logits(jnp, self.input.devmem, self.weights.devmem, b)
        self.output.devmem = self._softmax(jnp, logits)
        self.max_idx.devmem = jnp.argmax(logits, axis=1).astype(jnp.int32)
