"""Fully-connected forward units (reference: ``znicz/all2all.py``).

``y = act(x @ W + b)`` — the GEMM rides the MXU via
``jnp.dot``/``lax.dot_general`` (the reference hand-tiled this in
OpenCL/CUDA; on TPU XLA owns the tiling, SURVEY.md §2.3).  Activation
flavors are fused into the same jit region, so the elementwise tail
costs no extra HBM round-trip.

``All2AllSoftmax`` also produces ``max_idx`` (argmax per sample) like
the reference — used by the evaluator and image-saver units.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from znicz_tpu.memory import Vector
from znicz_tpu.ops import activations_math
from znicz_tpu.ops.nn_units import Forward


class All2All(Forward):
    """Linear fully-connected layer.

    ``output_sample_shape`` is the per-sample output shape (an int or
    tuple), mirroring the reference's constructor.

    ``model_parallel`` (Megatron-style tensor parallelism over the
    mesh's MODEL axis — beyond the reference, which only scaled via
    data parallelism):

    - ``"column"``: weights shard (n_in, n_out/m); output features
      shard over model.  Bias shards with the features.
    - ``"row"``: weights shard (n_in/m, n_out); expects a feature-
      sharded input (a preceding column layer) and produces a
      replicated-over-model output — GSPMD inserts the psum.
    - ``None`` (default): replicated weights, pure data parallelism.

    Annotation-only: the GEMMs are unchanged, ``sharding_for`` places
    the buffers, and XLA's partitioner derives the collectives
    (all-gather/reduce-scatter over ICI).  On a mesh with model=1 or
    no mesh at all the annotations are no-ops.
    """

    ACTIVATION = "linear"

    def __init__(self, workflow, output_sample_shape, name=None,
                 model_parallel: str | None = None, **kwargs):
        super().__init__(workflow, name=name, **kwargs)
        if isinstance(output_sample_shape, (int, np.integer)):
            output_sample_shape = (int(output_sample_shape),)
        self.output_sample_shape = tuple(output_sample_shape)
        self.activation = activations_math.get(self.ACTIVATION)
        if model_parallel not in (None, "column", "row"):
            raise ValueError(f"{self}: model_parallel must be None, "
                             f"'column' or 'row', got {model_parallel!r}")
        if model_parallel is not None \
                and len(self.output_sample_shape) != 1:
            # the column split partitions the FLATTENED n_out; a
            # multi-dim sample shape would shard the wrong physical dim
            raise ValueError(
                f"{self}: model_parallel requires a 1-D "
                f"output_sample_shape, got {self.output_sample_shape}")
        self.model_parallel = model_parallel

    @property
    def neurons(self) -> int:
        return int(np.prod(self.output_sample_shape))

    def _apply_model_parallel(self, n_in: int, n_out: int) -> None:
        """Set model-axis sharding dims on weights/bias/output before
        the device places them.  No-op when ``model_parallel`` is
        unset or the device has no mesh; a mesh WITHOUT a model axis
        raises (a silent no-op there would hide a sharding request)."""
        if self.model_parallel is None:
            return
        n_model = 1
        mesh = getattr(self.device, "mesh", None)
        if mesh is not None:
            from znicz_tpu.parallel.axis import MODEL_AXIS
            if MODEL_AXIS not in mesh.shape:
                # a custom mesh without the model axis (e.g. a seq-only
                # mesh) would otherwise die later in sharding_for with
                # an opaque PartitionSpec error naming a missing axis
                raise ValueError(
                    f"{self}: model_parallel='{self.model_parallel}' "
                    f"needs a mesh with a '{MODEL_AXIS}' axis; this "
                    f"mesh has {dict(mesh.shape)} (framework "
                    f"make_mesh always provides one; custom meshes "
                    f"must too, or drop model_parallel)")
            n_model = mesh.shape[MODEL_AXIS]
        from jax.sharding import PartitionSpec as P
        from znicz_tpu.parallel.axis import DATA_AXIS, MODEL_AXIS
        if self.model_parallel == "column":
            if n_out % n_model:
                raise ValueError(
                    f"{self}: column-parallel n_out {n_out} not "
                    f"divisible by model axis size {n_model}")
            self.partition_leaf("weights", P(None, MODEL_AXIS))
            if self.include_bias:
                self.partition_leaf("bias", P(MODEL_AXIS))
            # output features ride the model axis: (batch, n_out/m)
            # (1-D sample shape enforced above)
            self.partition_leaf("output", P(DATA_AXIS, MODEL_AXIS))
        else:  # row
            if n_in % n_model:
                raise ValueError(
                    f"{self}: row-parallel n_in {n_in} not divisible "
                    f"by model axis size {n_model}")
            self.partition_leaf("weights", P(MODEL_AXIS))
            # bias replicated: added after the psum; output replicated

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        if self.input is None or not self.input:
            raise AttributeError(f"{self}: input not linked/allocated yet")
        n_in = self.input.sample_size
        n_out = self.neurons
        if not self.weights:
            self.weights.reset(self.fill_array(
                (n_in, n_out), self.weights_filling, self.weights_stddev,
                fan_in=n_in))
        if self.include_bias and not self.bias:
            self.bias.reset(self.fill_array(
                (n_out,), self.bias_filling, self.bias_stddev, fan_in=n_in))
        batch = self.input.shape[0]
        self.output.reset(np.zeros((batch,) + self.output_sample_shape,
                                   dtype=self.output_store_dtype))
        self._apply_model_parallel(n_in, n_out)
        self.init_vectors(self.input, self.output, self.weights, self.bias)

    # -- math (shared shape logic; xp-generic) --------------------------
    def _forward(self, xp, x, w, b):
        batch = x.shape[0]
        y = self.mxu_dot(xp, x.reshape(batch, -1), w)
        if b is not None:
            y = y + b
        y = self.activation.fwd(xp, y)
        return y.reshape((batch,) + self.output_sample_shape)

    def numpy_run(self) -> None:
        self.input.map_read()
        self.weights.map_read()
        x = self.input.mem.astype(np.float32)
        b = None
        if self.include_bias:
            self.bias.map_read()
            b = self.bias.mem
        self.output.map_invalidate()
        self.output.mem[...] = self._forward(np, x, self.weights.mem, b)

    def xla_run(self) -> None:
        x = self.input.devmem
        w = self.weights.devmem
        b = self.bias.devmem if self.include_bias else None
        self.output.devmem = self._forward(jnp, x, w, b)


class All2AllTanh(All2All):
    """Fused scaled-tanh flavor (reference: ``All2AllTanh``)."""
    ACTIVATION = "tanh"


class All2AllRELU(All2All):
    """Fused smooth-RELU (softplus) flavor (reference: ``All2AllRELU``)."""
    ACTIVATION = "relu"


class All2AllStrictRELU(All2All):
    """Fused max(x,0) flavor (reference: ``All2AllStrictRELU``)."""
    ACTIVATION = "strict_relu"


class All2AllSigmoid(All2All):
    """Fused sigmoid flavor (reference: ``All2AllSigmoid``)."""
    ACTIVATION = "sigmoid"


class All2AllSoftmax(All2All):
    """Softmax output layer; also computes per-sample argmax
    (reference: ``All2AllSoftmax`` with its ``max_idx`` kernel)."""

    ACTIVATION = "linear"  # softmax applied over the linear output

    #: probabilities stay f32 — they feed the evaluator's CE/log and
    #: are tiny (batch × n_classes) next to the conv activations
    output_store_dtype = np.dtype(np.float32)

    def __init__(self, workflow, output_sample_shape, name=None, **kwargs):
        super().__init__(workflow, output_sample_shape, name=name, **kwargs)
        self.max_idx = Vector(name=f"{self.name}.max_idx", batch_major=True)

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        self.max_idx.reset(np.zeros(self.output.shape[0], dtype=np.int32))
        self.init_vectors(self.max_idx)

    def _softmax(self, xp, logits):
        m = logits.max(axis=1, keepdims=True)
        e = xp.exp(logits - m)
        return e / e.sum(axis=1, keepdims=True)

    def _logits(self, xp, x, w, b):
        y = self.mxu_dot(xp, x.reshape(x.shape[0], -1), w)
        return y if b is None else y + b

    def numpy_run(self) -> None:
        self.input.map_read()
        self.weights.map_read()
        b = None
        if self.include_bias:
            self.bias.map_read()
            b = self.bias.mem
        x = self.input.mem.astype(np.float32)
        logits = self._logits(np, x, self.weights.mem, b)
        self.output.map_invalidate()
        self.max_idx.map_invalidate()
        self.output.mem[...] = self._softmax(np, logits)
        self.max_idx.mem[...] = np.argmax(logits, axis=1).astype(np.int32)

    def xla_run(self) -> None:
        b = self.bias.devmem if self.include_bias else None
        logits = self._logits(jnp, self.input.devmem, self.weights.devmem, b)
        self.output.devmem = self._softmax(jnp, logits)
        self.max_idx.devmem = jnp.argmax(logits, axis=1).astype(jnp.int32)
