"""Convolution backward units (reference: ``znicz/gd_conv.py``).

The reference hand-wrote col2im scatter + GEMM kernels.  TPU-first,
the XLA path builds the two gradient convolutions with
``jax.linear_transpose`` of the forward's bare conv (``conv_raw``) —
exactly XLA's conv transpose rules (SURVEY.md §2.3: "lax.conv
transpose rules / autodiff") WITHOUT re-evaluating the forward the way
``jax.vjp`` of the full forward would; the activation derivative comes
from the forward unit's saved output, like the numpy oracle's.  The
oracle is the explicit im2col/col2im math, independently implemented,
so the transpose path is *tested against* the reference-style
computation.

The weight/bias gradients feed the shared base update
(``GradientDescentBase._apply_param_xla``) — on data-parallel meshes
that means the ZeRO-1 reduce-scatter → sharded-momentum → all-gather
form; conv kernels pick their data-shard dim like any other parameter
(largest non-model dim, usually ``n_kernels``).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from znicz_tpu.ops.conv import (
    Conv,
    ConvRELU,
    ConvSigmoid,
    ConvStrictRELU,
    ConvTanh,
    col2im,
    im2col,
)
from znicz_tpu.ops.nn_units import GradientDescentBase


class GradientDescentConv(GradientDescentBase):
    MATCHES = (Conv,)

    def __init__(self, workflow, name=None, **kwargs):
        super().__init__(workflow, name=name, **kwargs)
        self.forward_unit: Conv | None = None  # set by link_gds

    def initialize(self, device=None, **kwargs) -> None:
        if self.input is None or not self.input:
            raise AttributeError(f"{self}: input not linked yet")
        super().initialize(device=device, **kwargs)
        self.init_vectors(self.err_input, self.err_output, self.input,
                          self.output, self.weights, self.bias)
        # opt-in A/B lever (root.common.engine.conv_wgrad_im2col):
        # express the weight grad as patchesᵀ@delta — one tall GEMM
        # with a huge contraction dim — instead of the transposed
        # gradient conv, for geometry-starved first layers (c_in ≤ 4:
        # the conv-form wgrad contracts over only 3 input channels and
        # measured 51 TF/s in the round-3 profile, PERF.md)
        from znicz_tpu.utils.config import root
        self._wgrad_im2col = (
            bool(root.common.engine.get("conv_wgrad_im2col", False))
            and self.input.shape[-1] <= 4)

    # -- numpy oracle: explicit col2im/GEMM -----------------------------
    def numpy_run(self) -> None:
        fwd = self.forward_unit
        for vec in (self.err_output, self.input, self.output):
            vec.map_read()
        self.weights.map_write()
        x = self.input.mem.astype(np.float32)
        w = self.weights.mem
        n = x.shape[0]
        y = self.output.mem
        delta = self.err_output.mem * fwd.activation.derivative(
            np, y, None)  # conv activations are output-expressed
        oh, ow, k = delta.shape[1:]
        delta2d = delta.reshape(-1, k)
        cols = im2col(x, fwd.ky, fwd.kx, *fwd.sliding, fwd.padding)
        cols2d = cols.reshape(-1, cols.shape[-1])
        grad_w = (cols2d.T @ delta2d).reshape(w.shape)
        if self.need_err_input:
            err_cols = (delta2d @ w.reshape(-1, k).T).reshape(cols.shape)
            self.err_input.map_invalidate()
            self.err_input.mem[...] = col2im(
                err_cols, x.shape, fwd.ky, fwd.kx, *fwd.sliding,
                fwd.padding)
        self._apply_weights_np(grad_w)
        if self.bias is not None and self.bias:
            self.bias.map_write()
            self._apply_bias_np(delta2d.sum(axis=0))

    # -- XLA path: explicit transposed convs ----------------------------
    def xla_run(self) -> None:
        """Gradients via ``jax.linear_transpose`` of the bare conv —
        exactly XLA's conv transpose rules, but WITHOUT re-evaluating
        the forward the way ``jax.vjp`` of the full forward would
        (XLA's CSE does not reliably merge the recomputed convs; the
        recompute cost ~35% extra conv FLOPs per step, measured on the
        AlexNet region HLO).  The activation derivative comes from the
        forward unit's saved OUTPUT, mirroring the numpy oracle."""
        fwd = self.forward_unit
        x = self.input.devmem
        w = self.weights.devmem
        y = self.output.devmem
        err = self.err_output.devmem
        delta = err * fwd.activation.derivative(jnp, y, None)
        cotangent = delta if fwd.mxu_dtype is None \
            else delta.astype(fwd.mxu_dtype)
        if self.need_err_input:
            t_x = jax.linear_transpose(
                lambda xx: fwd.conv_raw(xx, w),
                jax.ShapeDtypeStruct(x.shape, x.dtype))
            (grad_x,) = t_x(cotangent)
            self.err_input.devmem = grad_x.astype(jnp.float32)
        if self._wgrad_im2col:
            grad_w = self._wgrad_via_patches(fwd, x, cotangent, w.shape)
        else:
            t_w = jax.linear_transpose(
                lambda ww: fwd.conv_raw(x, ww),
                jax.ShapeDtypeStruct(w.shape, w.dtype))
            (grad_w,) = t_w(cotangent)
        self._apply_weights_xla(grad_w.astype(jnp.float32))
        if self.bias is not None and self.bias:
            self._apply_bias_xla(
                delta.astype(jnp.float32).sum(axis=(0, 1, 2)))

    @staticmethod
    def _wgrad_via_patches(fwd, x, cotangent, w_shape):
        """Weight grad as one MXU GEMM: extract the forward's im2col
        patches (B·OH·OW, C·ky·kx) and contract with the cotangent
        (B·OH·OW, K) — mathematically identical to the transposed
        gradient conv (same sums, reassociated), tested against it in
        ``tests/test_gd_conv.py``."""
        pt, pb, pl, pr = fwd.padding
        dt = fwd.mxu_dtype
        if dt is not None:
            x = x.astype(dt)
        patches = jax.lax.conv_general_dilated_patches(
            x, filter_shape=(fwd.ky, fwd.kx),
            window_strides=fwd.sliding,
            padding=((pt, pb), (pl, pr)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        c = x.shape[-1]
        ky, kx, _, k = w_shape
        p2d = patches.reshape(-1, patches.shape[-1])
        d2d = cotangent.reshape(-1, k)
        grad = jnp.matmul(p2d.T, d2d,
                          preferred_element_type=jnp.float32)
        # patches features are (C, ky, kx)-ordered → HWIO weights
        return grad.reshape(c, ky, kx, k).transpose(1, 2, 0, 3)


class GDTanhConv(GradientDescentConv):
    MATCHES = (ConvTanh,)


class GDRELUConv(GradientDescentConv):
    MATCHES = (ConvRELU,)


class GDStrictRELUConv(GradientDescentConv):
    MATCHES = (ConvStrictRELU,)


class GDSigmoidConv(GradientDescentConv):
    MATCHES = (ConvSigmoid,)
