"""Convolution forward units (reference: ``znicz/conv.py``).

The reference lowered conv as im2col ("unpack") + GEMM with custom
OpenCL/CUDA kernels.  TPU-first, the XLA path is a single
``lax.conv_general_dilated`` (native HLO conv, tiled onto the MXU by
XLA — SURVEY.md §2.3: "do NOT replicate im2col"), with bias +
activation fused by the jit region.  The numpy oracle *does* use
im2col — an independent implementation that doubles as the spec.

Layouts are TPU-native: NHWC data, HWIO weights.

Constructor geometry follows the reference: ``n_kernels``, ``kx``/``ky``
(kernel width/height), ``sliding`` (stride ``(sy, sx)``), ``padding``
(int, ``(v, h)``, or ``(top, bottom, left, right)``).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from znicz_tpu.memory import Vector  # noqa: F401  (typing/docs)
from znicz_tpu.ops import activations_math
from znicz_tpu.ops.nn_units import Forward

DIMNUMS = ("NHWC", "HWIO", "NHWC")


def normalize_padding(padding) -> tuple[int, int, int, int]:
    """→ (top, bottom, left, right)."""
    if isinstance(padding, (int, np.integer)):
        return (int(padding),) * 4
    padding = tuple(int(p) for p in padding)
    if len(padding) == 2:
        v, h = padding
        return (v, v, h, h)
    if len(padding) == 4:
        return padding
    raise ValueError(f"bad padding spec {padding!r}")


def im2col(x: np.ndarray, ky: int, kx: int, sy: int, sx: int,
           pad: tuple[int, int, int, int]) -> np.ndarray:
    """NHWC patches → (N, oh, ow, ky*kx*C).  The numpy oracle's
    'unpack' (reference kernel family: conv forward unpack)."""
    pt, pb, pl, pr = pad
    xp = np.pad(x, ((0, 0), (pt, pb), (pl, pr), (0, 0)))
    n, h, w, c = xp.shape
    oh = (h - ky) // sy + 1
    ow = (w - kx) // sx + 1
    cols = np.zeros((n, oh, ow, ky, kx, c), dtype=x.dtype)
    for i in range(ky):
        for j in range(kx):
            cols[:, :, :, i, j, :] = \
                xp[:, i:i + oh * sy:sy, j:j + ow * sx:sx, :]
    return cols.reshape(n, oh, ow, ky * kx * c)


def col2im(cols: np.ndarray, x_shape, ky: int, kx: int, sy: int, sx: int,
           pad: tuple[int, int, int, int]) -> np.ndarray:
    """Scatter-add patches back (the oracle's col2im, reference kernel
    family: conv gradient)."""
    pt, pb, pl, pr = pad
    n, h, w, c = x_shape
    hp, wp = h + pt + pb, w + pl + pr
    out = np.zeros((n, hp, wp, c), dtype=cols.dtype)
    oh = (hp - ky) // sy + 1
    ow = (wp - kx) // sx + 1
    cols6 = cols.reshape(n, oh, ow, ky, kx, c)
    for i in range(ky):
        for j in range(kx):
            out[:, i:i + oh * sy:sy, j:j + ow * sx:sx, :] += \
                cols6[:, :, :, i, j, :]
    return out[:, pt:pt + h, pl:pl + w, :]


class Conv(Forward):
    """2-D convolution (linear flavor)."""

    ACTIVATION = "linear"

    def __init__(self, workflow, n_kernels: int, kx: int, ky: int,
                 sliding=(1, 1), padding=0, name=None, **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.n_kernels = int(n_kernels)
        self.kx, self.ky = int(kx), int(ky)
        self.sliding = (int(sliding[0]), int(sliding[1]))  # (sy, sx)
        self.padding = normalize_padding(padding)
        self.activation = activations_math.get(self.ACTIVATION)

    def output_spatial(self, h: int, w: int) -> tuple[int, int]:
        pt, pb, pl, pr = self.padding
        sy, sx = self.sliding
        return ((h + pt + pb - self.ky) // sy + 1,
                (w + pl + pr - self.kx) // sx + 1)

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        if self.input is None or not self.input:
            raise AttributeError(f"{self}: input not linked/allocated yet")
        if len(self.input.shape) != 4:
            raise ValueError(f"{self}: expected NHWC input, got shape "
                             f"{self.input.shape}")
        n, h, w, c = self.input.shape
        fan_in = self.ky * self.kx * c
        if not self.weights:
            self.weights.reset(self.fill_array(
                (self.ky, self.kx, c, self.n_kernels),
                self.weights_filling, self.weights_stddev, fan_in=fan_in))
        if self.include_bias and not self.bias:
            self.bias.reset(self.fill_array(
                (self.n_kernels,), self.bias_filling, self.bias_stddev,
                fan_in=fan_in))
        oh, ow = self.output_spatial(h, w)
        self.output.reset(np.zeros((n, oh, ow, self.n_kernels),
                                   dtype=self.output_store_dtype))
        self.init_vectors(self.input, self.output, self.weights, self.bias)

    # -- pure forward (jnp; the backward unit transposes conv_raw) ------
    def conv_raw(self, x, w):
        """The bare conv at MXU precision: bf16 in → bf16 out in bf16
        mode (single-dtype, so ``jax.linear_transpose``'d gradient
        convs stay single-dtype — the casts' own transposes move the
        cotangent between f32 and bf16)."""
        pt, pb, pl, pr = self.padding
        dt = self.mxu_dtype
        if dt is not None:
            x, w = x.astype(dt), w.astype(dt)
        return jax.lax.conv_general_dilated(
            x, w, window_strides=self.sliding,
            padding=((pt, pb), (pl, pr)),
            dimension_numbers=DIMNUMS)

    def xla_forward(self, x, w, b):
        y = self.conv_raw(x, w)
        if y.dtype != jnp.float32:
            y = y.astype(jnp.float32)
        if b is not None:
            y = y + b
        return self.activation.fwd(jnp, y)

    def numpy_run(self) -> None:
        self.input.map_read()
        self.weights.map_read()
        x = self.input.mem.astype(np.float32)
        w = self.weights.mem
        cols = im2col(x, self.ky, self.kx, *self.sliding, self.padding)
        y = cols @ w.reshape(-1, self.n_kernels)
        if self.include_bias:
            self.bias.map_read()
            y = y + self.bias.mem
        self.output.map_invalidate()
        self.output.mem[...] = self.activation.fwd(np, y)

    def xla_run(self) -> None:
        b = self.bias.devmem if self.include_bias else None
        self.output.devmem = self.xla_forward(
            self.input.devmem, self.weights.devmem, b)


class ConvTanh(Conv):
    """Fused scaled-tanh conv (reference: ``ConvTanh``)."""
    ACTIVATION = "tanh"


class ConvRELU(Conv):
    """Fused smooth-RELU conv (reference: ``ConvRELU``)."""
    ACTIVATION = "relu"


class ConvStrictRELU(Conv):
    """Fused max(x,0) conv (reference: ``ConvStrictRELU``)."""
    ACTIVATION = "strict_relu"


class ConvSigmoid(Conv):
    ACTIVATION = "sigmoid"
