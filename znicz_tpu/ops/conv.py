"""Convolution forward units (reference: ``znicz/conv.py``).

The reference lowered conv as im2col ("unpack") + GEMM with custom
OpenCL/CUDA kernels.  TPU-first, the XLA path is a single
``lax.conv_general_dilated`` (native HLO conv, tiled onto the MXU by
XLA — SURVEY.md §2.3: "do NOT replicate im2col"), with bias +
activation fused by the jit region.  The numpy oracle *does* use
im2col — an independent implementation that doubles as the spec.

Layouts are TPU-native: NHWC data, HWIO weights.

Constructor geometry follows the reference: ``n_kernels``, ``kx``/``ky``
(kernel width/height), ``sliding`` (stride ``(sy, sx)``), ``padding``
(int, ``(v, h)``, or ``(top, bottom, left, right)``).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from znicz_tpu.memory import Vector  # noqa: F401  (typing/docs)
from znicz_tpu.ops import activations_math
from znicz_tpu.ops.nn_units import Forward

DIMNUMS = ("NHWC", "HWIO", "NHWC")


def normalize_padding(padding) -> tuple[int, int, int, int]:
    """→ (top, bottom, left, right)."""
    if isinstance(padding, (int, np.integer)):
        return (int(padding),) * 4
    padding = tuple(int(p) for p in padding)
    if len(padding) == 2:
        v, h = padding
        return (v, v, h, h)
    if len(padding) == 4:
        return padding
    raise ValueError(f"bad padding spec {padding!r}")


def im2col(x: np.ndarray, ky: int, kx: int, sy: int, sx: int,
           pad: tuple[int, int, int, int]) -> np.ndarray:
    """NHWC patches → (N, oh, ow, ky*kx*C).  The numpy oracle's
    'unpack' (reference kernel family: conv forward unpack)."""
    pt, pb, pl, pr = pad
    xp = np.pad(x, ((0, 0), (pt, pb), (pl, pr), (0, 0)))
    n, h, w, c = xp.shape
    oh = (h - ky) // sy + 1
    ow = (w - kx) // sx + 1
    cols = np.zeros((n, oh, ow, ky, kx, c), dtype=x.dtype)
    for i in range(ky):
        for j in range(kx):
            cols[:, :, :, i, j, :] = \
                xp[:, i:i + oh * sy:sy, j:j + ow * sx:sx, :]
    return cols.reshape(n, oh, ow, ky * kx * c)


def col2im(cols: np.ndarray, x_shape, ky: int, kx: int, sy: int, sx: int,
           pad: tuple[int, int, int, int]) -> np.ndarray:
    """Scatter-add patches back (the oracle's col2im, reference kernel
    family: conv gradient)."""
    pt, pb, pl, pr = pad
    n, h, w, c = x_shape
    hp, wp = h + pt + pb, w + pl + pr
    out = np.zeros((n, hp, wp, c), dtype=cols.dtype)
    oh = (hp - ky) // sy + 1
    ow = (wp - kx) // sx + 1
    cols6 = cols.reshape(n, oh, ow, ky, kx, c)
    for i in range(ky):
        for j in range(kx):
            out[:, i:i + oh * sy:sy, j:j + ow * sx:sx, :] += \
                cols6[:, :, :, i, j, :]
    return out[:, pt:pt + h, pl:pl + w, :]


class Conv(Forward):
    """2-D convolution (linear flavor)."""

    ACTIVATION = "linear"

    def __init__(self, workflow, n_kernels: int, kx: int, ky: int,
                 sliding=(1, 1), padding=0, name=None, **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.n_kernels = int(n_kernels)
        self.kx, self.ky = int(kx), int(ky)
        self.sliding = (int(sliding[0]), int(sliding[1]))  # (sy, sx)
        self.padding = normalize_padding(padding)
        self.activation = activations_math.get(self.ACTIVATION)

    def output_spatial(self, h: int, w: int) -> tuple[int, int]:
        pt, pb, pl, pr = self.padding
        sy, sx = self.sliding
        return ((h + pt + pb - self.ky) // sy + 1,
                (w + pl + pr - self.kx) // sx + 1)

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        if self.input is None or not self.input:
            raise AttributeError(f"{self}: input not linked/allocated yet")
        if len(self.input.shape) != 4:
            raise ValueError(f"{self}: expected NHWC input, got shape "
                             f"{self.input.shape}")
        n, h, w, c = self.input.shape
        fan_in = self.ky * self.kx * c
        if not self.weights:
            self.weights.reset(self.fill_array(
                (self.ky, self.kx, c, self.n_kernels),
                self.weights_filling, self.weights_stddev, fan_in=fan_in))
        if self.include_bias and not self.bias:
            self.bias.reset(self.fill_array(
                (self.n_kernels,), self.bias_filling, self.bias_stddev,
                fan_in=fan_in))
        oh, ow = self.output_spatial(h, w)
        self.output.reset(np.zeros((n, oh, ow, self.n_kernels),
                                   dtype=self.output_store_dtype))
        self._s2d = self._space_to_depth_applicable(h, w, c)
        self.init_vectors(self.input, self.output, self.weights, self.bias)

    def _space_to_depth_applicable(self, h: int, w: int, c: int) -> bool:
        """Large-stride few-channel convs (AlexNet conv1: 11×11 s4 on
        RGB) starve the MXU — the contracting dim is only ky·kx·c and
        the stride makes XLA's windowing inefficient (profiled at
        ~55 TF/s vs ~170 for the 3×3 convs, profiles/r03_b384).  When
        the geometry allows an EXACT rewrite, conv_raw re-indexes the
        input into stride-sized blocks (space-to-depth) and runs a
        stride-1 conv with s²·c input channels instead.

        **Opt-in** (``root.common.engine.space_to_depth = True``): the
        chip A/B measured it NEUTRAL on AlexNet conv1 (9428 vs the
        9396–9568 img/s baseline band) — XLA's TPU backend evidently
        performs an equivalent transform internally for strided convs,
        so the manual rewrite stays available for geometries where it
        might matter but is off by default."""
        from znicz_tpu.utils.config import root
        if not bool(root.common.engine.get("space_to_depth", False)):
            return False
        sy, sx = self.sliding
        if sy != sx or sy < 2 or c > 8:
            return False
        b = sy
        pt, pb, pl, pr = self.padding
        hp, wp = h + pt + pb, w + pl + pr
        # the block conv yields ceil(hp/b) − ceil(k/b) + 1 outputs;
        # only exact when that matches the true floor-form count
        for size, k in ((hp, self.ky), (wp, self.kx)):
            if -(-size // b) - (-(-k // b)) + 1 != (size - k) // b + 1:
                return False
        return True

    # -- pure forward (jnp; the backward unit transposes conv_raw) ------
    def conv_raw(self, x, w):
        """The bare conv at MXU precision: bf16 in → bf16 out in bf16
        mode (single-dtype, so ``jax.linear_transpose``'d gradient
        convs stay single-dtype — the casts' own transposes move the
        cotangent between f32 and bf16).

        With ``_s2d`` (see ``_space_to_depth_applicable``) the conv is
        EXACTLY rewritten as stride-1 over stride-sized pixel blocks;
        everything here is linear, so the backward's
        ``jax.linear_transpose`` of this function automatically yields
        the transformed gradient convolutions too."""
        pt, pb, pl, pr = self.padding
        dt = self.mxu_dtype
        if dt is not None:
            x, w = x.astype(dt), w.astype(dt)
        if getattr(self, "_s2d", False):
            return self._conv_s2d(x, w)
        return jax.lax.conv_general_dilated(
            x, w, window_strides=self.sliding,
            padding=((pt, pb), (pl, pr)),
            dimension_numbers=DIMNUMS)

    def _conv_s2d(self, x, w):
        """Stride-b conv as a stride-1 conv over b×b pixel blocks:
        block (i,j) holds the b² pixels as extra channels, the kernel
        is zero-padded to a multiple of b and re-indexed the same way.
        Output position i then reads block window i..i+ceil(k/b)−1 —
        identical taps, contracted over b²·c channels on the MXU."""
        b = self.sliding[0]
        pt, pb_, pl, pr = self.padding
        n, h, wd, c = x.shape
        kyb, kxb = -(-self.ky // b), -(-self.kx // b)
        # kernel: pad to (kyb·b, kxb·b), split rows/cols into
        # (block, offset), move offsets into the channel dim
        w2 = jnp.pad(w, ((0, kyb * b - self.ky),
                         (0, kxb * b - self.kx), (0, 0), (0, 0)))
        w2 = w2.reshape(kyb, b, kxb, b, c, self.n_kernels)
        w2 = w2.transpose(0, 2, 1, 3, 4, 5).reshape(
            kyb, kxb, b * b * c, self.n_kernels)
        # input: conv padding + trailing pad to whole blocks, then the
        # same (block, offset) split
        hp, wp = h + pt + pb_, wd + pl + pr
        hb, wb = -(-hp // b), -(-wp // b)
        x2 = jnp.pad(x, ((0, 0), (pt, hb * b - hp + pb_),
                         (pl, wb * b - wp + pr), (0, 0)))
        x2 = x2.reshape(n, hb, b, wb, b, c)
        x2 = x2.transpose(0, 1, 3, 2, 4, 5).reshape(n, hb, wb, b * b * c)
        return jax.lax.conv_general_dilated(
            x2, w2, window_strides=(1, 1), padding="VALID",
            dimension_numbers=DIMNUMS)

    def xla_forward(self, x, w, b):
        y = self.conv_raw(x, w)
        if y.dtype != jnp.float32:
            y = y.astype(jnp.float32)
        if b is not None:
            y = y + b
        return self.activation.fwd(jnp, y)

    def numpy_run(self) -> None:
        self.input.map_read()
        self.weights.map_read()
        x = self.input.mem.astype(np.float32)
        w = self.weights.mem
        cols = im2col(x, self.ky, self.kx, *self.sliding, self.padding)
        y = cols @ w.reshape(-1, self.n_kernels)
        if self.include_bias:
            self.bias.map_read()
            y = y + self.bias.mem
        self.output.map_invalidate()
        self.output.mem[...] = self.activation.fwd(np, y)

    def xla_run(self) -> None:
        b = self.bias.devmem if self.include_bias else None
        self.output.devmem = self.xla_forward(
            self.input.devmem, self.weights.devmem, b)


class ConvTanh(Conv):
    """Fused scaled-tanh conv (reference: ``ConvTanh``)."""
    ACTIVATION = "tanh"


class ConvRELU(Conv):
    """Fused smooth-RELU conv (reference: ``ConvRELU``)."""
    ACTIVATION = "relu"


class ConvStrictRELU(Conv):
    """Fused max(x,0) conv (reference: ``ConvStrictRELU``)."""
    ACTIVATION = "strict_relu"


class ConvSigmoid(Conv):
    ACTIVATION = "sigmoid"
