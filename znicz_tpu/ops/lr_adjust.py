"""Learning-rate schedules applied to gradient-descent units.

Rebuilds the reference's ``znicz/lr_adjust.py``: a
:class:`LearningRateAdjust` unit holding per-GD-unit policies, fired
once per training minibatch, rewriting each unit's learning rate as a
function of the global training-iteration counter.  The policy set is
the Caffe-era family the reference targeted (step/exp/inv per
SURVEY.md §2.2, plus the arbitrary-step list form).

TPU-first delta: the adjusted rate is not a Python float captured at
trace time — that would force a jit-region recompile every time it
changed.  Each adjusted GD unit instead carries a tiny device-resident
``lr_state`` Vector ``[lr, lr_bias]`` that is a region leaf; the
adjuster rewrites it host-side between steps and the compiled program
reads it as data.
"""

from __future__ import annotations

import numpy as np

from znicz_tpu.loader.base import TRAIN
from znicz_tpu.ops.nn_units import GradientDescentBase
from znicz_tpu.units import Unit


# ----------------------------------------------------------------------
# policies: callables (base_lr, iteration) -> lr
# ----------------------------------------------------------------------
class LRPolicyBase:
    """A learning-rate schedule ``lr = f(base_lr, iteration)``."""

    def __call__(self, base_lr: float, itr: int) -> float:
        raise NotImplementedError

    def __repr__(self) -> str:
        args = ", ".join(f"{k}={v!r}" for k, v in sorted(
            self.__dict__.items()))
        return f"{type(self).__name__}({args})"


class FixedPolicy(LRPolicyBase):
    """Constant rate (optionally overriding the unit's base)."""

    def __init__(self, lr: float | None = None) -> None:
        self.lr = lr

    def __call__(self, base_lr: float, itr: int) -> float:
        return base_lr if self.lr is None else self.lr


class StepExpPolicy(LRPolicyBase):
    """``lr = base · gamma^⌊itr / step⌋`` (Caffe "step")."""

    def __init__(self, gamma: float, step: int) -> None:
        self.gamma = gamma
        self.step = int(step)

    def __call__(self, base_lr: float, itr: int) -> float:
        return base_lr * self.gamma ** (itr // self.step)


class ExpPolicy(LRPolicyBase):
    """``lr = base · gamma^itr``."""

    def __init__(self, gamma: float) -> None:
        self.gamma = gamma

    def __call__(self, base_lr: float, itr: int) -> float:
        return base_lr * self.gamma ** itr


class InvPolicy(LRPolicyBase):
    """``lr = base · (1 + gamma·itr)^(−power)``."""

    def __init__(self, gamma: float, power: float = 1.0) -> None:
        self.gamma = gamma
        self.power = power

    def __call__(self, base_lr: float, itr: int) -> float:
        return base_lr * (1.0 + self.gamma * itr) ** (-self.power)


class PolyPolicy(LRPolicyBase):
    """``lr = base · (1 − itr/max_iter)^power`` (clamped at 0)."""

    def __init__(self, max_iter: int, power: float = 1.0) -> None:
        self.max_iter = int(max_iter)
        self.power = power

    def __call__(self, base_lr: float, itr: int) -> float:
        frac = max(0.0, 1.0 - itr / self.max_iter)
        return base_lr * frac ** self.power


class ArbitraryStepPolicy(LRPolicyBase):
    """Explicit piecewise-constant schedule: ``[(lr, n_steps), …]``;
    the last rate holds forever (reference: arbitrary-step policy fed
    from AlexNet-style hand schedules)."""

    def __init__(self, lrs_with_lengths: list[tuple[float, int]]) -> None:
        if not lrs_with_lengths:
            raise ValueError("empty schedule")
        self.lrs_with_lengths = [(float(lr), int(n))
                                 for lr, n in lrs_with_lengths]

    def __call__(self, base_lr: float, itr: int) -> float:
        remaining = itr
        for lr, length in self.lrs_with_lengths:
            if remaining < length:
                return lr
            remaining -= length
        return self.lrs_with_lengths[-1][0]


POLICIES = {
    "fixed": FixedPolicy,
    "step_exp": StepExpPolicy,
    "exp": ExpPolicy,
    "inv": InvPolicy,
    "poly": PolyPolicy,
    "arbitrary_step": ArbitraryStepPolicy,
}


def make_policy(spec) -> LRPolicyBase | None:
    """Build a policy from ``None`` / a policy object / a
    ``(name, kwargs)`` pair / a ``{"name": ..., **kwargs}`` dict."""
    if spec is None or isinstance(spec, LRPolicyBase):
        return spec
    if isinstance(spec, dict):
        spec = dict(spec)
        name = spec.pop("name")
        return POLICIES[name](**spec)
    if isinstance(spec, (tuple, list)):
        name, kwargs = spec
        return POLICIES[name](**kwargs)
    raise TypeError(f"cannot build LR policy from {spec!r}")


# ----------------------------------------------------------------------
# the adjuster unit
# ----------------------------------------------------------------------
class LearningRateAdjust(Unit):
    """Rewrites GD units' learning rates per training iteration.

    Wire after the decision unit (``StandardWorkflow.link_lr_adjuster``
    does this); the FIFO scheduler then guarantees it fires before the
    next minibatch's compute region.  The iteration counter advances
    once per *training* minibatch, matching the reference's
    minibatch-count semantics.
    """

    SNAPSHOT_ATTRS = ("_n_iterations",)

    def __init__(self, workflow, name: str | None = None, **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self._gd_units: list[tuple[GradientDescentBase, LRPolicyBase | None,
                                   LRPolicyBase | None]] = []
        self._n_iterations = 0
        self.loader = None  # linked by the workflow builder

    def add_gd_unit(self, gd_unit: GradientDescentBase,
                    lr_policy=None, bias_lr_policy=None) -> None:
        self._gd_units.append((gd_unit, make_policy(lr_policy),
                               make_policy(bias_lr_policy)))

    def initialize(self, **kwargs) -> None:
        if self.loader is None:
            raise ValueError(f"{self}: loader not set")
        for gd_unit, lr_policy, bias_policy in self._gd_units:
            if lr_policy is None and bias_policy is None:
                continue
            if gd_unit.device is None:
                raise AttributeError(f"{gd_unit} has no device yet")
            gd_unit.lr_state.reset(np.asarray(
                [gd_unit.learning_rate, gd_unit.learning_rate_bias],
                dtype=np.float32))
            gd_unit.init_vectors(gd_unit.lr_state)
        super().initialize(**kwargs)
        self._apply()  # iteration 0 rates in place before the first step

    def run(self) -> None:
        if self.loader.minibatch_class != TRAIN:
            return  # only training minibatches advance the schedule
        self._n_iterations += 1
        self._apply()

    def load_state(self, state: dict) -> None:
        super().load_state(state)
        self._apply()

    def _apply(self) -> None:
        itr = self._n_iterations
        for gd_unit, lr_policy, bias_policy in self._gd_units:
            if lr_policy is None and bias_policy is None:
                continue
            vec = gd_unit.lr_state
            if lr_policy is not None:
                # both slots rewritten — skip the device→host fetch
                vec.map_invalidate()
                vec.mem[0] = lr_policy(gd_unit.learning_rate, itr)
                # reference behavior: bias follows the weight policy
                # unless given its own
                follow = bias_policy if bias_policy is not None else lr_policy
                vec.mem[1] = follow(gd_unit.learning_rate_bias, itr)
            else:
                vec.map_write()
                vec.mem[1] = bias_policy(gd_unit.learning_rate_bias, itr)
            # restore the device-authoritative invariant so eager
            # (non-region) xla_run can read devmem immediately
            vec.unmap()
