"""Standalone activation units (reference: ``znicz/activation.py`` —
``ForwardTanh``/``ForwardRELU``/``ForwardStrictRELU``/``ForwardSigmoid``
/``ForwardLog``/``ForwardMul`` and their ``Backward*`` mirrors), for
when an activation is not fused into All2All/Conv.

On TPU these are pure elementwise jnp ops the jit region fuses into
the neighboring GEMM/conv — no HBM round-trip (SURVEY.md §2.3:
"jnp elementwise, XLA fuses")."""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from znicz_tpu.ops import activations_math
from znicz_tpu.ops.nn_units import Forward, GradientDescentBase


class ActivationForward(Forward):
    """Weightless elementwise forward ``y = act(x)``."""

    ACTIVATION = "linear"

    def __init__(self, workflow, name=None, **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.activation = activations_math.get(self.ACTIVATION)

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        if self.input is None or not self.input:
            raise AttributeError(f"{self}: input not linked yet")
        self.output.reset(np.zeros(self.input.shape,
                                   dtype=self.output_store_dtype))
        self.inherit_model_shard(self.output)
        self.init_vectors(self.input, self.output)

    def numpy_run(self) -> None:
        self.input.map_read()
        self.output.map_invalidate()
        self.output.mem[...] = self.activation.fwd(
            np, self.input.mem.astype(np.float32))

    def xla_run(self) -> None:
        self.output.devmem = self.activation.fwd(jnp, self.input.devmem)


class ActivationBackward(GradientDescentBase):
    """Weightless backward ``err_input = err_output ⊙ act'``."""

    ACTIVATION = "linear"

    def __init__(self, workflow, name=None, **kwargs):
        kwargs.pop("learning_rate", None)
        super().__init__(workflow, name=name, **kwargs)
        self.activation = activations_math.get(self.ACTIVATION)

    def initialize(self, device=None, **kwargs) -> None:
        if self.input is None or not self.input:
            raise AttributeError(f"{self}: input not linked yet")
        super().initialize(device=device, **kwargs)
        self.init_vectors(self.err_input, self.err_output, self.input,
                          self.output)

    def numpy_run(self) -> None:
        for vec in (self.err_output, self.output):
            vec.map_read()
        x = None
        if self.activation.needs_input:
            self.input.map_read()
            x = self.input.mem
        self.err_input.map_invalidate()
        self.err_input.mem[...] = (
            self.err_output.mem
            * self.activation.derivative(np, self.output.mem, x))

    def xla_run(self) -> None:
        x = self.input.devmem if self.activation.needs_input else None
        self.err_input.devmem = (
            self.err_output.devmem
            * self.activation.derivative(jnp, self.output.devmem, x))


class ForwardTanh(ActivationForward):
    ACTIVATION = "tanh"


class BackwardTanh(ActivationBackward):
    ACTIVATION = "tanh"
    MATCHES = (ForwardTanh,)


class ForwardRELU(ActivationForward):
    ACTIVATION = "relu"


class BackwardRELU(ActivationBackward):
    ACTIVATION = "relu"
    MATCHES = (ForwardRELU,)


class ForwardStrictRELU(ActivationForward):
    ACTIVATION = "strict_relu"


class BackwardStrictRELU(ActivationBackward):
    ACTIVATION = "strict_relu"
    MATCHES = (ForwardStrictRELU,)


class ForwardSigmoid(ActivationForward):
    ACTIVATION = "sigmoid"


class BackwardSigmoid(ActivationBackward):
    ACTIVATION = "sigmoid"
    MATCHES = (ForwardSigmoid,)


class ForwardLog(ActivationForward):
    ACTIVATION = "log"


class BackwardLog(ActivationBackward):
    ACTIVATION = "log"
    MATCHES = (ForwardLog,)


class ForwardMul(ActivationForward):
    """Scale by a constant factor (reference: ``ForwardMul``)."""

    def __init__(self, workflow, factor: float = 1.0, name=None,
                 **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.factor = float(factor)

    def numpy_run(self) -> None:
        self.input.map_read()
        self.output.map_invalidate()
        self.output.mem[...] = self.input.mem * self.factor

    def xla_run(self) -> None:
        self.output.devmem = self.input.devmem * self.factor


class BackwardMul(GradientDescentBase):
    MATCHES = (ForwardMul,)

    def __init__(self, workflow, name=None, **kwargs):
        kwargs.pop("learning_rate", None)
        super().__init__(workflow, name=name, **kwargs)
        self.forward_unit: ForwardMul | None = None

    def initialize(self, device=None, **kwargs) -> None:
        if self.input is None or not self.input:
            raise AttributeError(f"{self}: input not linked yet")
        super().initialize(device=device, **kwargs)
        self.init_vectors(self.err_input, self.err_output)

    def numpy_run(self) -> None:
        self.err_output.map_read()
        self.err_input.map_invalidate()
        self.err_input.mem[...] = (self.err_output.mem
                                   * self.forward_unit.factor)

    def xla_run(self) -> None:
        self.err_input.devmem = (self.err_output.devmem
                                 * self.forward_unit.factor)
