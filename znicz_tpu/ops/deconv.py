"""Transposed convolution ("deconvolution") forward unit.

Rebuilds the reference's ``znicz/deconv.py`` ``Deconv``: the decoder
half of convolutional autoencoders (MnistAE / ImagenetAE samples).  A
``Deconv`` inverts the geometry of a paired :class:`~znicz_tpu.ops.conv.Conv`
— input has ``n_kernels`` channels, output has the conv's input
channels — and may *share* the conv's weight Vector (tied-weight AE).

The reference lowered this as a hand-written col2im scatter kernel.
TPU-first, the XLA path is the **``jax.linear_transpose`` of the
paired conv's data argument** (no primal evaluation, unlike
``jax.vjp``) — XLA's native transposed-conv lowering onto the MXU; the
numpy oracle is the explicit ``x @ Wᵀ`` + ``col2im`` math (an
independent implementation doubling as the spec, same pattern as
``gd_conv.py``).

Geometry contract (reference: ``Deconv.compute_padding`` /
``get_output_shape_from``): the output shape comes from
``output_shape_source`` (typically the paired conv's ``input``), and
``conv(output_shape) == input_shape`` is validated at initialize.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from znicz_tpu.memory import Vector
from znicz_tpu.ops import activations_math
from znicz_tpu.ops.conv import DIMNUMS, col2im, im2col, normalize_padding
from znicz_tpu.ops.nn_units import Forward


class Deconv(Forward):
    """Transposed 2-D convolution (linear flavor)."""

    ACTIVATION = "linear"

    def __init__(self, workflow, n_kernels: int, kx: int, ky: int,
                 sliding=(1, 1), padding=0, name=None,
                 include_bias: bool = False, **kwargs) -> None:
        # reference Deconv carries no bias by default (decoder half)
        super().__init__(workflow, name=name, include_bias=include_bias,
                         **kwargs)
        self.n_kernels = int(n_kernels)
        self.kx, self.ky = int(kx), int(ky)
        self.sliding = (int(sliding[0]), int(sliding[1]))
        self.padding = normalize_padding(padding)
        self.activation = activations_math.get(self.ACTIVATION)
        #: Vector whose shape defines the output (reference:
        #: ``get_output_shape_from``) — usually the paired conv's input
        self.output_shape_source: Vector | None = None

    # ------------------------------------------------------------------
    def conv_spatial(self, h: int, w: int) -> tuple[int, int]:
        """Spatial shape the paired conv would produce from (h, w)."""
        pt, pb, pl, pr = self.padding
        sy, sx = self.sliding
        return ((h + pt + pb - self.ky) // sy + 1,
                (w + pl + pr - self.kx) // sx + 1)

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        if self.input is None or not self.input:
            raise AttributeError(f"{self}: input not linked yet")
        if self.output_shape_source is None \
                or not self.output_shape_source:
            raise AttributeError(
                f"{self}: output_shape_source not linked — link it to "
                f"the paired conv's input (reference: "
                f"get_output_shape_from)")
        out_shape = tuple(self.output_shape_source.shape)
        n, ih, iw, k = self.input.shape
        if k != self.n_kernels:
            raise ValueError(f"{self}: input has {k} channels, "
                             f"expected n_kernels={self.n_kernels}")
        oh, ow = self.conv_spatial(out_shape[1], out_shape[2])
        if (oh, ow) != (ih, iw):
            raise ValueError(
                f"{self}: conv({out_shape[1:3]}) = {(oh, ow)} does not "
                f"match input spatial {(ih, iw)} — bad deconv geometry")
        c = out_shape[3]
        fan_in = self.ky * self.kx * c
        if not self.weights:  # may be shared with the paired conv
            self.weights.reset(self.fill_array(
                (self.ky, self.kx, c, self.n_kernels),
                self.weights_filling, self.weights_stddev, fan_in=fan_in))
        if self.include_bias and not self.bias:
            self.bias.reset(self.fill_array(
                (c,), self.bias_filling, self.bias_stddev, fan_in=fan_in))
        self.output.reset(np.zeros(out_shape,
                                   dtype=self.output_store_dtype))
        self.init_vectors(self.input, self.output, self.weights, self.bias)

    # -- pure forward (jnp) ---------------------------------------------
    def paired_conv_raw(self, y, w):
        """The PAIRED forward conv (out_space → in_space) at MXU
        precision — one home for the geometry/cast recipe shared by
        :meth:`deconv_raw` and the backward unit's input grad."""
        pt, pb, pl, pr = self.padding
        dt = self.mxu_dtype
        if dt is not None:  # bf16 inputs, MXU-native (see Conv.conv_raw)
            y, w = y.astype(dt), w.astype(dt)
        return jax.lax.conv_general_dilated(
            y, w, window_strides=self.sliding,
            padding=((pt, pb), (pl, pr)),
            dimension_numbers=DIMNUMS)

    def deconv_raw(self, x, w):
        """Bare transposed conv at MXU precision: the
        ``jax.linear_transpose`` (no primal evaluation) of the paired
        conv's data argument — exactly XLA's conv transpose rule."""
        dt = self.mxu_dtype
        if dt is not None:
            x = x.astype(dt)
        transpose = jax.linear_transpose(
            lambda y: self.paired_conv_raw(y, w),
            jax.ShapeDtypeStruct(self.output.shape, x.dtype))
        (out,) = transpose(x)
        return out

    def xla_forward(self, x, w, b):
        out = self.deconv_raw(x, w)
        if out.dtype != jnp.float32:
            out = out.astype(jnp.float32)
        if b is not None:
            out = out + b
        return self.activation.fwd(jnp, out)

    def numpy_run(self) -> None:
        self.input.map_read()
        self.weights.map_read()
        x = self.input.mem.astype(np.float32)
        w = self.weights.mem
        n, ih, iw, k = x.shape
        w2d = w.reshape(-1, k)                      # (ky*kx*C, K)
        cols = (x.reshape(-1, k) @ w2d.T).reshape(
            n, ih, iw, w2d.shape[0])
        out = col2im(cols, self.output.shape, self.ky, self.kx,
                     *self.sliding, self.padding)
        if self.include_bias:
            self.bias.map_read()
            out = out + self.bias.mem
        self.output.map_invalidate()
        self.output.mem[...] = self.activation.fwd(np, out)

    def xla_run(self) -> None:
        b = self.bias.devmem if self.include_bias else None
        self.output.devmem = self.xla_forward(
            self.input.devmem, self.weights.devmem, b)


class DeconvTanh(Deconv):
    ACTIVATION = "tanh"


class DeconvRELU(Deconv):
    ACTIVATION = "relu"


class DeconvSigmoid(Deconv):
    ACTIVATION = "sigmoid"


# keep the reference's module split: gradient unit in gd_deconv.py
from znicz_tpu.ops import gd_deconv  # noqa: E402,F401  (registers pairing)
