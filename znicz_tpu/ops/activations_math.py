"""Activation functions and their derivatives, numpy + jnp.

Semantics follow the reference's activation family (reference:
``znicz/activation.py``, ``znicz/all2all.py``, ``znicz/conv.py``):

- ``tanh`` is the scaled LeCun tanh ``y = 1.7159·tanh(0.6666·x)``;
- ``relu`` is the reference's *smooth* RELU ``y = log(1 + exp(x))``
  (softplus);
- ``strict_relu`` is ``max(x, 0)``;
- ``sigmoid``, ``log`` (``log(x + sqrt(x²+1))``, i.e. asinh), ``mul``
  (scale by a constant) complete the set.

Derivatives are expressed in terms of the *output* ``y`` where the
reference does so (cheap in the fused backward units); ``log`` needs
the input ``x``.  One table serves numpy and jnp because the math is
written against the array-API surface both share.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

import numpy as np

import jax.numpy as jnp


_TANH_A = 1.7159
_TANH_B = 0.6666


@dataclass(frozen=True)
class Activation:
    """fwd(xp, x) -> y;  derivative(xp, y, x) -> dy/dx."""
    name: str
    fwd: Callable
    derivative: Callable
    needs_input: bool = False


def _softplus(xp, x):
    # log(1+exp(x)) stably: max(x,0) + log1p(exp(-|x|))
    return xp.maximum(x, 0) + xp.log1p(xp.exp(-xp.abs(x)))


ACTIVATIONS: dict[str, Activation] = {
    "linear": Activation(
        "linear",
        fwd=lambda xp, x: x,
        derivative=lambda xp, y, x: xp.ones_like(y)),
    "tanh": Activation(
        "tanh",
        fwd=lambda xp, x: _TANH_A * xp.tanh(_TANH_B * x),
        # dy/dx = A·B·(1−tanh²) = (B/A)·(A²−y²)
        derivative=lambda xp, y, x: (_TANH_B / _TANH_A) * (
            _TANH_A * _TANH_A - y * y)),
    "relu": Activation(
        "relu",
        fwd=_softplus,
        # y = log(1+eˣ) ⇒ dy/dx = 1 − e^{−y}
        derivative=lambda xp, y, x: 1.0 - xp.exp(-y)),
    "strict_relu": Activation(
        "strict_relu",
        fwd=lambda xp, x: xp.maximum(x, 0),
        derivative=lambda xp, y, x: (y > 0).astype(y.dtype)),
    "sigmoid": Activation(
        "sigmoid",
        fwd=lambda xp, x: 1.0 / (1.0 + xp.exp(-x)),
        derivative=lambda xp, y, x: y * (1.0 - y)),
    "log": Activation(
        "log",
        fwd=lambda xp, x: xp.log(x + xp.sqrt(x * x + 1.0)),
        derivative=lambda xp, y, x: 1.0 / xp.sqrt(x * x + 1.0),
        needs_input=True),
}


def get(name: str) -> Activation:
    try:
        return ACTIVATIONS[name]
    except KeyError:
        raise ValueError(
            f"unknown activation '{name}' "
            f"(have {sorted(ACTIVATIONS)})") from None


def np_ns():
    return np


def jnp_ns():
    return jnp
