"""NN op library: forward units, paired gradient units, evaluators,
decision/schedule units (reference: the ``znicz/*.py`` unit corpus,
SURVEY.md §2.2).

Every forward unit has a ``numpy_run`` oracle and an ``xla_run`` jax
path; backward units are explicit (not autodiff) so per-unit
cross-backend tests mirror the reference's test strategy, and the whole
fwd+bwd chain still compiles into one XLA program via jit regions.
"""
