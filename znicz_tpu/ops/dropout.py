"""Dropout (reference: ``znicz/dropout.py`` — ``DropoutForward`` /
``DropoutBackward``).

Train mode: zero each activation with probability ``dropout_ratio``
and scale survivors by ``1/(1−ratio)`` (inverted dropout, so eval is
identity — documented divergence: the reference scaled at eval time;
final-accuracy semantics are identical).  The mask is stored and
reused by the backward unit, exactly like the reference.

``forward_mode`` ("train"/"eval") is a static region key, so the jit
region compiles a masked and an identity variant — this is the
per-minibatch-gate case SURVEY.md §7 calls out.  Device randomness
comes from the unit's own PRNG key chain (a region leaf).

Pallas variant (``root.common.engine.use_pallas`` incl. ``"dropout"``,
resolved once at initialize): mask generation + apply fuse into one
VMEM pass over TPU-core PRNG bits (``pallas_kernels.dropout_apply``);
no mask array materializes — the backward regenerates the identical
mask from the same per-step seed.  The default follows the in-graph
chip A/B in PALLAS_BENCH.md.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from znicz_tpu.memory import Vector
from znicz_tpu.ops.nn_units import Forward, WeightlessGradientUnit


class DropoutForward(Forward):
    def __init__(self, workflow, dropout_ratio: float = 0.5, name=None,
                 **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        if not 0.0 <= dropout_ratio < 1.0:
            raise ValueError(f"dropout_ratio {dropout_ratio} not in [0,1)")
        self.dropout_ratio = float(dropout_ratio)
        self.forward_mode = "train"
        self.mask = Vector(name=f"{self.name}.mask", batch_major=True)

    def region_key(self) -> tuple:
        return (self.forward_mode,)

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        if self.input is None or not self.input:
            raise AttributeError(f"{self}: input not linked yet")
        from znicz_tpu.ops import pallas_kernels
        self._use_pallas = pallas_kernels.use_pallas(self.device,
                                                     "dropout")
        self._pallas_seed = None  # per-step traced seed (fwd → bwd)
        self.output.reset(np.zeros(self.input.shape,
                                   dtype=self.output_store_dtype))
        if self._use_pallas:
            # no mask array at all: the backward regenerates it in
            # VMEM from the seed — allocating/uploading the Vector
            # would negate the kernel's HBM saving
            self.inherit_model_shard(self.output)
            self.init_vectors(self.input, self.output)
        else:
            self.mask.reset(np.ones(self.input.shape,
                                    dtype=self.act_store_dtype))
            self.inherit_model_shard(self.output, self.mask)
            self.init_vectors(self.input, self.output, self.mask)
        self.init_rng()

    def numpy_run(self) -> None:
        from znicz_tpu.utils import prng
        self.input.map_read()
        self.output.map_invalidate()
        if self.forward_mode == "train":
            keep = 1.0 - self.dropout_ratio
            self.mask.map_invalidate()
            self.mask.mem[...] = (
                prng.get().numpy.uniform(size=self.input.shape) < keep
            ).astype(np.float32) / keep
            self.output.mem[...] = self.input.mem * self.mask.mem
        else:
            self.output.mem[...] = self.input.mem

    def xla_run(self) -> None:
        x = self.input.devmem
        if self.forward_mode != "train":
            self.output.devmem = x
            return
        key = self.take_key()
        if self._use_pallas:
            from znicz_tpu.ops import pallas_kernels
            # one int32 seed per step drives the TPU-core PRNG; the
            # backward regenerates the identical mask from it (no
            # mask array materializes in HBM)
            seed = jax.random.bits(key, (1,), jnp.uint32) \
                .astype(jnp.int32)
            self._pallas_seed = seed
            self.output.devmem = pallas_kernels.dropout_apply(
                x, seed, self.dropout_ratio)
            return
        keep = 1.0 - self.dropout_ratio
        mask = jax.random.bernoulli(key, keep, x.shape).astype(
            x.dtype) / keep
        self.mask.devmem = mask
        self.output.devmem = x * mask


class DropoutBackward(WeightlessGradientUnit):
    MATCHES = (DropoutForward,)

    def region_key(self) -> tuple:
        fwd = self.forward_unit
        return (fwd.forward_mode if fwd is not None else "train",)

    def numpy_run(self) -> None:
        fwd = self.forward_unit
        self.err_output.map_read()
        self.err_input.map_invalidate()
        if fwd.forward_mode == "train":
            fwd.mask.map_read()
            self.err_input.mem[...] = self.err_output.mem * fwd.mask.mem
        else:
            self.err_input.mem[...] = self.err_output.mem

    def xla_run(self) -> None:
        fwd = self.forward_unit
        err = self.err_output.devmem
        if fwd.forward_mode != "train":
            self.err_input.devmem = err
            return
        if getattr(fwd, "_use_pallas", False):
            from znicz_tpu.ops import pallas_kernels
            # same seed, same shape → bit-identical mask regenerated
            # in VMEM (err · mask ≡ dropout_apply(err, seed))
            self.err_input.devmem = pallas_kernels.dropout_apply(
                err, fwd._pallas_seed, fwd.dropout_ratio)
            return
        self.err_input.devmem = err * fwd.mask.devmem

