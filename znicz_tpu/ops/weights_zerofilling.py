"""ZeroFiller: force-zero masked weight entries after each update —
sparsity experiment support (reference:
``znicz/weights_zerofilling.py`` ``ZeroFiller``).

Not a chain layer: wire it as a side unit after the backward chain
(``zf.link_from(gd_unit)``) with ``target_weights`` linked to the
forward unit's ``weights``; the mask persists in snapshots.
"""

from __future__ import annotations

import numpy as np

from znicz_tpu.accelerated_units import AcceleratedUnit
from znicz_tpu.memory import Vector


class ZeroFiller(AcceleratedUnit):
    def __init__(self, workflow, name=None, **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.target_weights: Vector | None = None  # link from a fwd unit
        self.zero_mask = Vector(name=f"{self.name}.zero_mask")

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        if self.target_weights is None or not self.target_weights:
            raise AttributeError(f"{self}: target_weights not linked")
        if not self.zero_mask:
            self.zero_mask.reset(
                np.ones(self.target_weights.shape, dtype=np.float32))
        self.init_vectors(self.target_weights, self.zero_mask)

    def numpy_run(self) -> None:
        self.target_weights.map_write()
        self.zero_mask.map_read()
        self.target_weights.mem[...] *= self.zero_mask.mem

    def xla_run(self) -> None:
        self.target_weights.devmem = (
            self.target_weights.devmem * self.zero_mask.devmem)
