"""Pooling forward units (reference: ``znicz/pooling.py``).

Reference semantics preserved:

- :class:`MaxAbsPooling` — the reference's ``MaxPooling`` picked the
  element with the largest **absolute** value, keeping its sign, and
  recorded the winner offset for the backward scatter;
  :class:`MaxPooling` here is the plain max variant.
- :class:`AvgPooling` — window mean.
- :class:`StochasticPooling` — samples a window element with
  probability proportional to its (positive) value at train time
  (reference: stochastic pooling with on-device PRNG).

TPU-first: the XLA path is ``lax.reduce_window`` (and a
``jax.random``-driven gather for stochastic pooling); backward units
(``gd_pooling.py``) use the vjp transpose —
``select_and_scatter``-style — instead of recorded offsets
(SURVEY.md §2.3: "max-offsets ... or recompute-in-bwd").  The numpy
oracle records winner offsets exactly like the reference, so the test
suite proves the two formulations agree.

Window geometry: ``kx``/``ky`` + ``sliding``; inputs NHWC.  Edge
windows are truncated (the reference padded the tail window; we use
-inf/0 padding through ``reduce_window`` which matches truncation for
max/avg given the count normalization below).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from znicz_tpu.accelerated_units import AcceleratedUnit
from znicz_tpu.memory import Vector
from znicz_tpu.ops.nn_units import Forward


class Pooling(Forward):
    """Base pooling unit (weightless Forward)."""

    def __init__(self, workflow, kx: int, ky: int, sliding=None,
                 name=None, **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.kx, self.ky = int(kx), int(ky)
        if sliding is None:
            sliding = (self.ky, self.kx)  # reference default: no overlap
        self.sliding = (int(sliding[0]), int(sliding[1]))

    def output_spatial(self, h: int, w: int) -> tuple[int, int]:
        sy, sx = self.sliding
        # ceil-div: tail windows are truncated (reference behavior)
        return (-(-(h - self.ky) // sy) + 1 if h > self.ky else 1,
                -(-(w - self.kx) // sx) + 1 if w > self.kx else 1)

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        if self.input is None or not self.input:
            raise AttributeError(f"{self}: input not linked yet")
        n, h, w, c = self.input.shape
        oh, ow = self.output_spatial(h, w)
        self.output.reset(np.zeros((n, oh, ow, c),
                                   dtype=self.output_store_dtype))
        self.init_vectors(self.input, self.output)
        self._setup()

    def _setup(self) -> None:
        pass

    # shared window iteration for the numpy oracle
    def _windows(self, h: int, w: int):
        sy, sx = self.sliding
        oh, ow = self.output_spatial(h, w)
        for oy in range(oh):
            y0 = oy * sy
            for ox in range(ow):
                x0 = ox * sx
                yield (oy, ox, y0, min(y0 + self.ky, h),
                       x0, min(x0 + self.kx, w))

    def _pad_hw(self, h: int, w: int) -> tuple[int, int]:
        """reduce_window low/high padding so XLA covers the same
        (truncated-at-the-tail) windows as the oracle."""
        sy, sx = self.sliding
        oh, ow = self.output_spatial(h, w)
        need_h = (oh - 1) * sy + self.ky
        need_w = (ow - 1) * sx + self.kx
        return need_h - h, need_w - w

    def stack_windows(self, x):
        """jnp: every window as (n, oh, ow, ky*kx, c), out-of-range
        cells marked −inf.  Shared by the stochastic forward, the
        deterministic-tie MaxAbs forward, and the backward scatters."""
        n, h, w, c = x.shape
        oh, ow = self.output_spatial(h, w)
        sy, sx = self.sliding
        ph, pw = self._pad_hw(h, w)
        xp = jnp.pad(x, ((0, 0), (0, ph), (0, pw), (0, 0)),
                     constant_values=-jnp.inf)
        return jnp.stack([
            xp[:, i:i + (oh - 1) * sy + 1:sy,
               j:j + (ow - 1) * sx + 1:sx, :]
            for i in range(self.ky) for j in range(self.kx)], axis=3)


class MaxPooling(Pooling):
    """Plain max pooling."""

    def numpy_run(self) -> None:
        self.input.map_read()
        x = self.input.mem
        n, h, w, c = x.shape
        self.output.map_invalidate()
        out = self.output.mem
        for oy, ox, y0, y1, x0, x1 in self._windows(h, w):
            out[:, oy, ox, :] = x[:, y0:y1, x0:x1, :].max(axis=(1, 2))

    def xla_forward(self, x):
        ph, pw = self._pad_hw(x.shape[1], x.shape[2])
        return jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max,
            window_dimensions=(1, self.ky, self.kx, 1),
            window_strides=(1, *self.sliding, 1),
            padding=((0, 0), (0, ph), (0, pw), (0, 0)))

    def xla_run(self) -> None:
        self.output.devmem = self.xla_forward(self.input.devmem)


class MaxAbsPooling(Pooling):
    """Largest-|x| element, sign preserved (the reference's
    ``MaxPooling`` semantics — AlexNet-era CNNs with tanh need the
    signed extremum)."""

    def numpy_run(self) -> None:
        self.input.map_read()
        x = self.input.mem
        n, h, w, c = x.shape
        self.output.map_invalidate()
        out = self.output.mem
        for oy, ox, y0, y1, x0, x1 in self._windows(h, w):
            win = x[:, y0:y1, x0:x1, :].reshape(n, -1, c)
            idx = np.abs(win).argmax(axis=1)
            out[:, oy, ox, :] = np.take_along_axis(
                win, idx[:, None, :], axis=1)[:, 0, :]

    def xla_forward(self, x):
        # argmax over |window| (first occurrence) instead of
        # reduce_window: a |a|==|b| tie with opposite signs is
        # non-commutative under reduce_window's unspecified order —
        # the stacked argmax matches the oracle and the backward
        # scatter deterministically.
        wins = self.stack_windows(x)
        key = jnp.where(jnp.isfinite(wins), jnp.abs(wins), -jnp.inf)
        idx = key.argmax(axis=3)
        return jnp.take_along_axis(
            wins, idx[:, :, :, None, :], axis=3)[:, :, :, 0, :]

    def xla_run(self) -> None:
        self.output.devmem = self.xla_forward(self.input.devmem)


class AvgPooling(Pooling):
    """Window mean (truncated tail windows divide by the true count)."""

    def numpy_run(self) -> None:
        self.input.map_read()
        x = self.input.mem
        n, h, w, c = x.shape
        self.output.map_invalidate()
        out = self.output.mem
        for oy, ox, y0, y1, x0, x1 in self._windows(h, w):
            out[:, oy, ox, :] = x[:, y0:y1, x0:x1, :].mean(axis=(1, 2))

    def xla_forward(self, x):
        ph, pw = self._pad_hw(x.shape[1], x.shape[2])
        sums = jax.lax.reduce_window(
            x, jnp.zeros((), x.dtype), jax.lax.add,
            window_dimensions=(1, self.ky, self.kx, 1),
            window_strides=(1, *self.sliding, 1),
            padding=((0, 0), (0, ph), (0, pw), (0, 0)))
        counts = jax.lax.reduce_window(
            jnp.ones(x.shape[1:3], x.dtype), jnp.zeros((), x.dtype),
            jax.lax.add,
            window_dimensions=(self.ky, self.kx),
            window_strides=self.sliding,
            padding=((0, ph), (0, pw)))
        return sums / counts[None, :, :, None]

    def xla_run(self) -> None:
        self.output.devmem = self.xla_forward(self.input.devmem)


class StochasticPooling(Pooling):
    """Train: sample ∝ max(x,0) within the window (uniform over the
    window when all values ≤ 0); eval: probability-weighted average
    (reference: ``StochasticPooling``).  ``forward_mode`` ("train" /
    "eval") is a static region key."""

    def __init__(self, workflow, kx, ky, sliding=None, name=None,
                 **kwargs) -> None:
        super().__init__(workflow, kx, ky, sliding=sliding, name=name,
                         **kwargs)
        self.forward_mode = "train"
        self.last_choice = Vector(name=f"{self.name}.last_choice",
                                  batch_major=True)

    def region_key(self) -> tuple:
        return (self.forward_mode,)

    def _setup(self) -> None:
        self.init_rng()
        n, oh, ow, c = self.output.shape
        self.last_choice.reset(np.zeros((n, oh, ow, c), dtype=np.int32))
        self.init_vectors(self.last_choice)

    def full_window(self, x: np.ndarray, y0, y1, x0, x1) -> np.ndarray:
        """(n, ky*kx, c) window padded with -inf at out-of-range cells
        so indices are in FULL window coordinates on both backends."""
        n, _, _, c = x.shape
        win = np.full((n, self.ky, self.kx, c), -np.inf, dtype=x.dtype)
        win[:, :y1 - y0, :x1 - x0, :] = x[:, y0:y1, x0:x1, :]
        return win.reshape(n, self.ky * self.kx, c)

    def numpy_run(self) -> None:
        from znicz_tpu.utils import prng
        self.input.map_read()
        x = self.input.mem
        n, h, w, c = x.shape
        self.output.map_invalidate()
        self.last_choice.map_invalidate()
        out = self.output.mem
        choice = self.last_choice.mem
        rnd = prng.get().numpy
        for oy, ox, y0, y1, x0, x1 in self._windows(h, w):
            win = self.full_window(x, y0, y1, x0, x1)
            valid = np.isfinite(win)
            win0 = np.where(valid, win, 0.0)
            pos = np.maximum(win0, 0.0) * valid
            total = pos.sum(axis=1, keepdims=True)
            kcnt = valid.sum(axis=1, keepdims=True).astype(x.dtype)
            uniform = valid.astype(x.dtype) / np.maximum(kcnt, 1.0)
            p = np.where(total > 0,
                         pos / np.where(total > 0, total, 1.0), uniform)
            if self.forward_mode == "train":
                cum = p.cumsum(axis=1)
                r = rnd.uniform(size=(n, 1, c))
                idx = (r > cum).sum(axis=1)
                out[:, oy, ox, :] = np.take_along_axis(
                    win0, idx[:, None, :], axis=1)[:, 0, :]
                choice[:, oy, ox, :] = idx
            else:
                out[:, oy, ox, :] = (p * win0).sum(axis=1)

    def xla_run(self) -> None:
        x = self.input.devmem
        n, h, w, c = x.shape
        oh, ow = self.output_spatial(h, w)
        wins = self.stack_windows(x)  # (n, oh, ow, ky*kx, c)
        valid = jnp.isfinite(wins)
        wins0 = jnp.where(valid, wins, 0.0)
        pos = jnp.maximum(wins0, 0.0) * valid
        total = pos.sum(axis=3, keepdims=True)
        kcnt = valid.sum(axis=3, keepdims=True).astype(x.dtype)
        uniform = valid.astype(x.dtype) / jnp.maximum(kcnt, 1.0)
        probs = jnp.where(total > 0, pos / jnp.where(total > 0, total, 1.0),
                          uniform)
        if self.forward_mode == "train":
            key = self.take_key()
            r = jax.random.uniform(key, (n, oh, ow, 1, c), dtype=x.dtype)
            cum = jnp.cumsum(probs, axis=3)
            idx = (r > cum).sum(axis=3)
            self.last_choice.devmem = idx.astype(jnp.int32)
            self.output.devmem = jnp.take_along_axis(
                wins0, idx[:, :, :, None, :], axis=3)[:, :, :, 0, :]
        else:
            self.output.devmem = (probs * wins0).sum(axis=3)
