"""Small auxiliary units from the reference's long tail
(reference: ``znicz/multi_hist.py``, ``znicz/labels_printer.py``,
``znicz/channel_splitter.py`` — SURVEY.md §2.2 verify-on-mount items;
rebuilt by behavioral description).
"""

from __future__ import annotations

import numpy as np

from znicz_tpu.accelerated_units import AcceleratedUnit
from znicz_tpu.memory import Vector
from znicz_tpu.plotting_units import Plotter
from znicz_tpu.units import Unit


class MultiHistogram(Plotter):
    """Per-layer weight histograms, one panel per watched Vector,
    published through the graphics service each firing (reference:
    ``MultiHistogram`` — weight-distribution diagnostics)."""

    def __init__(self, workflow, name: str | None = None,
                 n_bins: int = 30, **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.n_bins = int(n_bins)
        self._watched: list[tuple[str, Vector]] = []
        self.histograms: dict[str, tuple[np.ndarray, np.ndarray]] = {}

    def watch(self, label: str, vector: Vector) -> "MultiHistogram":
        self._watched.append((label, vector))
        return self

    def watch_workflow_weights(self) -> "MultiHistogram":
        for unit in getattr(self.workflow, "forwards", []):
            if unit.weights:
                self.watch(unit.name, unit.weights)
        return self

    def make_payload(self) -> dict | None:
        panels = {}
        for label, vec in self._watched:
            if not vec:
                continue
            vec.map_read()
            counts, edges = np.histogram(np.asarray(vec.mem).ravel(),
                                         bins=self.n_bins)
            self.histograms[label] = (counts, edges)
            panels[label] = counts.tolist()
        return {"kind": "multi_hist", "panels": panels} \
            if panels else None


class LabelsPrinter(Unit):
    """Logs per-minibatch predicted vs true labels with optional
    index→name mapping (reference: ``labels_printer.py``).  Wire after
    the forward chain, gate as desired (typically eval classes)."""

    def __init__(self, workflow, name: str | None = None,
                 label_names: dict[int, str] | None = None,
                 limit: int = 10, **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.label_names = dict(label_names or {})
        self.limit = int(limit)
        self.max_idx: Vector | None = None      # link from softmax fwd
        self.labels: Vector | None = None       # link from loader
        self.minibatch_valid: Vector | None = None
        self.lines: list[str] = []              # last firing's output

    def _name_of(self, idx: int) -> str:
        return self.label_names.get(idx, str(idx))

    def run(self) -> None:
        self.max_idx.map_read()
        self.labels.map_read()
        count = len(self.labels.mem)
        if self.minibatch_valid is not None and self.minibatch_valid:
            self.minibatch_valid.map_read()
            count = min(count, int(self.minibatch_valid.mem))
        self.lines = []
        for row in range(min(count, self.limit)):
            pred = int(self.max_idx.mem[row])
            true = int(self.labels.mem[row])
            mark = " " if pred == true else "✗"
            self.lines.append(
                f"{mark} pred={self._name_of(pred)} "
                f"true={self._name_of(true)}")
        self.info("labels:\n%s", "\n".join(self.lines))


class ChannelSplitter(AcceleratedUnit):
    """Splits the input's channel axis into per-group outputs
    (reference: ``channel_splitter.py`` — e.g. feeding separate towers
    per color plane).  ``groups`` is a list of channel-index lists;
    outputs land in ``self.outputs[i]`` (``output`` aliases group 0)."""

    def __init__(self, workflow, groups, name: str | None = None,
                 **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.groups = [list(map(int, g)) for g in groups]
        if not self.groups:
            raise ValueError("need at least one channel group")
        self.input: Vector | None = None
        self.outputs = [Vector(name=f"{self.name}.out{i}",
                               batch_major=True)
                        for i in range(len(self.groups))]
        self.output = self.outputs[0]

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        if self.input is None or not self.input:
            raise AttributeError(f"{self}: input not linked yet")
        c = self.input.shape[-1]
        for group in self.groups:
            bad = [ch for ch in group if not 0 <= ch < c]
            if bad:
                raise ValueError(f"{self}: channels {bad} out of "
                                 f"range (input has {c})")
        base = self.input.shape[:-1]
        for vec, group in zip(self.outputs, self.groups):
            vec.reset(np.zeros(base + (len(group),), dtype=np.float32))
        self.init_vectors(self.input, *self.outputs)

    def numpy_run(self) -> None:
        self.input.map_read()
        for vec, group in zip(self.outputs, self.groups):
            vec.map_invalidate()
            vec.mem[...] = self.input.mem[..., group]

    def xla_run(self) -> None:
        x = self.input.devmem
        for vec, group in zip(self.outputs, self.groups):
            vec.devmem = x[..., np.array(group)]
