"""Pooling backward units (reference: ``znicz/gd_pooling.py``).

The reference scattered errors to recorded winner offsets with custom
kernels.  Here the numpy oracle recomputes winners with ``argmax`` and
scatters explicitly; the XLA path builds the same scatter from a
static ``ky×kx`` unroll of strided ``.at[].add`` updates (XLA fuses
these into one scatter program inside the jit region) — equivalent to
``lax.select_and_scatter_add`` but shared across all four pooling
flavors, including the |x| and stochastic selections that
``reduce_window``'s autodiff cannot express.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from znicz_tpu.ops.nn_units import WeightlessGradientUnit
from znicz_tpu.ops.pooling import (
    AvgPooling,
    MaxAbsPooling,
    MaxPooling,
    StochasticPooling,
)


class GDPoolingBase(WeightlessGradientUnit):
    """Weightless backward: transforms err_output → err_input."""

    # -- shared geometry helpers ---------------------------------------
    def _stack_windows(self, x):
        return self.forward_unit.stack_windows(x)

    def _scatter_windows(self, err_wins, x_shape):
        """jnp inverse of _stack_windows: (n,oh,ow,ky*kx,c) → NHWC."""
        fwd = self.forward_unit
        n, h, w, c = x_shape
        oh, ow = fwd.output_spatial(h, w)
        sy, sx = fwd.sliding
        ph, pw = fwd._pad_hw(h, w)
        out = jnp.zeros((n, h + ph, w + pw, c), err_wins.dtype)
        e = 0
        for i in range(fwd.ky):
            for j in range(fwd.kx):
                out = out.at[:, i:i + (oh - 1) * sy + 1:sy,
                             j:j + (ow - 1) * sx + 1:sx, :].add(
                    err_wins[:, :, :, e, :])
                e += 1
        return out[:, :h, :w, :]

    def _numpy_scatter(self, pick_idx) -> None:
        """Oracle: scatter err to the winner offsets (reference's
        recorded-offset semantics)."""
        fwd = self.forward_unit
        x = self.input.mem
        n, h, w, c = x.shape
        err = self.err_output.mem
        self.err_input.map_invalidate()
        out = self.err_input.mem
        out[...] = 0.0
        for oy, ox, y0, y1, x0, x1 in fwd._windows(h, w):
            win = x[:, y0:y1, x0:x1, :].reshape(n, -1, c)
            idx = pick_idx(win, oy, ox)
            wh, ww = y1 - y0, x1 - x0
            iy = y0 + idx // ww
            ix = x0 + idx % ww
            bi = np.arange(n)[:, None]
            ci = np.arange(c)[None, :]
            np.add.at(out, (bi, iy, ix, ci), err[:, oy, ox, :])


class GDMaxPooling(GDPoolingBase):
    MATCHES = (MaxPooling,)
    _use_abs = False

    def numpy_run(self) -> None:
        for vec in (self.err_output, self.input):
            vec.map_read()

        def pick(win, oy, ox):
            key = np.abs(win) if self._use_abs else win
            return key.argmax(axis=1)

        self._numpy_scatter(pick)

    def xla_run(self) -> None:
        if not self._use_abs:
            # plain max: autodiff of the reduce_window forward lowers
            # to XLA's native SelectAndScatter — no materialized
            # (n,oh,ow,ky·kx,c) window tensor, ~9× less HBM traffic
            # for a 3×3 pool than the explicit scatter below
            import jax

            fwd = self.forward_unit
            _, vjp = jax.vjp(fwd.xla_forward, self.input.devmem)
            (self.err_input.devmem,) = vjp(self.err_output.devmem)
            return
        x = self.input.devmem
        wins = self._stack_windows(x)
        # |x| selection can't ride reduce_window autodiff (the forward
        # returns the SIGNED winner); keep the explicit window scatter
        key = jnp.where(jnp.isfinite(wins), jnp.abs(wins), -jnp.inf)
        idx = key.argmax(axis=3)
        onehot = (jnp.arange(wins.shape[3])[None, None, None, :, None]
                  == idx[:, :, :, None, :])
        err_wins = onehot * self.err_output.devmem[:, :, :, None, :]
        self.err_input.devmem = self._scatter_windows(
            err_wins.astype(x.dtype), x.shape)


class GDMaxAbsPooling(GDMaxPooling):
    MATCHES = (MaxAbsPooling,)
    _use_abs = True


class GDAvgPooling(GDPoolingBase):
    MATCHES = (AvgPooling,)

    def numpy_run(self) -> None:
        fwd = self.forward_unit
        for vec in (self.err_output, self.input):
            vec.map_read()
        x = self.input.mem
        n, h, w, c = x.shape
        err = self.err_output.mem
        self.err_input.map_invalidate()
        out = self.err_input.mem
        out[...] = 0.0
        for oy, ox, y0, y1, x0, x1 in fwd._windows(h, w):
            count = (y1 - y0) * (x1 - x0)
            out[:, y0:y1, x0:x1, :] += \
                err[:, oy, ox, None, None, :].reshape(n, 1, 1, c) / count

    def xla_run(self) -> None:
        x = self.input.devmem
        wins = self._stack_windows(x)
        valid = jnp.isfinite(wins)
        counts = valid.sum(axis=3, keepdims=True).astype(x.dtype)
        err_wins = (valid * self.err_output.devmem[:, :, :, None, :]
                    / jnp.maximum(counts, 1.0))
        self.err_input.devmem = self._scatter_windows(
            err_wins.astype(x.dtype), x.shape)


class GDStochasticPooling(GDPoolingBase):
    """Scatter to the element sampled at forward time (recorded in
    ``last_choice`` by both backends)."""

    MATCHES = (StochasticPooling,)

    def numpy_run(self) -> None:
        fwd = self.forward_unit
        for vec in (self.err_output, self.input):
            vec.map_read()
        fwd.last_choice.map_read()
        choice = fwd.last_choice.mem  # FULL-window coordinates
        x = self.input.mem
        n, h, w, c = x.shape
        err = self.err_output.mem
        self.err_input.map_invalidate()
        out = self.err_input.mem
        out[...] = 0.0
        bi = np.arange(n)[:, None]
        ci = np.arange(c)[None, :]
        for oy, ox, y0, y1, x0, x1 in fwd._windows(h, w):
            idx = choice[:, oy, ox, :]
            iy = y0 + idx // fwd.kx
            ix = x0 + idx % fwd.kx
            np.add.at(out, (bi, iy, ix, ci), err[:, oy, ox, :])

    def xla_run(self) -> None:
        fwd = self.forward_unit
        x = self.input.devmem
        k = fwd.ky * fwd.kx
        idx = fwd.last_choice.devmem
        onehot = (jnp.arange(k)[None, None, None, :, None]
                  == idx[:, :, :, None, :])
        err_wins = onehot * self.err_output.devmem[:, :, :, None, :]
        self.err_input.devmem = self._scatter_windows(
            err_wins.astype(x.dtype), x.shape)
