"""Fused flash-attention Pallas TPU kernels (forward + backward).

Why this exists: the round-5 profile of the T=2048 sequence step
(PERF.md) shows the six attention-core GEMMs plus the softmax
reduction pinned at the HBM bandwidth roof (~660–800 GB/s, 11–50
TF/s) streaming (B, H, T, T) score/probability tensors — ~78% of the
step.  The XLA paths (plain einsum and the ``lax.scan`` blocked fold
in :mod:`znicz_tpu.parallel.ring_attention`) cannot avoid
materializing those tensors (plain) or the per-step carry round-trips
(scan).  A fused kernel keeps every (block_q, block_k) score tile in
VMEM: the only HBM traffic is q/k/v/o (+ per-row logsumexp), so the
core runs at MXU rate instead of bandwidth rate.

Design (the standard flash decomposition, implemented TPU-first):

- **forward**: grid (B, H, nq, nk), K-blocks innermost ("arbitrary"
  semantics — sequential per core); online-softmax state (running row
  max m, normalizer l, weighted accumulator) lives in VMEM scratch
  across the K iterations; the output block and the per-row
  logsumexp are written once at the last K block.
- **backward**: recompute-from-lse form — no (T, T) residual is ever
  stored.  Saves (q, k, v, o, lse) from the forward, precomputes
  ``delta = rowsum(do·o)`` (one cheap XLA pass), then two kernels:
  ``dq`` (grid over K blocks innermost, accumulating dq tiles) and
  ``dk/dv`` (grid over Q blocks innermost, accumulating dk/dv tiles);
  each recomputes the score tile p = exp(s − lse) in VMEM.
- **dtypes**: tile GEMMs run at the input dtype (bf16 in the
  framework's mixed-precision mode) with f32 accumulation via
  ``preferred_element_type``; softmax statistics, lse, delta and all
  accumulators are f32 — the same bf16-inputs/f32-accumulation
  convention as the rest of the repo.
- **causal**: global-position mask inside the tile (exact across
  block boundaries — same rule as ``ring_attention._visibility``);
  fully-masked tiles are skipped via ``pl.when``, so causal runs at
  ~2× effective rate.

Layout contract: (B, T, H, D) at the boundary (the unit-graph
convention); kernels run head-major (B, H, T, D) — the wrapper
transposes, which costs two cheap bandwidth passes versus the many
(T, T) passes saved.

Adoption is measured, not assumed: SEQ_BENCH.json / PERF.md round 5
carry the chip A/B against the plain and scan-blocked XLA forms (the
PALLAS_BENCH.md decision rule).  ``interpret=True`` runs the same
kernels on CPU for the oracle equality tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: jax renamed ``TPUCompilerParams`` → ``CompilerParams``; accept both
#: so the kernels run on 0.4.x and current jax alike
_COMPILER_PARAMS = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams

_NEG_INF = -1e30
#: default tile sizes — chip-swept (PERF.md round 5): 1024×1024 beats
#: 512×512 by ~1.2× (fewer grid revisits of the VMEM stats; the f32
#: score tile is 4 MB); 2048-wide tiles overflow VMEM and fail to
#: compile, so callers wanting other shapes pass block_q/block_k
BLOCK_Q = 1024
BLOCK_K = 1024
#: lane width for the per-row statistics arrays (lse, delta): the
#: minimum tile-legal last dim — the value is replicated across lanes
_LANES = 8


def _causal_mask(iq, ik, bq: int, bk: int):
    """(bq, bk) visibility tile from GLOBAL positions (rows iq·bq…,
    cols ik·bk…)."""
    rows = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    cols = ik * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return rows >= cols


def _dot(a, b, trans_a: bool = False, trans_b: bool = False):
    """MXU dot with f32 accumulation, contracting dims picked so no
    operand is materialized transposed."""
    dims = (((0,) if trans_a else (1,), (1,) if trans_b else (0,)),
            ((), ()))
    return jax.lax.dot_general(a, b, dims,
                               preferred_element_type=jnp.float32)


# ----------------------------------------------------------------------
# forward
# ----------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, causal, bq, bk):
    iq, ik = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    visible = True if not causal else iq * bq + bq - 1 >= ik * bk

    @pl.when(visible)
    def _fold():
        q = q_ref[0, 0]                       # (bq, D)
        s = _dot(q, k_ref[0, 0], trans_b=True) * scale   # (bq, bk) f32
        if causal:
            s = jnp.where(_causal_mask(iq, ik, bq, bk), s, _NEG_INF)
        m_prev = m_scr[:, :1]                 # (bq, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                # masked → exp(−huge) = 0
        corr = jnp.exp(m_prev - m_new)        # (bq, 1)
        l_scr[...] = l_scr[...] * corr + jnp.sum(p, axis=1,
                                                 keepdims=True)
        acc_scr[...] = acc_scr[...] * corr \
            + _dot(p.astype(v_ref.dtype), v_ref[0, 0])
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(ik == nk - 1)
    def _finish():
        l = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0, 0] = (acc_scr[...] / l).astype(o_ref.dtype)
        # row stats ride 8 lanes (minimum tile-legal lane width; the
        # value is the same in every lane)
        lse_ref[0, 0] = jnp.broadcast_to(
            m_scr[:, :1] + jnp.log(l), lse_ref.shape[2:])


def _fwd_call(q, k, v, causal, bq, bk, interpret):
    b, h, t, d = q.shape
    tk = k.shape[2]
    nq, nk = t // bq, tk // bk
    kernel = functools.partial(_fwd_kernel, scale=1.0 / np.sqrt(d),
                               causal=causal, bq=bq, bk=bk)
    qspec = pl.BlockSpec((1, 1, bq, d), lambda b_, h_, iq, ik: (b_, h_, iq, 0))
    kspec = pl.BlockSpec((1, 1, bk, d), lambda b_, h_, iq, ik: (b_, h_, ik, 0))
    return pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=[qspec, kspec, kspec],
        out_specs=(qspec,
                   pl.BlockSpec((1, 1, bq, _LANES),
                                lambda b_, h_, iq, ik: (b_, h_, iq, 0))),
        out_shape=(jax.ShapeDtypeStruct((b, h, t, d), q.dtype),
                   jax.ShapeDtypeStruct((b, h, t, _LANES), jnp.float32)),
        scratch_shapes=[pltpu.VMEM((bq, 128), jnp.float32),
                        pltpu.VMEM((bq, 128), jnp.float32),
                        pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v)


# ----------------------------------------------------------------------
# backward: dq kernel (K blocks innermost), dk/dv kernel (Q innermost)
# ----------------------------------------------------------------------
def _p_tile(q_ref, k_ref, lse_ref, iq, ik, scale, causal, bq, bk):
    """Recompute the probability tile p = exp(s − lse) in VMEM."""
    s = _dot(q_ref[0, 0], k_ref[0, 0], trans_b=True) * scale
    if causal:
        s = jnp.where(_causal_mask(iq, ik, bq, bk), s, _NEG_INF)
    return jnp.exp(s - lse_ref[0, 0][:, :1])     # masked → 0


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, dq_scr, *, scale, causal, bq, bk):
    iq, ik = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    visible = True if not causal else iq * bq + bq - 1 >= ik * bk

    @pl.when(visible)
    def _fold():
        p = _p_tile(q_ref, k_ref, lse_ref, iq, ik, scale, causal,
                    bq, bk)
        dp = _dot(do_ref[0, 0], v_ref[0, 0], trans_b=True)  # (bq, bk)
        ds = p * (dp - delta_ref[0, 0][:, :1]) * scale
        dq_scr[...] += _dot(ds.astype(k_ref.dtype), k_ref[0, 0])

    @pl.when(ik == nk - 1)
    def _finish():
        dq_ref[0, 0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, scale, causal,
                bq, bk):
    ik, iq = pl.program_id(2), pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(iq == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    visible = True if not causal else iq * bq + bq - 1 >= ik * bk

    @pl.when(visible)
    def _fold():
        p = _p_tile(q_ref, k_ref, lse_ref, iq, ik, scale, causal,
                    bq, bk)
        do = do_ref[0, 0]
        # dv += pᵀ · do ; contract the q dim without materializing pᵀ
        dv_scr[...] += _dot(p.astype(do.dtype), do, trans_a=True)
        dp = _dot(do, v_ref[0, 0], trans_b=True)
        ds = p * (dp - delta_ref[0, 0][:, :1]) * scale
        dk_scr[...] += _dot(ds.astype(q_ref.dtype), q_ref[0, 0],
                            trans_a=True)

    @pl.when(iq == nq - 1)
    def _finish():
        dk_ref[0, 0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_call(q, k, v, o, lse, do, causal, bq, bk, interpret):
    b, h, t, d = q.shape
    tk = k.shape[2]
    nq, nk = t // bq, tk // bk
    delta = jnp.broadcast_to(
        jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                axis=-1, keepdims=True),
        (b, h, t, _LANES))                            # (B, H, T, 8)
    scale = 1.0 / np.sqrt(d)
    qspec = pl.BlockSpec((1, 1, bq, d), lambda b_, h_, iq, ik: (b_, h_, iq, 0))
    kspec = pl.BlockSpec((1, 1, bk, d), lambda b_, h_, iq, ik: (b_, h_, ik, 0))
    rspec = pl.BlockSpec((1, 1, bq, _LANES),
                         lambda b_, h_, iq, ik: (b_, h_, iq, 0))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk),
        grid=(b, h, nq, nk),
        in_specs=[qspec, kspec, kspec, qspec, rspec, rspec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((b, h, t, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    # dk/dv: Q blocks innermost; the q-side specs index by the LAST
    # grid dim now, the k-side by dim 2
    qspec2 = pl.BlockSpec((1, 1, bq, d), lambda b_, h_, ik, iq: (b_, h_, iq, 0))
    kspec2 = pl.BlockSpec((1, 1, bk, d), lambda b_, h_, ik, iq: (b_, h_, ik, 0))
    rspec2 = pl.BlockSpec((1, 1, bq, _LANES),
                          lambda b_, h_, ik, iq: (b_, h_, iq, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk),
        grid=(b, h, nk, nq),
        in_specs=[qspec2, kspec2, kspec2, qspec2, rspec2, rspec2],
        out_specs=(kspec2, kspec2),
        out_shape=(jax.ShapeDtypeStruct((b, h, tk, d), k.dtype),
                   jax.ShapeDtypeStruct((b, h, tk, d), v.dtype)),
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ----------------------------------------------------------------------
# custom_vjp wrapper (head-major) + the (B, T, H, D) public entry
# ----------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, bq, bk, interpret):
    out, _ = _fwd_call(q, k, v, causal, bq, bk, interpret)
    return out


def _flash_fwd(q, k, v, causal, bq, bk, interpret):
    out, lse = _fwd_call(q, k, v, causal, bq, bk, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, bq, bk, interpret, res, do):
    q, k, v, out, lse = res
    do = do.astype(q.dtype)
    return _bwd_call(q, k, v, out, lse, do, causal, bq, bk, interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal: bool = False,
                    block_q: int = BLOCK_Q, block_k: int = BLOCK_K,
                    dot_dtype=None, interpret: bool = False,
                    mesh=None, spec=None):
    """Fused flash attention: (B, T, H, D) → (B, T, H, D) f32.

    ``dot_dtype`` casts q/k/v (the tile-GEMM operand dtype — bf16 in
    the framework's mixed-precision mode); accumulation and softmax
    statistics are always f32.  Blocks must divide T (same contract as
    ``local_attention_blocked``).  Differentiable via the fused
    recompute backward — no (T, T) tensor ever reaches HBM in either
    direction.

    ``mesh``/``spec`` is the mesh-native path: ``spec`` is a boundary-
    layout (B, T, H, D) PartitionSpec (derive it with
    :func:`znicz_tpu.parallel.mesh.kernel_shard_spec`) and the kernel
    runs per-shard under ``shard_map`` — without it an opaque
    ``pallas_call`` has no GSPMD sharding rule, so a multi-device mesh
    would replicate-and-gather the operands onto every device.  Only
    batch-like dims may shard (batch over ``data``; heads compose with
    TP the same way); sharding T is the ring's job and is rejected
    here, as is sharding the head dim.  Gradients flow through the
    shard_map (the custom_vjp backward runs per-shard — attention is
    independent per batch element and head, so no cross-shard
    reduction exists).
    """
    b, t, h, d = q.shape
    tk = k.shape[1]
    bq, bk = min(block_q, t), min(block_k, tk)
    if t % bq or tk % bk:
        raise ValueError(f"T {t}/{tk} not divisible by blocks "
                         f"({bq}, {bk})")
    if dot_dtype is not None:
        q, k, v = (a.astype(dot_dtype) for a in (q, k, v))
    qh, kh, vh = (a.transpose(0, 2, 1, 3) for a in (q, k, v))
    if mesh is not None and spec is not None \
            and any(a is not None for a in spec):
        if spec[1] is not None or spec[3] is not None:
            raise ValueError(
                f"flash_attention shard spec {spec} shards T or the "
                f"head dim — only batch-like dims (batch, heads) may "
                f"shard; time sharding rides the ring path")
        from znicz_tpu.parallel.mesh import shard_map_unchecked
        from jax.sharding import PartitionSpec as P
        hspec = P(spec[0], spec[2], None, None)  # boundary → head-major
        fn = shard_map_unchecked(
            lambda a, b_, c: _flash(a, b_, c, causal, bq, bk,
                                    interpret),
            mesh, in_specs=(hspec, hspec, hspec), out_specs=hspec)
        out = fn(qh, kh, vh)
    else:
        out = _flash(qh, kh, vh, causal, bq, bk, interpret)
    return out.transpose(0, 2, 1, 3).astype(jnp.float32)
