"""Fused flash-attention Pallas TPU kernels (forward + backward).

Why this exists: the round-5 profile of the T=2048 sequence step
(PERF.md) shows the six attention-core GEMMs plus the softmax
reduction pinned at the HBM bandwidth roof (~660–800 GB/s, 11–50
TF/s) streaming (B, H, T, T) score/probability tensors — ~78% of the
step.  The XLA paths (plain einsum and the ``lax.scan`` blocked fold
in :mod:`znicz_tpu.parallel.ring_attention`) cannot avoid
materializing those tensors (plain) or the per-step carry round-trips
(scan).  A fused kernel keeps every (block_q, block_k) score tile in
VMEM: the only HBM traffic is q/k/v/o (+ per-row logsumexp), so the
core runs at MXU rate instead of bandwidth rate.

Design (the standard flash decomposition, implemented TPU-first):

- **forward**: grid (B, H, nq, nk), K-blocks innermost ("arbitrary"
  semantics — sequential per core); online-softmax state (running row
  max m, normalizer l, weighted accumulator) lives in VMEM scratch
  across the K iterations; the output block and the per-row
  logsumexp are written once at the last K block.
- **backward**: recompute-from-lse form — no (T, T) residual is ever
  stored.  Saves (q, k, v, o, lse) from the forward, precomputes
  ``delta = rowsum(do·o)`` (one cheap XLA pass), then two kernels:
  ``dq`` (grid over K blocks innermost, accumulating dq tiles) and
  ``dk/dv`` (grid over Q blocks innermost, accumulating dk/dv tiles);
  each recomputes the score tile p = exp(s − lse) in VMEM.
- **dtypes**: tile GEMMs run at the input dtype (bf16 in the
  framework's mixed-precision mode) with f32 accumulation via
  ``preferred_element_type``; softmax statistics, lse, delta and all
  accumulators are f32 — the same bf16-inputs/f32-accumulation
  convention as the rest of the repo.
- **causal**: global-position mask inside the tile (exact across
  block boundaries — same rule as ``ring_attention._visibility``);
  fully-masked tiles are skipped via ``pl.when``, so causal runs at
  ~2× effective rate.
- **global offsets** (round 6, the ring-fold composition): every
  kernel takes ``q_offset``/``k_offset`` scalars (SMEM) placing this
  call's q rows / k cols on the GLOBAL sequence axis, so one kernel
  invocation can be a single ring hop — the `pl.when` tile-skip then
  skips whole hops that sit entirely above the causal diagonal.
  Offsets are traced values (the ring derives them from
  ``axis_index``), which is why they ride SMEM instead of being
  Python constants.  With offsets, a hop can contain FULLY-MASKED
  rows (rows above the hop's first key) — the kernels guard those
  with explicit mask selects (forward p-tile and the backward
  recompute both) so the statistics degrade to (m=-inf, l=0) instead
  of exploding; such a hop contributes lse ≈ -1e30 and weight 0 to
  the cross-hop combination.
- **head packing** (round 6, ``pack=2``): pairs of dh=64 heads ride
  one kernel program as a (…, 128)-lane layout — q/k/v/o tiles carry
  both sub-heads side by side in the lane dim (full 128-lane VMEM
  loads/stores and element ops instead of half-width dh=64 tiles, the
  measured half-MXU bottleneck: MFU 0.25 at head_dim 64 vs 0.405 at
  128 — PERF.md round 5), while every GEMM and every softmax
  statistic stays per-sub-head (static lane slices), so the math is
  exactly per-head attention.  The pack happens as a free reshape at
  the (B, T, H, Dh) boundary (heads are adjacent to Dh there), never
  a model change.

Layout contract: (B, T, H, D) at the boundary (the unit-graph
convention); kernels run head-major (B, H, T, D) — the wrapper
transposes, which costs two cheap bandwidth passes versus the many
(T, T) passes saved.

Adoption is measured, not assumed: SEQ_BENCH.json / PERF.md round 5
carry the chip A/B against the plain and scan-blocked XLA forms (the
PALLAS_BENCH.md decision rule).  ``interpret=True`` runs the same
kernels on CPU for the oracle equality tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: jax renamed ``TPUCompilerParams`` → ``CompilerParams``; accept both
#: so the kernels run on 0.4.x and current jax alike
_COMPILER_PARAMS = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams

_NEG_INF = -1e30
#: default tile sizes — chip-swept (PERF.md round 5): 1024×1024 beats
#: 512×512 by ~1.2× (fewer grid revisits of the VMEM stats; the f32
#: score tile is 4 MB); 2048-wide tiles overflow VMEM and fail to
#: compile, so callers wanting other shapes pass block_q/block_k
BLOCK_Q = 1024
BLOCK_K = 1024
#: lane width for the per-row statistics arrays (lse, delta): the
#: minimum tile-legal last dim — the value is replicated across lanes
#: (with head packing, each sub-head owns one _LANES-wide lane group)
_LANES = 8
#: lane width of the f32 stats scratch (one VMEM tile row); sub-heads
#: split it into 128/pack-wide column groups
_STAT_LANES = 128


def _causal_mask(iq, ik, bq: int, bk: int, q_off, k_off):
    """(bq, bk) visibility tile from GLOBAL positions (rows
    q_off + iq·bq…, cols k_off + ik·bk…).  Offsets are traced int32
    scalars (0 outside the ring path)."""
    rows = q_off + iq * bq \
        + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    cols = k_off + ik * bk \
        + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return rows >= cols


def _dot(a, b, trans_a: bool = False, trans_b: bool = False):
    """MXU dot with f32 accumulation, contracting dims picked so no
    operand is materialized transposed."""
    dims = (((0,) if trans_a else (1,), (1,) if trans_b else (0,)),
            ((), ()))
    return jax.lax.dot_general(a, b, dims,
                               preferred_element_type=jnp.float32)


def _off_arr(v):
    """Offsets ride SMEM as (1, 1) int32 — accepts Python ints and
    traced scalars alike; None means 0."""
    if v is None:
        return jnp.zeros((1, 1), jnp.int32)
    return jnp.asarray(v, jnp.int32).reshape(1, 1)


def kernel_legal(t_q: int, t_k: int, dh: int, bq: int, bk: int) -> bool:
    """The kernel's tiling-legality gate (shared by the unit gate and
    the ring fold): blocks must tile T evenly and the head dim must be
    lane-legal (dh % 8 — e.g. dh=1 via a to_sequence net would crash
    Mosaic at trace instead of falling back; ADVICE round 5)."""
    return (t_q % bq == 0 and t_k % bk == 0
            and t_q % 8 == 0 and t_k % 8 == 0 and dh % 8 == 0)


def resolve_head_pack(flag, n_heads: int, dh: int) -> int:
    """Head-pack factor for the kernel call path: 2 when the
    ``engine.flash_head_pack`` gate is on and pairs of heads fit the
    128-lane tile (dh·2 ≤ 128, lane-legal, head count even) — else 1.
    A model change is never implied; packing is a kernel-boundary
    reshape."""
    if not flag:
        return 1
    if n_heads % 2 == 0 and dh % 8 == 0 and dh * 2 <= 128:
        return 2
    return 1


def causal_block_for(t: int, default_bq: int, default_bk: int,
                     min_block: int = 256):
    """Auto-pick causal blocks from grid depth (round-6 sweep,
    verdict item 3): at T=2048 the default 1024² tiles give a 2×2
    grid with ONE skippable tile, so causal ran at non-causal step
    time (MFU 0.167 vs 0.253).  Shrink blocks until the K-grid is at
    least 4 deep (≥ ~half the tiles skippable), floored at
    ``min_block`` (smaller tiles trade MXU efficiency for skip
    depth — the DMA/revisit floor the round-5 block sweep measured).
    Returns (block_q, block_k)."""
    bq, bk = min(default_bq, t), min(default_bk, t)
    while bk > min_block and t // bk < 4 and t % (bk // 2) == 0:
        bk //= 2
    while bq > min_block and t // bq < 4 and t % (bq // 2) == 0:
        bq //= 2
    return bq, bk


# ----------------------------------------------------------------------
# forward
# ----------------------------------------------------------------------
def _fwd_kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, causal, bq, bk, pack):
    iq, ik = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)
    q_off, k_off = qoff_ref[0, 0], koff_ref[0, 0]

    @pl.when(ik == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    visible = True if not causal \
        else q_off + iq * bq + bq - 1 >= k_off + ik * bk

    @pl.when(visible)
    def _fold():
        mask = (_causal_mask(iq, ik, bq, bk, q_off, k_off)
                if causal else None)
        q_all, k_all, v_all = q_ref[0, 0], k_ref[0, 0], v_ref[0, 0]
        d = q_all.shape[1]
        dh, sw = d // pack, _STAT_LANES // pack
        m_all, l_all, acc_all = m_scr[...], l_scr[...], acc_scr[...]
        m_out, l_out, acc_out = [], [], []
        for p in range(pack):           # static: per-sub-head math
            fs = slice(p * dh, (p + 1) * dh)
            s = _dot(q_all[:, fs], k_all[:, fs], trans_b=True) * scale
            if causal:
                s = jnp.where(mask, s, _NEG_INF)
            m_prev = m_all[:, p * sw:p * sw + 1]        # (bq, 1)
            m_new = jnp.maximum(m_prev,
                                jnp.max(s, axis=1, keepdims=True))
            pt = jnp.exp(s - m_new)
            if causal:
                # offset hops can hold FULLY-masked rows (m stays
                # -inf): exp(s - m) = exp(0) there without this guard
                pt = jnp.where(mask, pt, 0.0)
            corr = jnp.exp(m_prev - m_new)              # (bq, 1)
            l_out.append(jnp.broadcast_to(
                l_all[:, p * sw:p * sw + 1] * corr
                + jnp.sum(pt, axis=1, keepdims=True), (bq, sw)))
            acc_out.append(acc_all[:, fs] * corr
                           + _dot(pt.astype(v_all.dtype),
                                  v_all[:, fs]))
            m_out.append(jnp.broadcast_to(m_new, (bq, sw)))
        m_scr[...] = jnp.concatenate(m_out, axis=1)
        l_scr[...] = jnp.concatenate(l_out, axis=1)
        acc_scr[...] = jnp.concatenate(acc_out, axis=1)

    @pl.when(ik == nk - 1)
    def _finish():
        d = o_ref.shape[3]
        dh, sw = d // pack, _STAT_LANES // pack
        o_out, lse_out = [], []
        for p in range(pack):
            fs = slice(p * dh, (p + 1) * dh)
            l = jnp.maximum(l_scr[:, p * sw:p * sw + 1], 1e-30)
            o_out.append((acc_scr[:, fs] / l).astype(o_ref.dtype))
            # row stats ride _LANES lanes per sub-head (minimum
            # tile-legal lane width; the value repeats in every lane)
            lse_out.append(jnp.broadcast_to(
                m_scr[:, p * sw:p * sw + 1] + jnp.log(l),
                (bq, _LANES)))
        o_ref[0, 0] = jnp.concatenate(o_out, axis=1)
        lse_ref[0, 0] = jnp.concatenate(lse_out, axis=1)


def _fwd_call(q, k, v, q_off, k_off, causal, bq, bk, interpret, pack):
    b, h, t, d = q.shape
    tk = k.shape[2]
    nq, nk = t // bq, tk // bk
    kernel = functools.partial(_fwd_kernel,
                               scale=1.0 / np.sqrt(d // pack),
                               causal=causal, bq=bq, bk=bk, pack=pack)
    off_spec = pl.BlockSpec(memory_space=pltpu.SMEM)
    qspec = pl.BlockSpec((1, 1, bq, d), lambda b_, h_, iq, ik: (b_, h_, iq, 0))
    kspec = pl.BlockSpec((1, 1, bk, d), lambda b_, h_, iq, ik: (b_, h_, ik, 0))
    lanes = pack * _LANES
    return pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=[off_spec, off_spec, qspec, kspec, kspec],
        out_specs=(qspec,
                   pl.BlockSpec((1, 1, bq, lanes),
                                lambda b_, h_, iq, ik: (b_, h_, iq, 0))),
        out_shape=(jax.ShapeDtypeStruct((b, h, t, d), q.dtype),
                   jax.ShapeDtypeStruct((b, h, t, lanes), jnp.float32)),
        scratch_shapes=[pltpu.VMEM((bq, _STAT_LANES), jnp.float32),
                        pltpu.VMEM((bq, _STAT_LANES), jnp.float32),
                        pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q_off, k_off, q, k, v)


# ----------------------------------------------------------------------
# backward: dq kernel (K blocks innermost), dk/dv kernel (Q innermost)
# ----------------------------------------------------------------------
def _p_tile(q, k, lse_col, scale, mask):
    """Recompute one sub-head's probability tile p = exp(s − lse) in
    VMEM.  The mask select also guards fully-masked rows (offset
    hops): there lse ≈ -1e30 and the unmasked exp overflows."""
    s = _dot(q, k, trans_b=True) * scale
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    p = jnp.exp(s - lse_col)
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    return p


def _dq_kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref, do_ref,
               lse_ref, delta_ref, dq_ref, dq_scr, *, scale, causal,
               bq, bk, pack):
    iq, ik = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)
    q_off, k_off = qoff_ref[0, 0], koff_ref[0, 0]

    @pl.when(ik == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    visible = True if not causal \
        else q_off + iq * bq + bq - 1 >= k_off + ik * bk

    @pl.when(visible)
    def _fold():
        mask = (_causal_mask(iq, ik, bq, bk, q_off, k_off)
                if causal else None)
        q_all, k_all = q_ref[0, 0], k_ref[0, 0]
        v_all, do_all = v_ref[0, 0], do_ref[0, 0]
        dh = q_all.shape[1] // pack
        parts = []
        for p in range(pack):
            fs = slice(p * dh, (p + 1) * dh)
            ls = slice(p * _LANES, p * _LANES + 1)
            pt = _p_tile(q_all[:, fs], k_all[:, fs],
                         lse_ref[0, 0][:, ls], scale, mask)
            dp = _dot(do_all[:, fs], v_all[:, fs], trans_b=True)
            ds = pt * (dp - delta_ref[0, 0][:, ls]) * scale
            parts.append(_dot(ds.astype(k_all.dtype), k_all[:, fs]))
        dq_scr[...] += jnp.concatenate(parts, axis=1)

    @pl.when(ik == nk - 1)
    def _finish():
        dq_ref[0, 0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref, do_ref,
                lse_ref, delta_ref, dk_ref, dv_ref, dk_scr, dv_scr, *,
                scale, causal, bq, bk, pack):
    ik, iq = pl.program_id(2), pl.program_id(3)
    nq = pl.num_programs(3)
    q_off, k_off = qoff_ref[0, 0], koff_ref[0, 0]

    @pl.when(iq == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    visible = True if not causal \
        else q_off + iq * bq + bq - 1 >= k_off + ik * bk

    @pl.when(visible)
    def _fold():
        mask = (_causal_mask(iq, ik, bq, bk, q_off, k_off)
                if causal else None)
        q_all, k_all = q_ref[0, 0], k_ref[0, 0]
        v_all, do_all = v_ref[0, 0], do_ref[0, 0]
        dh = q_all.shape[1] // pack
        dk_parts, dv_parts = [], []
        for p in range(pack):
            fs = slice(p * dh, (p + 1) * dh)
            ls = slice(p * _LANES, p * _LANES + 1)
            pt = _p_tile(q_all[:, fs], k_all[:, fs],
                         lse_ref[0, 0][:, ls], scale, mask)
            do = do_all[:, fs]
            # dv += pᵀ · do ; contract the q dim without
            # materializing pᵀ
            dv_parts.append(_dot(pt.astype(do.dtype), do,
                                 trans_a=True))
            dp = _dot(do, v_all[:, fs], trans_b=True)
            ds = pt * (dp - delta_ref[0, 0][:, ls]) * scale
            dk_parts.append(_dot(ds.astype(q_all.dtype),
                                 q_all[:, fs], trans_a=True))
        dk_scr[...] += jnp.concatenate(dk_parts, axis=1)
        dv_scr[...] += jnp.concatenate(dv_parts, axis=1)

    @pl.when(iq == nq - 1)
    def _finish():
        dk_ref[0, 0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_call(q, k, v, lse, do, delta4, q_off, k_off, causal, bq, bk,
              interpret, pack):
    """``delta4``: (B, H, T, pack) f32 — rowsum(do·o) per SUB-head,
    already adjusted for any lse cotangent (the hop composition's
    extra term)."""
    b, h, t, d = q.shape
    tk = k.shape[2]
    nq, nk = t // bq, tk // bk
    lanes = pack * _LANES
    # per-sub-head delta rides _LANES lanes each, like lse
    delta = jnp.repeat(delta4, _LANES, axis=-1)      # (B, H, T, lanes)
    scale = 1.0 / np.sqrt(d // pack)
    off_spec = pl.BlockSpec(memory_space=pltpu.SMEM)
    qspec = pl.BlockSpec((1, 1, bq, d), lambda b_, h_, iq, ik: (b_, h_, iq, 0))
    kspec = pl.BlockSpec((1, 1, bk, d), lambda b_, h_, iq, ik: (b_, h_, ik, 0))
    rspec = pl.BlockSpec((1, 1, bq, lanes),
                         lambda b_, h_, iq, ik: (b_, h_, iq, 0))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, pack=pack),
        grid=(b, h, nq, nk),
        in_specs=[off_spec, off_spec, qspec, kspec, kspec, qspec,
                  rspec, rspec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((b, h, t, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q_off, k_off, q, k, v, do, lse, delta)
    # dk/dv: Q blocks innermost; the q-side specs index by the LAST
    # grid dim now, the k-side by dim 2
    qspec2 = pl.BlockSpec((1, 1, bq, d), lambda b_, h_, ik, iq: (b_, h_, iq, 0))
    kspec2 = pl.BlockSpec((1, 1, bk, d), lambda b_, h_, ik, iq: (b_, h_, ik, 0))
    rspec2 = pl.BlockSpec((1, 1, bq, lanes),
                          lambda b_, h_, ik, iq: (b_, h_, iq, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, pack=pack),
        grid=(b, h, nk, nq),
        in_specs=[off_spec, off_spec, qspec2, kspec2, kspec2, qspec2,
                  rspec2, rspec2],
        out_specs=(kspec2, kspec2),
        out_shape=(jax.ShapeDtypeStruct((b, h, tk, d), k.dtype),
                   jax.ShapeDtypeStruct((b, h, tk, d), v.dtype)),
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q_off, k_off, q, k, v, do, lse, delta)
    return dq, dk, dv


# ----------------------------------------------------------------------
# custom_vjp hop (head-major) + the (B, T, H, D) public entry
# ----------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash_hop(q, k, v, q_off, k_off, causal, bq, bk, interpret, pack):
    """One flash pass over head-major (packed) operands at global
    positions (q_off, k_off) → (out, lse).  This is BOTH the plain
    single-call kernel (offsets 0, lse discarded) and the per-hop
    ring fold (lse feeds the cross-hop online-softmax combination);
    the lse cotangent folds into delta in the backward, so one
    custom_vjp serves both."""
    return _fwd_call(q, k, v, q_off, k_off, causal, bq, bk, interpret,
                     pack)


def _hop_fwd(q, k, v, q_off, k_off, causal, bq, bk, interpret, pack):
    out, lse = _fwd_call(q, k, v, q_off, k_off, causal, bq, bk,
                         interpret, pack)
    return (out, lse), (q, k, v, out, lse, q_off, k_off)


def _hop_bwd(causal, bq, bk, interpret, pack, res, cts):
    q, k, v, out, lse, q_off, k_off = res
    do, dlse = cts
    do = do.astype(q.dtype)
    b, h, t, d = q.shape
    dh = d // pack
    # delta = rowsum(do·o) per sub-head; the lse cotangent (hop
    # composition) enters the score gradient as ds += p·dlse, i.e.
    # delta -= dlse (lanes are value copies → group-sum them)
    delta4 = jnp.sum(
        (do.astype(jnp.float32) * out.astype(jnp.float32))
        .reshape(b, h, t, pack, dh), axis=-1)
    delta4 = delta4 - dlse.astype(jnp.float32) \
        .reshape(b, h, t, pack, _LANES).sum(axis=-1)
    dq, dk, dv = _bwd_call(q, k, v, lse, do, delta4, q_off, k_off,
                           causal, bq, bk, interpret, pack)
    zero = np.zeros((1, 1), jax.dtypes.float0)
    return dq, dk, dv, zero, zero


_flash_hop.defvjp(_hop_fwd, _hop_bwd)


def ring_hop(qh, kh, vh, q_offset, k_offset, causal: bool,
             block_q: int, block_k: int, interpret: bool = False,
             pack: int = 1):
    """One ring hop on head-major, already-packed operands
    (B, Hp, T, pack·dh): returns (out in qh.dtype, lse (B, Hp, T,
    pack) f32).  Offsets may be traced scalars (``axis_index``
    arithmetic under shard_map)."""
    out, lse = _flash_hop(qh, kh, vh, _off_arr(q_offset),
                          _off_arr(k_offset), causal, block_q,
                          block_k, interpret, pack)
    return out, lse[..., ::_LANES]


def pack_heads(x, pack: int):
    """(B, T, H, dh) boundary layout → head-major packed
    (B, H//pack, T, pack·dh).  Heads are adjacent to dh at the
    boundary, so the pack itself is a free reshape; the transpose is
    the same bandwidth pass the unpacked path already pays."""
    b, t, h, dh = x.shape
    return x.reshape(b, t, h // pack, pack * dh).transpose(0, 2, 1, 3)


def unpack_heads(x, pack: int, n_heads: int):
    """Inverse of :func:`pack_heads`: (B, Hp, T, pack·dh) →
    (B, T, H, dh)."""
    b, hp, t, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b, t, n_heads, d // pack)


def flash_attention(q, k, v, causal: bool = False,
                    block_q: int = BLOCK_Q, block_k: int = BLOCK_K,
                    dot_dtype=None, interpret: bool = False,
                    mesh=None, spec=None, q_offset=None, k_offset=None,
                    head_pack: int = 1):
    """Fused flash attention: (B, T, H, D) → (B, T, H, D) f32.

    ``dot_dtype`` casts q/k/v (the tile-GEMM operand dtype — bf16 in
    the framework's mixed-precision mode); accumulation and softmax
    statistics are always f32.  Blocks must divide T (same contract as
    ``local_attention_blocked``).  Differentiable via the fused
    recompute backward — no (T, T) tensor ever reaches HBM in either
    direction.

    ``q_offset``/``k_offset`` place this call on the GLOBAL sequence
    axis for causal masking (the ring-hop geometry; may be traced
    scalars).  ``head_pack=2`` folds head pairs into 128-lane tiles
    (see the module docstring) — exact per-head math, resolved by the
    unit gate via :func:`resolve_head_pack`.

    ``mesh``/``spec`` is the mesh-native path: ``spec`` is a boundary-
    layout (B, T, H, D) PartitionSpec (derive it with
    :func:`znicz_tpu.parallel.mesh.kernel_shard_spec`) and the kernel
    runs per-shard under ``shard_map`` — without it an opaque
    ``pallas_call`` has no GSPMD sharding rule, so a multi-device mesh
    would replicate-and-gather the operands onto every device.  Only
    batch-like dims may shard (batch over ``data``; heads compose with
    TP the same way); sharding T is the ring's job and is rejected
    here, as is sharding the head dim.  Gradients flow through the
    shard_map (the custom_vjp backward runs per-shard — attention is
    independent per batch element and head, so no cross-shard
    reduction exists).
    """
    b, t, h, d = q.shape
    tk = k.shape[1]
    pack = int(head_pack) if head_pack else 1
    if pack > 1 and h % pack:
        raise ValueError(f"head_pack {pack} does not divide "
                         f"{h} heads")
    bq, bk = min(block_q, t), min(block_k, tk)
    if t % bq or tk % bk:
        raise ValueError(f"T {t}/{tk} not divisible by blocks "
                         f"({bq}, {bk})")
    if dot_dtype is not None:
        q, k, v = (a.astype(dot_dtype) for a in (q, k, v))
    qh, kh, vh = (pack_heads(a, pack) for a in (q, k, v))
    if mesh is not None and spec is not None \
            and any(a is not None for a in spec):
        if spec[1] is not None or spec[3] is not None:
            raise ValueError(
                f"flash_attention shard spec {spec} shards T or the "
                f"head dim — only batch-like dims (batch, heads) may "
                f"shard; time sharding rides the ring path")
        if q_offset is not None or k_offset is not None:
            raise ValueError(
                "global offsets ride the ring path (per-shard hops), "
                "not the batch-sharded shard_map path")
        from znicz_tpu.parallel.mesh import shard_map_unchecked
        from jax.sharding import PartitionSpec as P
        hspec = P(spec[0], spec[2], None, None)  # boundary → head-major
        fn = shard_map_unchecked(
            lambda a, b_, c: _flash_hop(
                a, b_, c, _off_arr(None), _off_arr(None), causal, bq,
                bk, interpret, pack)[0],
            mesh, in_specs=(hspec, hspec, hspec), out_specs=hspec)
        out = fn(qh, kh, vh)
    else:
        out = _flash_hop(qh, kh, vh, _off_arr(q_offset),
                         _off_arr(k_offset), causal, bq, bk,
                         interpret, pack)[0]
    return unpack_heads(out, pack, h).astype(jnp.float32)
