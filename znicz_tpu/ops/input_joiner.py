"""InputJoiner (reference: ``veles/input_joiner.py``).

Concatenates several units' outputs into one ``(batch, Σ features)``
Vector — the reference used a small OpenCL copy kernel per input; here
it is one ``jnp.concatenate`` the jit region fuses away.

Wiring: ``join.link_inputs(a, b, ...)`` aliases each source's
``output`` Vector; a paired :class:`GDInputJoiner` splits the error
back by the recorded offsets.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from znicz_tpu.memory import Vector
from znicz_tpu.ops.nn_units import Forward, WeightlessGradientUnit


class InputJoiner(Forward):
    def __init__(self, workflow, name=None, **kwargs) -> None:
        super().__init__(workflow, name=name, **kwargs)
        self.inputs: list[Vector] = []
        self.offsets: list[int] = []

    def link_inputs(self, *units) -> "InputJoiner":
        for unit in units:
            self.inputs.append(unit.output)
        return self

    def initialize(self, device=None, **kwargs) -> None:
        super().initialize(device=device, **kwargs)
        if not self.inputs:
            raise AttributeError(f"{self}: no inputs linked")
        for vec in self.inputs:
            if not vec:
                raise AttributeError(f"{self}: input '{vec.name}' "
                                     f"not allocated yet")
        n = self.inputs[0].shape[0]
        sizes = []
        for vec in self.inputs:
            if vec.shape[0] != n:
                raise ValueError(f"{self}: batch mismatch")
            sizes.append(vec.sample_size)
        self.offsets = list(np.cumsum([0] + sizes))
        self.output.reset(np.zeros((n, self.offsets[-1]),
                                   dtype=np.float32))
        self.init_vectors(self.output, *self.inputs)

    def region_vectors(self) -> list[Vector]:
        # the inputs list is invisible to the default __dict__ scan
        vecs = super().region_vectors()
        seen = {id(v) for v in vecs}
        for vec in self.inputs:
            if id(vec) not in seen:
                vecs.append(vec)
        return vecs

    def numpy_run(self) -> None:
        n = self.inputs[0].shape[0]
        self.output.map_invalidate()
        parts = []
        for vec in self.inputs:
            vec.map_read()
            parts.append(vec.mem.reshape(n, -1))
        self.output.mem[...] = np.concatenate(parts, axis=1)

    def xla_run(self) -> None:
        n = self.inputs[0].shape[0]
        self.output.devmem = jnp.concatenate(
            [vec.devmem.reshape(n, -1) for vec in self.inputs], axis=1)


class GDInputJoiner(WeightlessGradientUnit):
    """Split the joined error back into per-source pieces
    (``err_inputs[i]`` matches ``forward_unit.inputs[i]``)."""

    MATCHES = (InputJoiner,)
    REQUIRES_INPUT = False  # fans the error out to err_inputs instead

    def __init__(self, workflow, name=None, **kwargs):
        super().__init__(workflow, name=name, **kwargs)
        self.err_inputs: list[Vector] = []

    def initialize(self, device=None, **kwargs) -> None:
        fwd = self.forward_unit
        if fwd is not None and not fwd.inputs:
            raise AttributeError(f"{self}: forward_unit has no inputs yet")
        super().initialize(device=device, **kwargs)
        if fwd is not None and not self.err_inputs:
            # post-super: dtype follows the activation storage policy
            self.err_inputs = [
                Vector(np.zeros(vec.shape, dtype=self.act_store_dtype),
                       name=f"{self.name}.err_input{i}", batch_major=True)
                for i, vec in enumerate(fwd.inputs)]
        self.init_vectors(*self.err_inputs)

    def region_vectors(self) -> list[Vector]:
        vecs = super().region_vectors()
        seen = {id(v) for v in vecs}
        for vec in self.err_inputs:
            if id(vec) not in seen:
                vecs.append(vec)
        return vecs

    def numpy_run(self) -> None:
        fwd = self.forward_unit
        self.err_output.map_read()
        err = self.err_output.mem
        for vec, lo, hi in zip(self.err_inputs, fwd.offsets,
                               fwd.offsets[1:]):
            vec.map_invalidate()
            vec.mem[...] = err[:, lo:hi].reshape(vec.shape)

    def xla_run(self) -> None:
        fwd = self.forward_unit
        err = self.err_output.devmem
        for vec, lo, hi in zip(self.err_inputs, fwd.offsets,
                               fwd.offsets[1:]):
            vec.devmem = err[:, lo:hi].reshape(vec.shape)
