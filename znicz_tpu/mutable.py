"""Shared mutable flags and linkable attributes.

Rebuilds the reference's gating primitives (reference:
``veles/mutable.py``): units gate on :class:`Bool` objects that other
units mutate, and lazily-derived booleans (``~a``, ``a & b``, ``a | b``)
let a gate follow another flag without copying it.

These are **host-side control-plane** objects: they decide which units
run between device steps.  Per-minibatch conditions that must live
*inside* a jit region are handled separately (static region keys or
``lax.cond`` — see :mod:`znicz_tpu.accelerated_units`).
"""

from __future__ import annotations

from typing import Callable


class Bool:
    """A shared mutable boolean.

    Units hold *references* to the same ``Bool`` so one unit flipping it
    (``flag << True``) is observed by every gate that watches it.
    Deriving (``~a``, ``a & b``, ``a | b``) produces a live view that
    re-evaluates on every read.
    """

    __slots__ = ("_value", "_expr", "on_true")

    def __init__(self, value: bool = False) -> None:
        self._value = bool(value)
        self._expr: Callable[[], bool] | None = None
        #: optional callbacks fired when the flag transitions to True
        self.on_true: list[Callable[[], None]] = []

    @classmethod
    def _derived(cls, expr: Callable[[], bool]) -> "Bool":
        b = cls()
        b._expr = expr
        return b

    @property
    def value(self) -> bool:
        if self._expr is not None:
            return self._expr()
        return self._value

    @value.setter
    def value(self, v: bool) -> None:
        if self._expr is not None:
            raise ValueError("cannot assign to a derived Bool")
        was = self._value
        self._value = bool(v)
        if self._value and not was:
            for cb in self.on_true:
                cb()

    def __lshift__(self, v: bool) -> "Bool":
        """``flag << True`` — in-place assignment that reads naturally
        at call sites (the reference used ``<<=``)."""
        self.value = v
        return self

    def __bool__(self) -> bool:
        return self.value

    def __invert__(self) -> "Bool":
        return Bool._derived(lambda: not self.value)

    def __and__(self, other: "Bool") -> "Bool":
        return Bool._derived(lambda: self.value and bool(other))

    def __or__(self, other: "Bool") -> "Bool":
        return Bool._derived(lambda: self.value or bool(other))

    def __repr__(self) -> str:
        kind = "derived" if self._expr is not None else "plain"
        return f"Bool({self.value}, {kind})"


class LinkableAttribute:
    """Descriptor record for an attribute aliased from another object.

    ``b.link_attrs(a, ("input", "output"))`` makes ``b.input`` a live
    alias of ``a.output``: reads and writes on ``b.input`` go to ``a``.
    Stored in the owner's ``_linked_attrs`` table; resolution happens in
    :meth:`znicz_tpu.units.Unit.__getattr__` / ``__setattr__``.
    """

    __slots__ = ("source", "source_name", "two_way")

    def __init__(self, source: object, source_name: str,
                 two_way: bool = True) -> None:
        self.source = source
        self.source_name = source_name
        self.two_way = two_way

    def get(self):
        return getattr(self.source, self.source_name)

    def set(self, value) -> None:
        if not self.two_way:
            raise AttributeError(
                f"attribute is linked one-way from "
                f"{type(self.source).__name__}.{self.source_name}")
        setattr(self.source, self.source_name, value)
