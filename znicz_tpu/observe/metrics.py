"""Process-local metrics registry: counters, gauges, histograms.

The reference framework's observability was live *introspection* —
plotters and the web status page read whatever attributes a workflow
happened to expose (``veles/web_status.py``).  This module is the
modern equivalent's measurement half: a thread-safe, process-local
registry of named metric families in the Prometheus data model

- **counter** — monotone accumulator (``znicz_xla_compiles_total``),
- **gauge** — set-to-current value (``znicz_serving_queue_rows``),
- **histogram** — fixed-bucket distribution with cumulative
  ``le``-bucket counts (``znicz_unit_run_seconds``),

each optionally split by a small, fixed set of labels.  Two
expositions: :meth:`MetricsRegistry.to_prometheus` (text format 0.0.4,
what ``WebStatusServer`` serves at ``/metrics``) and
:meth:`MetricsRegistry.to_json` (the machine-readable feed).

Design constraints, in order:

1. **Near-zero overhead when telemetry is off** — every hot-path
   instrumentation site checks :func:`enabled`
   (``root.common.engine.telemetry``, default on) before doing any
   work; a disabled gate costs one dict lookup.
2. **Thread safety** — the serving scheduler thread, the web-status
   handler threads and the training loop all touch the registry; one
   registry-level lock guards family creation and every child update
   (contention is negligible: host-side events are O(kHz)).
3. **Bounded cardinality** — labels are unit/bucket/direction-shaped
   (dozens of children), never per-request.

Canonical series used across the framework live here as helper
constructors (:func:`xla_compiles`, :func:`unit_run_seconds`, …) so
instrumentation sites and tests agree on names by construction.
"""

from __future__ import annotations

import bisect
import math
import threading
from collections import OrderedDict
from typing import Callable, Iterable

from znicz_tpu.utils.config import root


def enabled() -> bool:
    """The telemetry master gate: ``root.common.engine.telemetry``
    (default on).  Hot-path instrumentation (per-unit spans/timing,
    transfer byte counts) short-circuits on this; rare-event counters
    (compiles, snapshots) and the serving engine's own stats are
    always recorded — they are functional state, not overhead."""
    return bool(root.common.engine.get("telemetry", True))


#: default histogram bounds (seconds): log-ish ladder from 0.1 ms to
#: 30 s — covers unit fires, serve latencies and snapshot writes
DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


def _fmt(value: float) -> str:
    """Prometheus sample-value formatting: integral floats print as
    integers, +Inf spelled the Prometheus way."""
    if value == math.inf:
        return "+Inf"
    if value == -math.inf:
        return "-Inf"
    f = float(value)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_label(value: str) -> str:
    return (str(value).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


class Counter:
    """Monotone accumulator child."""

    __slots__ = ("_lock", "_value")

    def __init__(self, lock: threading.RLock) -> None:
        self._lock = lock
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter increment must be >= 0, "
                             f"got {amount}")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Set-to-current child.  ``set_function`` turns it into a
    callback gauge read at collect time (live queue depths)."""

    __slots__ = ("_lock", "_value", "_fn")

    def __init__(self, lock: threading.RLock) -> None:
        self._lock = lock
        self._value = 0.0
        self._fn: Callable[[], float] | None = None

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)
            self._fn = None

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    def set_function(self, fn: Callable[[], float]) -> None:
        with self._lock:
            self._fn = fn

    @property
    def value(self) -> float:
        fn = self._fn
        if fn is not None:
            try:
                return float(fn())
            except Exception:  # noqa: BLE001 — a dead callback reads 0
                return 0.0
        return self._value


class Histogram:
    """Fixed-bucket distribution child with Prometheus ``le``
    semantics (cumulative counts of observations <= bound)."""

    __slots__ = ("_lock", "bounds", "counts", "sum", "count", "_max")

    def __init__(self, lock: threading.RLock,
                 bounds: tuple[float, ...]) -> None:
        self._lock = lock
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)  # +1: the +Inf bucket
        self.sum = 0.0
        self.count = 0
        self._max = -math.inf

    def observe(self, value: float) -> None:
        value = float(value)
        idx = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self.counts[idx] += 1
            self.sum += value
            self.count += 1
            if value > self._max:
                self._max = value

    def percentile(self, q: float) -> float:
        """Bucket-interpolated percentile estimate (error bounded by
        the width of the bucket the true quantile lands in — the
        classic Prometheus ``histogram_quantile`` math)."""
        with self._lock:
            total = self.count
            if not total:
                return 0.0
            rank = q / 100.0 * total
            cum = 0
            for i, n in enumerate(self.counts):
                if not n:
                    continue
                lo_cum = cum
                cum += n
                if cum >= rank:
                    lo = self.bounds[i - 1] if i > 0 else 0.0
                    hi = (self.bounds[i] if i < len(self.bounds)
                          else max(self._max, lo))
                    frac = (rank - lo_cum) / n
                    return lo + (hi - lo) * frac
            return max(self._max, 0.0)


class MetricFamily:
    """One named metric + its labeled children."""

    KINDS = ("counter", "gauge", "histogram")
    _CHILD = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}

    def __init__(self, name: str, kind: str, help_: str,
                 labelnames: tuple[str, ...],
                 lock: threading.RLock,
                 buckets: tuple[float, ...] = DEFAULT_BUCKETS) -> None:
        if kind not in self.KINDS:
            raise ValueError(f"unknown metric kind '{kind}'")
        self.name = name
        self.kind = kind
        self.help = help_
        self.labelnames = tuple(labelnames)
        self.buckets = tuple(sorted(buckets))
        self._lock = lock
        self._children: "OrderedDict[tuple, object]" = OrderedDict()

    def labels(self, **labelvalues):
        """The child for this label combination, created on first
        use.  Label names must match the family declaration exactly."""
        if tuple(sorted(labelvalues)) != tuple(sorted(self.labelnames)):
            raise ValueError(
                f"metric '{self.name}' declares labels "
                f"{self.labelnames}, got {tuple(sorted(labelvalues))}")
        key = tuple(str(labelvalues[n]) for n in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                if self.kind == "histogram":
                    child = Histogram(self._lock, self.buckets)
                else:
                    child = self._CHILD[self.kind](self._lock)
                self._children[key] = child
            return child

    # label-less convenience: the family IS its single child ---------
    def _solo(self):
        if self.labelnames:
            raise ValueError(
                f"metric '{self.name}' has labels {self.labelnames} — "
                f"address a child via .labels(...)")
        return self.labels()

    def inc(self, amount: float = 1.0) -> None:
        self._solo().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._solo().dec(amount)

    def set_function(self, fn: Callable[[], float]) -> None:
        self._solo().set_function(fn)

    def set(self, value: float) -> None:
        self._solo().set(value)

    def observe(self, value: float) -> None:
        self._solo().observe(value)

    @property
    def value(self) -> float:
        return self._solo().value

    def items(self) -> list[tuple[tuple, object]]:
        with self._lock:
            return list(self._children.items())


class MetricsRegistry:
    """Thread-safe, process-local registry of metric families."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._families: "OrderedDict[str, MetricFamily]" = OrderedDict()

    # ------------------------------------------------------------------
    # declaration (idempotent: re-declaring the same family returns it)
    # ------------------------------------------------------------------
    def _declare(self, name: str, kind: str, help_: str,
                 labels: Iterable[str],
                 buckets: tuple[float, ...] = DEFAULT_BUCKETS
                 ) -> MetricFamily:
        labels = tuple(labels)
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.labelnames != labels:
                    raise ValueError(
                        f"metric '{name}' already registered as "
                        f"{fam.kind}{fam.labelnames}, cannot re-declare "
                        f"as {kind}{labels}")
                return fam
            fam = MetricFamily(name, kind, help_, labels, self._lock,
                               buckets=buckets)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help_: str = "",
                labels: Iterable[str] = ()) -> MetricFamily:
        return self._declare(name, "counter", help_, labels)

    def gauge(self, name: str, help_: str = "",
              labels: Iterable[str] = ()) -> MetricFamily:
        return self._declare(name, "gauge", help_, labels)

    def histogram(self, name: str, help_: str = "",
                  labels: Iterable[str] = (),
                  buckets: tuple[float, ...] = DEFAULT_BUCKETS
                  ) -> MetricFamily:
        return self._declare(name, "histogram", help_, labels,
                             buckets=buckets)

    def get(self, name: str) -> MetricFamily | None:
        with self._lock:
            return self._families.get(name)

    def clear(self) -> None:
        """Drop every family (tests)."""
        with self._lock:
            self._families.clear()

    # ------------------------------------------------------------------
    # exposition
    # ------------------------------------------------------------------
    def to_json(self) -> dict:
        out: dict = {}
        with self._lock:
            families = list(self._families.values())
        for fam in families:
            rows = []
            for key, child in fam.items():
                labels = dict(zip(fam.labelnames, key))
                if fam.kind == "histogram":
                    rows.append({
                        "labels": labels,
                        "buckets": {_fmt(b): c for b, c in zip(
                            fam.buckets + (math.inf,), child.counts)},
                        "sum": child.sum, "count": child.count})
                else:
                    rows.append({"labels": labels,
                                 "value": child.value})
            out[fam.name] = {"type": fam.kind, "help": fam.help,
                             "values": rows}
        return out

    def to_prometheus(self) -> str:
        """Text exposition format 0.0.4."""
        lines: list[str] = []
        with self._lock:
            families = list(self._families.values())
        for fam in families:
            if fam.help:
                lines.append(f"# HELP {fam.name} {fam.help}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            for key, child in fam.items():
                pairs = [f'{n}="{_escape_label(v)}"'
                         for n, v in zip(fam.labelnames, key)]
                base = ",".join(pairs)
                if fam.kind == "histogram":
                    cum = 0
                    for bound, n in zip(fam.buckets + (math.inf,),
                                        child.counts):
                        cum += n
                        le = ([f'le="{_fmt(bound)}"'] if not base
                              else pairs + [f'le="{_fmt(bound)}"'])
                        lines.append(
                            f"{fam.name}_bucket{{{','.join(le)}}} {cum}")
                    suffix = f"{{{base}}}" if base else ""
                    lines.append(
                        f"{fam.name}_sum{suffix} {_fmt(child.sum)}")
                    lines.append(
                        f"{fam.name}_count{suffix} {child.count}")
                else:
                    suffix = f"{{{base}}}" if base else ""
                    lines.append(
                        f"{fam.name}{suffix} {_fmt(child.value)}")
        return "\n".join(lines) + ("\n" if lines else "")


#: the process-global registry every framework series registers on
REGISTRY = MetricsRegistry()


# ----------------------------------------------------------------------
# canonical framework series — single home for the names so
# instrumentation sites, the dryrun attestation and the tests agree
# ----------------------------------------------------------------------
def xla_compiles(site: str) -> Counter:
    """XLA trace+compile events: jit-region variants, scan chunks and
    serving AOT programs, labeled by site.  The steady-state retrace
    guard asserts this stays flat on warmed paths."""
    return REGISTRY.counter(
        "znicz_xla_compiles_total",
        "XLA program compiles (jit-region variants, scan chunks, "
        "serving AOT buckets)", labels=("site",)).labels(site=site)


def aot_cache_events(site: str, outcome: str) -> Counter:
    """Persisted-AOT-cache verdicts by compile site: ``hit`` (an
    executable deserialized instead of compiled — must NOT move
    :func:`xla_compiles`), ``miss`` (no entry; the site traced as it
    always did) and ``corrupt`` (digest/deserialize failure — entry
    quarantined, site fell back to tracing, paired with a
    ``recoveries{kind="aotcache_fallback"}`` increment).  The coldstart
    bench asserts ``hit>0`` with ``znicz_xla_compiles_total`` flat on
    its warm arm."""
    return REGISTRY.counter(
        "znicz_aot_cache_total",
        "Persisted AOT executable cache lookups by site and outcome "
        "(hit=deserialized, miss=traced, corrupt=quarantined+traced)",
        labels=("site", "outcome")).labels(site=site, outcome=outcome)


def aot_cache_bytes(cache: str = "local") -> Gauge:
    """Resident bytes of the persisted AOT executable store (payloads
    only; sidecars/metadata excluded).  Bounded by
    ``engine.aot_cache_bytes`` — the store evicts oldest-first past
    it."""
    return REGISTRY.gauge(
        "znicz_aot_cache_bytes",
        "Bytes of serialized executables resident in the AOT cache",
        labels=("cache",)).labels(cache=cache)


def unit_run_seconds(unit: str) -> Histogram:
    """Per-unit ``run()`` wall time (host control plane)."""
    return REGISTRY.histogram(
        "znicz_unit_run_seconds",
        "Unit.run wall time by unit name",
        labels=("unit",)).labels(unit=unit)


def transfer_bytes(direction: str) -> Counter:
    """Host<->device transfer volume through the Vector map/unmap
    protocol (``h2d`` uploads, ``d2h`` fetches)."""
    return REGISTRY.counter(
        "znicz_device_transfer_bytes_total",
        "Vector host<->device transfer bytes by direction",
        labels=("direction",)).labels(direction=direction)


def input_wait_seconds(loader: str) -> Histogram:
    """Host time a training step spent BLOCKED on the input pipeline
    (prefetch miss, empty prefetch queue).  A fully hidden input plane
    keeps this ≈ 0 while :func:`input_stage_seconds` keeps accruing —
    the ratio of the two sums is the input-overlap attestation the
    dryrun and ``stream_bench`` report as ``input_hidden``."""
    return REGISTRY.histogram(
        "znicz_input_wait_seconds",
        "Step time blocked waiting for the input pipeline",
        labels=("loader",)).labels(loader=loader)


def input_stage_seconds(loader: str) -> Histogram:
    """Producer-side cost of one minibatch (shard read/decode +
    staging) — the work the prefetch must hide under the device
    step."""
    return REGISTRY.histogram(
        "znicz_input_stage_seconds",
        "Producer time to read+stage one minibatch",
        labels=("loader",)).labels(loader=loader)


def prefetch_depth(loader: str) -> Gauge:
    """Configured prefetch depth (in-flight device batches) of a
    streaming/double-buffered loader."""
    return REGISTRY.gauge(
        "znicz_prefetch_depth",
        "Loader prefetch depth (0 = synchronous input)",
        labels=("loader",)).labels(loader=loader)


def loader_prefetch(loader: str, event: str) -> Counter:
    """Loader prefetch lifecycle counters: ``hit`` (step served from
    an in-flight prefetch), ``miss`` (synchronous fallback),
    ``epoch_cross`` (prefetch legally spanned an epoch boundary via
    the counter-based shuffle — each one is a recovered stall)."""
    return REGISTRY.counter(
        "znicz_loader_prefetch_total",
        "Loader prefetch events (hit/miss/epoch_cross)",
        labels=("loader", "event")).labels(loader=loader, event=event)


def partition_rules(workflow: str) -> Gauge:
    """Size of a workflow's declarative partition-rule table (unit
    overrides + default tail) — the dryrun tail attests
    ``partition=rules, specs=N`` from this pair of gauges."""
    return REGISTRY.gauge(
        "znicz_partition_rules",
        "Partition-rule table size (overrides + default tail)",
        labels=("workflow",)).labels(workflow=workflow)


def partition_leaves(workflow: str) -> Gauge:
    """Vector leaves bound (resolved) through a workflow's partition
    table — every placed buffer the rule engine decided."""
    return REGISTRY.gauge(
        "znicz_partition_leaves",
        "Vector leaves resolved through the partition-rule table",
        labels=("workflow",)).labels(workflow=workflow)


def pipeline_stages(workflow: str) -> Gauge:
    """Pipeline-parallel stage count K the workflow's unit chain was
    split into (round 20) — 0/absent means unstaged execution."""
    return REGISTRY.gauge(
        "znicz_pipeline_stages",
        "Pipeline-parallel stages the forward/backward chain spans",
        labels=("workflow",)).labels(workflow=workflow)


def pipeline_bubble_seconds(workflow: str) -> Counter:
    """Cumulative pipeline bubble time: per optimizer step, the sum
    over stages of (schedule makespan − that stage's busy time).  With
    the 1F1B schedule the steady-state fraction is (K−1)/(M+K−1);
    divide by wall time to read the realized fraction from /metrics."""
    return REGISTRY.counter(
        "znicz_pipeline_bubble_seconds_total",
        "Stage idle (bubble) seconds summed over pipeline stages",
        labels=("workflow",)).labels(workflow=workflow)


def grad_accum_microbatches(workflow: str) -> Gauge:
    """Microbatches accumulated on device per optimizer step
    (``engine.grad_accum``; round 20) — 1 means fused batches."""
    return REGISTRY.gauge(
        "znicz_grad_accum_microbatches",
        "Gradient-accumulation microbatches per optimizer step",
        labels=("workflow",)).labels(workflow=workflow)


def snapshot_seconds(op: str) -> Histogram:
    return REGISTRY.histogram(
        "znicz_snapshot_seconds",
        "Snapshot state-tree save/load duration",
        labels=("op",)).labels(op=op)


def epochs_total(workflow: str) -> Counter:
    return REGISTRY.counter(
        "znicz_epochs_total", "Training epochs completed",
        labels=("workflow",)).labels(workflow=workflow)


def region_steps(region: str) -> Counter:
    return REGISTRY.counter(
        "znicz_region_steps_total",
        "Jit-region device steps dispatched (scan chunks count each "
        "inner step)", labels=("region",)).labels(region=region)


def backend_info(backend: str, platform: str) -> Gauge:
    return REGISTRY.gauge(
        "znicz_backend_info",
        "Active device backend (value is always 1; read the labels)",
        labels=("backend", "platform")).labels(
            backend=backend, platform=platform)


def serving_requests(engine: str, event: str) -> Counter:
    return REGISTRY.counter(
        "znicz_serving_requests_total",
        "Serving requests by lifecycle event "
        "(submitted/served/rejected)",
        labels=("engine", "event")).labels(engine=engine, event=event)


def serving_latency_seconds(engine: str) -> Histogram:
    return REGISTRY.histogram(
        "znicz_serving_latency_seconds",
        "Serving enqueue->reply latency",
        labels=("engine",)).labels(engine=engine)


def serving_queue_rows(engine: str) -> Gauge:
    return REGISTRY.gauge(
        "znicz_serving_queue_rows",
        "Rows pending in the continuous batcher's bounded queue",
        labels=("engine",)).labels(engine=engine)


def serving_bucket_batches(engine: str, bucket: int) -> Counter:
    return REGISTRY.counter(
        "znicz_serving_bucket_batches_total",
        "Coalesced batches dispatched per bucket size",
        labels=("engine", "bucket")).labels(engine=engine,
                                            bucket=bucket)


def serving_bucket_rows(engine: str, bucket: int) -> Counter:
    return REGISTRY.counter(
        "znicz_serving_bucket_rows_total",
        "Real (non-padded) rows served per bucket size",
        labels=("engine", "bucket")).labels(engine=engine,
                                            bucket=bucket)


def serving_ttft_seconds(engine: str) -> Histogram:
    """Time-to-first-token: prompt submit → first generated token
    available (queue wait + prefill + first sample).  The interactive
    half of decode latency — kept as its OWN canonical series beside
    :func:`serving_token_seconds` because the two move independently
    (admission policy moves TTFT, cache locality moves per-token)."""
    return REGISTRY.histogram(
        "znicz_serving_ttft_seconds",
        "Decode time-to-first-token (submit -> first token)",
        labels=("engine",)).labels(engine=engine)


def serving_token_seconds(engine: str) -> Histogram:
    """Per-token decode latency: one observation per generated token
    after the first (the steady-state token cadence a streaming client
    sees)."""
    return REGISTRY.histogram(
        "znicz_serving_token_seconds",
        "Decode per-token latency (inter-token cadence after the "
        "first token)", labels=("engine",)).labels(engine=engine)


def serving_tokens(engine: str, kind: str) -> Counter:
    """Token throughput counters: ``prompt`` (prefilled positions)
    vs ``generated`` (sampled tokens) — tokens/s on a dashboard is
    ``rate(generated)``."""
    return REGISTRY.counter(
        "znicz_serving_tokens_total",
        "Decode tokens by kind (prompt=prefilled, generated=sampled)",
        labels=("engine", "kind")).labels(engine=engine, kind=kind)


def serving_decode_slots(engine: str) -> Gauge:
    """Live decode slots (sequences mid-generation) — occupancy of
    the preallocated KV-cache pages."""
    return REGISTRY.gauge(
        "znicz_serving_decode_slots",
        "Sequences currently occupying KV-cache decode slots",
        labels=("engine",)).labels(engine=engine)


def kv_pages_total(engine: str) -> Gauge:
    """Pages in the decode engine's paged KV pool (fixed at start —
    the token-capacity bound: ``pages × kv_page_tokens`` tokens)."""
    return REGISTRY.gauge(
        "znicz_kv_pages_total",
        "KV-cache pages in the paged decode pool",
        labels=("engine",)).labels(engine=engine)


def kv_pages_used(engine: str) -> Gauge:
    """Pages currently held by live sequences or the prefix cache —
    the page-table occupancy series ROADMAP item 3 names; a live
    callback gauge, so /metrics always reads the current pool state."""
    return REGISTRY.gauge(
        "znicz_kv_pages_used",
        "KV-cache pages held by live sequences + the prefix cache",
        labels=("engine",)).labels(engine=engine)


def kv_bytes_per_lane(engine: str) -> Gauge:
    """KV-cache bytes reserved per decode lane (pool bytes —
    including the per-block scale pools of int8 pages — over
    ``max_slots``).  Cache bytes bound decode concurrency, so this is
    the direct denominator of the round-21 quantization lanes win."""
    return REGISTRY.gauge(
        "znicz_kv_bytes_per_lane",
        "KV-cache bytes reserved per decode lane",
        labels=("engine",)).labels(engine=engine)


def kv_page_migrations(engine: str, direction: str) -> Counter:
    """KV pages moved between tiers/pools (round 22): ``spill`` (HBM
    → host-DRAM tier, a cold prefix block demoted under pool
    pressure), ``restore`` (host → HBM through the staging ring — a
    spilled block matched again), ``handoff`` (prefill pool → decode
    pool, one count per page carried by a prefill→decode transfer).
    Spill traffic trending up at a flat hit rate means the working
    set outgrew HBM and the tier is absorbing it — the intended
    shape; restores outpacing spills means thrash (tier too small)."""
    return REGISTRY.counter(
        "znicz_kv_page_migrations_total",
        "KV pages moved between cache tiers / serving pools",
        labels=("engine", "direction")).labels(engine=engine,
                                               direction=direction)


def kv_spill_pages(engine: str) -> Gauge:
    """Host-DRAM tier occupancy (live callback gauge): KV pages
    currently spilled out of the HBM pool.  With
    ``znicz_kv_pages_used`` this is the two-tier residency picture —
    total cached prefix capacity is the sum."""
    return REGISTRY.gauge(
        "znicz_kv_spill_pages",
        "KV pages resident in the host-DRAM spill tier",
        labels=("engine",)).labels(engine=engine)


def prefix_cache_events(engine: str, event: str) -> Counter:
    """Prefix-sharing admissions: ``hit`` (≥1 full block of the
    prompt reused from the radix cache), ``miss`` (prefilled from
    scratch), ``evicted`` (a cached block released under pool
    pressure).  Hit *tokens* ride ``znicz_prefix_tokens_total``."""
    return REGISTRY.counter(
        "znicz_prefix_cache_total",
        "Prefix-cache admission events (hit/miss/evicted)",
        labels=("engine", "event")).labels(engine=engine, event=event)


def prefix_tokens(engine: str, kind: str) -> Counter:
    """Prompt tokens by prefix-cache outcome: ``shared`` positions
    skipped prefill entirely (their K/V pages were reused),
    ``computed`` positions paid the prefill forward."""
    return REGISTRY.counter(
        "znicz_prefix_tokens_total",
        "Prompt tokens by prefix-cache outcome (shared/computed)",
        labels=("engine", "kind")).labels(engine=engine, kind=kind)


def spec_tokens(engine: str, verdict: str) -> Counter:
    """Speculative-decoding drafter proposals by verifier verdict
    (``accepted`` / ``rejected``) — acceptance rate is
    ``accepted / (accepted + rejected)``."""
    return REGISTRY.counter(
        "znicz_spec_tokens_total",
        "Drafted tokens by verification verdict (accepted/rejected)",
        labels=("engine", "verdict")).labels(engine=engine,
                                             verdict=verdict)


def swap_pause_seconds(engine: str) -> Counter:
    """Cumulative wall time decode admission was paused for swap
    drains.  TTFT deadline clocks stamp from admission-ELIGIBLE time
    (submit time + any overlapping pause), so this series is the
    audit trail for what the serving SLO histograms exclude."""
    return REGISTRY.counter(
        "znicz_swap_pause_seconds_total",
        "Decode admission pause time accumulated by swap drains",
        labels=("engine",)).labels(engine=engine)


def serving_warmup_seconds(engine: str) -> Gauge:
    return REGISTRY.gauge(
        "znicz_serving_warmup_seconds",
        "Wall time spent AOT-compiling the bucket ladder at start()",
        labels=("engine",)).labels(engine=engine)


# ----------------------------------------------------------------------
# resilience series (round 11): every fault, skip, retry, quarantine,
# rollback and breaker transition is a scrapeable counter so the chaos
# dryrun attests recovery from the same /metrics feed Prometheus reads
# ----------------------------------------------------------------------
def faults_injected(site: str) -> Counter:
    """Deterministic fault-injection events by named site (one event
    per transient firing; a persistent fault counts once)."""
    return REGISTRY.counter(
        "znicz_faults_injected_total",
        "Injected fault events by site (resilience.faults)",
        labels=("site",)).labels(site=site)


def recoveries(kind: str) -> Counter:
    """Recovery events: the system absorbed a fault and kept going
    (anomaly_step, rollback, shard_retry, shard_quarantine,
    reader_restart, serving_retry, snapshot_write,
    snapshot_fallback)."""
    return REGISTRY.counter(
        "znicz_recoveries_total",
        "Faults absorbed without failing the run, by recovery kind",
        labels=("kind",)).labels(kind=kind)


def step_anomalies(workflow: str, kind: str) -> Counter:
    """Training steps whose loss (kind=loss) or gradients (kind=grad)
    went non-finite; the guard skipped their optimizer update."""
    return REGISTRY.counter(
        "znicz_step_anomalies_total",
        "Non-finite training steps by kind (update skipped)",
        labels=("workflow", "kind")).labels(workflow=workflow, kind=kind)


def anomaly_rollbacks(workflow: str) -> Counter:
    return REGISTRY.counter(
        "znicz_anomaly_rollbacks_total",
        "Rollbacks to the last good snapshot after K consecutive "
        "anomalous steps", labels=("workflow",)).labels(workflow=workflow)


def loader_read_retries(loader: str) -> Counter:
    return REGISTRY.counter(
        "znicz_loader_read_retries_total",
        "Shard read attempts that failed and were retried",
        labels=("loader",)).labels(loader=loader)


def loader_shards_quarantined(loader: str) -> Counter:
    return REGISTRY.counter(
        "znicz_loader_shards_quarantined_total",
        "Shards quarantined after exhausting read retries (their rows "
        "deliver zeros for the rest of the run)",
        labels=("loader",)).labels(loader=loader)


def loader_pipeline_restarts(loader: str) -> Counter:
    return REGISTRY.counter(
        "znicz_loader_pipeline_restarts_total",
        "Streaming pipelines rebuilt after a producer/uploader thread "
        "died", labels=("loader",)).labels(loader=loader)


def snapshot_failures(op: str) -> Counter:
    return REGISTRY.counter(
        "znicz_snapshot_failures_total",
        "Snapshot operations that failed and were absorbed "
        "(op=write: training continued on the last good snapshot; "
        "op=load: a corrupt file fell back to an older snapshot)",
        labels=("op",)).labels(op=op)


def serving_breaker_state(engine: str) -> Gauge:
    """0 = closed (healthy), 1 = half-open (probing), 2 = open
    (shedding load with fast Overloaded replies)."""
    return REGISTRY.gauge(
        "znicz_serving_breaker_state",
        "Circuit-breaker state (0 closed, 1 half-open, 2 open)",
        labels=("engine",)).labels(engine=engine)


def serving_breaker_transitions(engine: str, to: str) -> Counter:
    return REGISTRY.counter(
        "znicz_serving_breaker_transitions_total",
        "Circuit-breaker state transitions by target state",
        labels=("engine", "to")).labels(engine=engine, to=to)


def serving_queue_age_seconds(engine: str, pool: str = "all") -> Gauge:
    """Age of the oldest pending request (live callback gauge) — the
    breaker's stall signal, a /readyz input, and the autoscalers'
    scale-up trigger.  ``pool`` (round 22) splits the series for
    disaggregated serving: ``prefill`` and ``decode`` queues age
    independently (a prompt burst must scale the prefill pool without
    touching decode residency), while monolithic engines keep the
    single ``all`` child."""
    return REGISTRY.gauge(
        "znicz_serving_queue_age_seconds",
        "Age of the oldest request pending in the serving queue",
        labels=("engine", "pool")).labels(engine=engine, pool=pool)


def last_step_timestamp(workflow: str) -> Gauge:
    """Unix time of the last completed training step — /readyz turns
    this into last-step staleness for external supervisors."""
    return REGISTRY.gauge(
        "znicz_last_step_timestamp_seconds",
        "Unix timestamp of the workflow's last completed step",
        labels=("workflow",)).labels(workflow=workflow)


# ----------------------------------------------------------------------
# continuous-learning series (round 13): the train-to-serve handoff —
# every publish, swap verdict and live model version is a scrapeable
# series so the soak harness and the chaos dryrun attest the
# publish→verify→canary→promote→rollback pipeline from /metrics
# ----------------------------------------------------------------------
def swaps_total(engine: str, outcome: str) -> Counter:
    """Weight hot-swap verdicts per serving engine: ``promoted`` (the
    candidate went live), ``rejected`` (the canary gate refused it —
    the incumbent kept serving), ``rolled_back`` (a promoted model
    tripped probation and the prior version was restored)."""
    return REGISTRY.counter(
        "znicz_swaps_total",
        "Weight hot-swap outcomes (promoted/rejected/rolled_back)",
        labels=("engine", "outcome")).labels(engine=engine,
                                             outcome=outcome)


def quant_canary(engine: str, outcome: str) -> Counter:
    """Canary verdicts for QUANTIZED candidates only (round 21):
    ``promoted`` / ``rejected`` / ``rolled_back``, a sub-ledger of
    ``znicz_swaps_total`` — the int8 publisher arm's health is a
    separate question from ordinary weight refreshes (a mis-scaled
    calibration must show up here as ``rejected``)."""
    return REGISTRY.counter(
        "znicz_quant_canary_total",
        "Canary outcomes for int8-quantized swap candidates",
        labels=("engine", "outcome")).labels(engine=engine,
                                             outcome=outcome)


def model_version(engine: str) -> Gauge:
    """The monotonic published-model version an engine is currently
    serving (0 = the bundle it started from, before any promote)."""
    return REGISTRY.gauge(
        "znicz_model_version",
        "Published model version currently live on the engine",
        labels=("engine",)).labels(engine=engine)


def swap_duration_seconds(engine: str) -> Histogram:
    """End-to-end hot-swap duration: stage (host→device upload of the
    candidate weights, off the dispatch path) + drain (decode engines
    let old-model generations finish) + the atomic publish flip."""
    return REGISTRY.histogram(
        "znicz_swap_duration_seconds",
        "Weight hot-swap duration (stage + drain + atomic flip)",
        labels=("engine",)).labels(engine=engine)


def snapshot_age_seconds(source: str) -> Gauge:
    """Seconds since ``source`` (a Snapshotter prefix or a publisher
    directory) last wrote a GOOD artifact — a live callback gauge, so
    /readyz sees a stalled trainer as staleness without any writer
    heartbeat code (threshold: ``engine.ready_max_snapshot_age_s``)."""
    return REGISTRY.gauge(
        "znicz_snapshot_age_seconds",
        "Time since the last good snapshot/publish by source",
        labels=("source",)).labels(source=source)


# ----------------------------------------------------------------------
# population series (round 14): K-replica evolution as a mesh workload —
# per-member fitness, generation and exploit/explore progress are
# scrapeable so the population dryrun and pop_bench attest the engine
# from the same /metrics feed as everything else
# ----------------------------------------------------------------------
def population_members(engine: str) -> Gauge:
    """Members (stacked model replicas) in the population run."""
    return REGISTRY.gauge(
        "znicz_population_members",
        "Model replicas trained by the population engine",
        labels=("engine",)).labels(engine=engine)


def population_fitness(engine: str, member: int) -> Gauge:
    """Per-member fitness (higher is better; classification runs
    report ``-validation_err_pt``), updated at every epoch boundary."""
    return REGISTRY.gauge(
        "znicz_population_fitness",
        "Per-member population fitness (latest epoch; higher=better)",
        labels=("engine", "member")).labels(engine=engine,
                                            member=member)


def population_best_fitness(engine: str) -> Gauge:
    """Best fitness any member has reached so far in the run — the
    single number the dryrun tail and dashboards read."""
    return REGISTRY.gauge(
        "znicz_population_best_fitness",
        "Best member fitness seen so far in the population run",
        labels=("engine",)).labels(engine=engine)


def population_generations(engine: str) -> Counter:
    return REGISTRY.counter(
        "znicz_population_generations_total",
        "Evolution generations applied to the stacked population",
        labels=("engine",)).labels(engine=engine)


def population_evolution(engine: str, op: str) -> Counter:
    """Evolution-op counters: ``exploit`` (a truncated member copied a
    winner's weights+hypers), ``explore`` (its hypers were perturbed),
    ``crossover`` (a slot was refilled by arithmetic weight blending),
    ``mutate`` (its hypers were mutated)."""
    return REGISTRY.counter(
        "znicz_population_evolution_total",
        "Population evolution ops (exploit/explore/crossover/mutate)",
        labels=("engine", "op")).labels(engine=engine, op=op)


def publishes_total(source: str) -> Counter:
    """Snapshot bundles published for serving pickup (the training
    side of the handoff; the watcher's digest verdicts ride
    ``znicz_snapshot_failures_total{op=publish}``)."""
    return REGISTRY.counter(
        "znicz_publishes_total",
        "Model bundles published to the serving handoff directory",
        labels=("source",)).labels(source=source)


# ----------------------------------------------------------------------
# round 16: multi-tenant fleet series — the isolation proof is read
# from exactly these (the bench and the dryrun attest per-tenant p99,
# shed attribution and replica counts from a live /metrics scrape)
# ----------------------------------------------------------------------
def fleet_requests(fleet: str, tenant: str, event: str) -> Counter:
    """Per-tenant request lifecycle on one fleet: ``submitted``,
    ``served``, ``shed`` (rate-limit/preemption/breaker), ``expired``
    (deadline), ``failed``.  ``shed`` attribution per tenant is the
    overload proof: under a low-priority flood ONLY the flooding
    tenant's child moves."""
    return REGISTRY.counter(
        "znicz_fleet_requests_total",
        "Fleet requests by tenant and lifecycle event",
        labels=("fleet", "tenant", "event")).labels(
        fleet=fleet, tenant=tenant, event=event)


def fleet_latency_seconds(fleet: str, tenant: str) -> Histogram:
    """Per-tenant SLO-latency distribution: submit→reply for one-shot
    scoring, submit→first-token (TTFT) for generation — the
    scheduling-bound metric in both cases (a generation's completion
    time is proportional to the tokens requested; its cadence rides
    ``znicz_serving_token_seconds``)."""
    return REGISTRY.histogram(
        "znicz_fleet_latency_seconds",
        "Fleet SLO latency by tenant (reply for one-shot, TTFT for "
        "generation)",
        labels=("fleet", "tenant")).labels(fleet=fleet, tenant=tenant)


def fleet_latency_p99_seconds(fleet: str, tenant: str) -> Gauge:
    """Exact windowed per-tenant p99 exported as a summary-style
    gauge (callback over the fleet's sliding window) — the SLO bound
    the isolation attestation reads from the scrape, immune to
    histogram-bucket interpolation error."""
    return REGISTRY.gauge(
        "znicz_fleet_latency_p99_seconds",
        "Exact windowed p99 fleet latency by tenant",
        labels=("fleet", "tenant")).labels(fleet=fleet, tenant=tenant)


def fleet_breaker_state(fleet: str, tenant: str) -> Gauge:
    """Per-TENANT circuit breaker (0=closed, 1=half-open, 2=open):
    one tenant's breaker opening sheds only that tenant."""
    return REGISTRY.gauge(
        "znicz_fleet_breaker_state",
        "Per-tenant fleet breaker state (0 closed, 1 half-open, "
        "2 open)",
        labels=("fleet", "tenant")).labels(fleet=fleet, tenant=tenant)


def fleet_tenant_tokens(fleet: str, tenant: str) -> Gauge:
    """Live token-bucket level per tenant (callback gauge)."""
    return REGISTRY.gauge(
        "znicz_fleet_tenant_tokens",
        "Fleet admission token-bucket level by tenant",
        labels=("fleet", "tenant")).labels(fleet=fleet, tenant=tenant)


def fleet_models(fleet: str) -> Gauge:
    """Resident models on one fleet (the dryrun tail's ``fleet=N
    models``)."""
    return REGISTRY.gauge(
        "znicz_fleet_models",
        "Models resident in the fleet",
        labels=("fleet",)).labels(fleet=fleet)


def quantized_models(fleet: str) -> Gauge:
    """Resident models serving from int8-quantized bundles (round
    21) — with ``znicz_fleet_models`` this is the fleet's quantization
    rollout fraction, the residency dividend of halved weight
    bytes."""
    return REGISTRY.gauge(
        "znicz_quantized_models",
        "Resident fleet models serving int8-quantized bundles",
        labels=("fleet",)).labels(fleet=fleet)


def fleet_replicas(fleet: str, model: str) -> Gauge:
    """Live replica count per model (the autoscaler moves this; a
    ``fleet.replica_loss`` injection dips it until repair)."""
    return REGISTRY.gauge(
        "znicz_fleet_replicas",
        "Live serving replicas per fleet model",
        labels=("fleet", "model")).labels(fleet=fleet, model=model)


def fleet_scale_events(fleet: str, model: str, op: str) -> Counter:
    """Autoscaler verdicts per model: ``up``, ``down``, ``repair``
    (replica-loss respawn)."""
    return REGISTRY.counter(
        "znicz_fleet_scale_events_total",
        "Fleet autoscaler scale events per model",
        labels=("fleet", "model", "op")).labels(
        fleet=fleet, model=model, op=op)


def fleet_traffic_weight(fleet: str, model: str, version: str) -> Gauge:
    """Configured A/B traffic fraction per model version (weighted
    routing generalizing the round-13 two-version canary)."""
    return REGISTRY.gauge(
        "znicz_fleet_traffic_weight",
        "Configured traffic fraction per fleet model version",
        labels=("fleet", "model", "version")).labels(
        fleet=fleet, model=model, version=version)


def fleet_ladder_evictions(fleet: str, model: str) -> Counter:
    """Bucket programs dropped by the SHARED ladder budget under
    memory pressure — pressure lands on the lowest-priority model's
    ladder first."""
    return REGISTRY.counter(
        "znicz_fleet_ladder_evictions_total",
        "Bucket programs evicted by the shared fleet ladder budget",
        labels=("fleet", "model")).labels(fleet=fleet, model=model)


# ----------------------------------------------------------------------
# round 19: silent-data-corruption sentinel — fingerprint votes,
# redundant-compute audits and quarantine verdicts are scrapeable so
# the sdc dryrun attests detection from the same /metrics feed
# ----------------------------------------------------------------------
def sdc_votes(workflow: str, verdict: str) -> Counter:
    """Cross-replica fingerprint votes by verdict: ``clean`` (every
    process's post-update param fingerprint agreed) vs ``divergent``
    (at least one chip/host computed different params — the silent-
    data-corruption signature none of the isfinite/digest layers can
    see)."""
    return REGISTRY.counter(
        "znicz_sdc_votes_total",
        "Cross-replica fingerprint votes (clean/divergent)",
        labels=("workflow", "verdict")).labels(workflow=workflow,
                                               verdict=verdict)


def sdc_audits(workflow: str, verdict: str) -> Counter:
    """Redundant-compute audits by verdict: the last microbatch's step
    replayed on the shadow oracle either ``match``ed the device's
    post-update fingerprints or caught a ``mismatch``."""
    return REGISTRY.counter(
        "znicz_sdc_audits_total",
        "Redundant-compute shadow audits (match/mismatch)",
        labels=("workflow", "verdict")).labels(workflow=workflow,
                                               verdict=verdict)


def sdc_detected(kind: str) -> Counter:
    """Confirmed silent-data-corruption detections by detector:
    ``vote`` (cross-replica fingerprint compare), ``audit``
    (redundant-compute replay), ``serving`` (sampled shadow re-score
    of live replies)."""
    return REGISTRY.counter(
        "znicz_sdc_detected_total",
        "Confirmed SDC detections by detector (vote/audit/serving)",
        labels=("kind",)).labels(kind=kind)


def sdc_suspects(process, device: str) -> Counter:
    """SDC suspicion events attributed to a process/device pair —
    ``device`` is ``-`` for host-level attributions (training votes /
    audits) or the serving replica id for shadow-audit catches."""
    return REGISTRY.counter(
        "znicz_sdc_suspect_total",
        "SDC suspicion events by process and device/replica",
        labels=("process", "device")).labels(process=process,
                                             device=device)


def sdc_quarantined(kind: str) -> Counter:
    """Corrupt compute units removed from service: ``host`` (elastic
    gang restarted without the culprit, blocklisted) or ``replica``
    (serving replica removed via the ReplicaGroup repair path)."""
    return REGISTRY.counter(
        "znicz_sdc_quarantined_total",
        "Corrupt hosts/replicas quarantined after confirmed SDC",
        labels=("kind",)).labels(kind=kind)


def loader_rows_quarantined(loader: str) -> Counter:
    """Minibatch rows served as ZEROS because their shard is
    quarantined — the silent-data-loss that used to be invisible:
    ``_gather_retry`` kept the run alive but nothing counted the
    zero-filled rows.  Report-only on /readyz."""
    return REGISTRY.counter(
        "znicz_loader_rows_quarantined_total",
        "Rows zero-filled from quarantined shards (silent data loss, "
        "now loud)", labels=("loader",)).labels(loader=loader)


#: the currently-live build_info child's label key (previous children
#: are zeroed when richer info arrives, so scrapes read the ==1 row)
_build_info_live: tuple | None = None


def set_build_info(*, platform: str = "?", mesh: str = "?",
                   processes: str = "?", fallback: bool = False) -> None:
    """Register/refresh the ``znicz_build_info`` gauge: package
    version, jax version, platform, mesh shape and process count as
    labels, value 1 — fleet debugging can tell which build a scrape
    came from.  Called from device creation (full info) and from
    ``WebStatusServer`` (``fallback=True`` — registers only when
    nothing richer did, so supervisor-only processes export it too).
    Richer info supersedes: the previous child is zeroed so exactly
    one row reads 1."""
    global _build_info_live
    if fallback and _build_info_live is not None:
        return
    import jax

    import znicz_tpu
    fam = REGISTRY.gauge(
        "znicz_build_info",
        "Build identity (value 1; read the labels): package version, "
        "jax version, platform, mesh shape, process count",
        labels=("version", "jax", "platform", "mesh", "processes"))
    key = {"version": znicz_tpu.__version__, "jax": jax.__version__,
           "platform": str(platform), "mesh": str(mesh),
           "processes": str(processes)}
    key_t = tuple(sorted(key.items()))
    if _build_info_live == key_t:
        return
    if _build_info_live is not None:
        fam.labels(**dict(_build_info_live)).set(0)
    fam.labels(**key).set(1)
    _build_info_live = key_t


# -- elastic multi-host supervision (round 18) -------------------------
def heartbeat_age_seconds(process) -> Gauge:
    """Seconds since process ``process`` last beat into the heartbeat
    channel (callback gauge fed by the coordinator-side
    ``HeartbeatMonitor`` — /metrics and /readyz read peer liveness
    from the same series).  ``inf`` renders as ``+Inf`` when a peer
    has never beaten."""
    return REGISTRY.gauge(
        "znicz_heartbeat_age_seconds",
        "Seconds since each process's last heartbeat",
        labels=("process",)).labels(process=process)


def host_losses(kind: str) -> Counter:
    """Processes the elastic supervisor declared gone, by kind:
    ``loss`` (died / heartbeat stale), ``stall`` (wall-clock beats
    flow, step counter frozen — hung collective), ``preempt``
    (checkpoint-on-signal drain + EXIT_PREEMPTED), ``sdc`` (round 19:
    a confirmed silent-data-corruption culprit exited EXIT_SDC and is
    blocklisted — the restart resumes from the PRE-divergence
    snapshot, not the newest one)."""
    return REGISTRY.counter(
        "znicz_host_losses_total",
        "Hosts lost to the elastic supervisor by kind",
        labels=("kind",)).labels(kind=kind)


def elastic_restarts() -> Counter:
    """Gang relaunches onto the surviving host set (each one implies a
    reshard-resume from the newest digest-verified snapshot)."""
    return REGISTRY.counter(
        "znicz_elastic_restarts_total",
        "Elastic gang restarts onto the surviving mesh")._solo()


def checkpoint_on_signal() -> Counter:
    """Barriered preemption checkpoints completed (worker-side; the
    gang supervisor folds worker heartbeat attestations into its own
    registry under the same name)."""
    return REGISTRY.counter(
        "znicz_checkpoint_on_signal_total",
        "Preemption-triggered barriered checkpoints")._solo()


# ----------------------------------------------------------------------
# round 24: correlated observability — exact windowed percentiles as
# canonical gauges (the number SERVE_BENCH rows print and /metrics
# exports must be the SAME number), flight-recorder health, and the
# federated gang-level series the supervisor/fleet scrape loops write
# ----------------------------------------------------------------------
def _percentile(sorted_vals: list, q: float) -> float:
    """Nearest-rank percentile of an already-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def window_p99(win, n0: int = 0) -> float:
    """p99 of a latency window's tail, skipping the first ``n0``
    samples.

    The per-pass slice the serve bench and the dryruns use to compare
    warmed passes: snapshot ``len(win)`` before a pass, then take the
    p99 of only the observations that pass appended, so cold-start and
    earlier-pass samples never pollute the comparison.  ``win`` is any
    iterable of latencies (typically an engine's bounded phase deque).
    Promoted here (round 24) from ``serving.engine`` so the bench-side
    helper and the :func:`phase_p99_seconds` callback gauges are one
    implementation."""
    tail = sorted(list(win)[n0:])
    return _percentile(tail, 99.0)


def phase_p99_seconds(engine: str, phase: str) -> Gauge:
    """Exact windowed p99 of one serving phase (``queue`` /
    ``prefill`` / ``handoff`` / ``decode`` / ``ttft`` / ``token``) as
    a live callback gauge over the engine's bounded phase window —
    the per-phase decomposition of tail latency the disagg split is
    justified by, readable from ONE scrape instead of a bench
    stopwatch."""
    return REGISTRY.gauge(
        "znicz_phase_p99_seconds",
        "Exact windowed p99 latency per serving phase",
        labels=("engine", "phase")).labels(engine=engine, phase=phase)


def trace_requests(engine: str, outcome: str) -> Counter:
    """Request traces closed per engine by outcome (``ok`` / ``shed``
    / ``expired`` / ``failed``) — the denominator for /trace.json
    request-tree sampling (the span ring is bounded; this counter is
    not)."""
    return REGISTRY.counter(
        "znicz_trace_requests_total",
        "Request-scoped traces finished, by outcome",
        labels=("engine", "outcome")).labels(engine=engine,
                                             outcome=outcome)


def flightrecord_events(kind: str) -> Counter:
    """Ops events journaled by the flight recorder, by kind (swap,
    canary, breaker, restart, quarantine, autoscale, ...)."""
    return REGISTRY.counter(
        "znicz_flightrecord_events_total",
        "Flight-recorder events journaled, by kind",
        labels=("kind",)).labels(kind=kind)


def flightrecord_dropped() -> Counter:
    """Flight-recorder events DROPPED because the journal write
    stalled or failed (disk full, torn device, injected
    ``observe.recorder_stall``) — telemetry degrades to counting
    here and never blocks a dispatch or a swap."""
    return REGISTRY.counter(
        "znicz_flightrecord_dropped_total",
        "Flight-recorder events dropped on journal write "
        "stall/failure")._solo()


def fed_sources(gang: str) -> Gauge:
    """Child sources (worker /metrics endpoints, in-process child
    registries, heartbeat channels) a federator folds per scrape."""
    return REGISTRY.gauge(
        "znicz_fed_sources",
        "Sources folded into the federated gang-level scrape",
        labels=("gang",)).labels(gang=gang)


def fed_scrape_age_seconds(gang: str, source: str) -> Gauge:
    """Seconds since ``source`` was last folded successfully (live
    callback gauge) — the federated view's staleness bound: a child
    whose exporter died shows up HERE, not as silently frozen
    numbers."""
    return REGISTRY.gauge(
        "znicz_fed_scrape_age_seconds",
        "Staleness of each federated source's last successful fold",
        labels=("gang", "source")).labels(gang=gang, source=source)


def fed_queue_age_seconds(gang: str, process: str, pool: str) -> Gauge:
    """Federated copy of each child's oldest-pending-request age,
    labeled by process AND pool — one scrape answers 'which pool is
    backed up on which host'."""
    return REGISTRY.gauge(
        "znicz_fed_queue_age_seconds",
        "Federated per-child serving queue age by process and pool",
        labels=("gang", "process", "pool")).labels(
        gang=gang, process=process, pool=pool)


def fed_requests(gang: str, process: str, event: str) -> Gauge:
    """Federated snapshot of each child's request lifecycle counters
    (summed over that child's engines) — a gauge, not a counter: the
    federator republishes the child's last-seen totals."""
    return REGISTRY.gauge(
        "znicz_fed_requests",
        "Federated per-child serving request totals by event",
        labels=("gang", "process", "event")).labels(
        gang=gang, process=process, event=event)


def fed_heartbeat_age_seconds(gang: str, process: str) -> Gauge:
    """Federated heartbeat staleness per gang member (fed from the
    supervisor's heartbeat channel fold)."""
    return REGISTRY.gauge(
        "znicz_fed_heartbeat_age_seconds",
        "Federated seconds since each gang member's last heartbeat",
        labels=("gang", "process")).labels(gang=gang, process=process)


def fed_step(gang: str, process: str) -> Gauge:
    """Federated per-member step counter — 'which host is slow' read
    straight off the spread of this family's children."""
    return REGISTRY.gauge(
        "znicz_fed_step",
        "Federated per-member training/serving step counter",
        labels=("gang", "process")).labels(gang=gang, process=process)
