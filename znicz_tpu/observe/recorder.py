"""Ops flight recorder: bounded, crash-safe structured event journal.

Counters say HOW OFTEN something happened; a post-mortem needs to know
WHAT happened, IN WHAT ORDER, CORRELATED WITH WHAT — today that story
lives in stdout logs that die with the process.  The flight recorder
journals every consequential ops event — swap verdicts, canary
rejections, elastic restarts, SDC quarantines, breaker transitions,
autoscaler actions, AOT-cache quarantines — as JSONL with monotone
sequence numbers and trace/step correlation IDs, into a bounded ring
of on-disk segments that ride the snapshot-dir fence conventions:

- the ACTIVE segment is appended+flushed per event (a crash loses at
  most the final partial line, which the reader skips);
- a FULL segment is sealed by writing its ``.sha256`` sidecar strictly
  after the data — a sidecarless segment is the crash window, its
  parseable prefix still counts;
- the oldest sealed segments are deleted past ``max_segments`` — the
  journal is a ring, never an unbounded log.

Failure discipline (the ``observe.recorder_stall`` contract): a
journal write that stalls or fails must NEVER block or fail the
caller — a swap, a dispatch, a restart proceeds identically with a
dead disk underneath; the recorder degrades to counting drops on
``znicz_flightrecord_dropped_total``.

:func:`record` is the module-level hook instrumentation sites call;
:meth:`FlightRecorder.dump_since` is the read API ``/flightrecord``
serves.  All of it is gated on ``root.common.engine.telemetry``.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
import threading
import time

from znicz_tpu.observe import metrics as _metrics

__all__ = ["FlightRecorder", "get_recorder", "set_recorder", "record"]

_SEG_PREFIX = "flight_"
_SEG_SUFFIX = ".jsonl"


def _seg_name(idx: int) -> str:
    return f"{_SEG_PREFIX}{idx:06d}{_SEG_SUFFIX}"


def _seg_index(name: str) -> int | None:
    if not (name.startswith(_SEG_PREFIX) and name.endswith(_SEG_SUFFIX)):
        return None
    try:
        return int(name[len(_SEG_PREFIX):-len(_SEG_SUFFIX)])
    except ValueError:
        return None


class FlightRecorder:
    """One journal directory: an append-only active segment plus a
    bounded ring of sealed (sha256-sidecarred) predecessors."""

    def __init__(self, directory: str, *, segment_events: int = 256,
                 max_segments: int = 8) -> None:
        self.directory = str(directory)
        self.segment_events = max(1, int(segment_events))
        self.max_segments = max(2, int(max_segments))
        self._lock = threading.Lock()
        self._fh = None
        self._seq = 0
        self._seg_events = 0
        self._seg_idx = 0
        os.makedirs(self.directory, exist_ok=True)
        existing = self._segments()
        if existing:
            self._seg_idx = existing[-1] + 1
            # resume the sequence past anything already journaled so
            # dump_since(seq) stays monotone across restarts
            for ev in self._read_segment(existing[-1]):
                self._seq = max(self._seq, int(ev.get("seq", 0)))

    # ------------------------------------------------------------------
    # write side
    # ------------------------------------------------------------------
    def record(self, kind: str, /, **fields) -> bool:
        """Journal one event; returns False when the event was dropped
        (write stall/failure) — NEVER raises, never blocks beyond one
        flushed line.  ``kind`` is positional-only so a field named
        ``kind`` cannot collide at the call site."""
        if not _metrics.enabled():
            return False
        from znicz_tpu.resilience import faults as _faults
        try:
            if _faults.fire("observe.recorder_stall") is not None:
                raise OSError("injected flight-recorder write stall")
            with self._lock:
                self._seq += 1
                event = {"t": round(time.time(), 6), "seq": self._seq,
                         "kind": str(kind)}
                for key, val in fields.items():
                    # envelope keys (t/seq/kind) are not overridable
                    if val is not None and key not in event:
                        event[key] = val
                if self._fh is None:
                    path = os.path.join(self.directory,
                                        _seg_name(self._seg_idx))
                    self._fh = open(path, "a")
                self._fh.write(json.dumps(event, default=str) + "\n")
                self._fh.flush()
                self._seg_events += 1
                if self._seg_events >= self.segment_events:
                    self._seal_locked()
        except Exception:  # noqa: BLE001 — a dead disk must not fail a swap
            _metrics.flightrecord_dropped().inc()
            return False
        _metrics.flightrecord_events(kind).inc()
        return True

    def _seal_locked(self) -> None:
        """Seal the active segment: close, sidecar strictly AFTER the
        data, roll to the next index, trim the ring."""
        self._fh.close()
        self._fh = None
        path = os.path.join(self.directory, _seg_name(self._seg_idx))
        digest = hashlib.sha256()
        with open(path, "rb") as fh:
            digest.update(fh.read())
        tmp = path + ".sha256.tmp"
        with open(tmp, "w") as fh:
            fh.write(digest.hexdigest() + "\n")
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path + ".sha256")
        self._seg_idx += 1
        self._seg_events = 0
        for idx in self._segments()[:-self.max_segments]:
            old = os.path.join(self.directory, _seg_name(idx))
            for victim in (old, old + ".sha256"):
                try:
                    os.remove(victim)
                except OSError:
                    pass

    def flush_seal(self) -> None:
        """Seal the active segment now (tests / shutdown hooks)."""
        with self._lock:
            if self._fh is not None:
                self._seal_locked()

    # ------------------------------------------------------------------
    # read side
    # ------------------------------------------------------------------
    def _segments(self) -> list[int]:
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        return sorted(i for i in (_seg_index(n) for n in names)
                      if i is not None)

    def _read_segment(self, idx: int) -> list[dict]:
        path = os.path.join(self.directory, _seg_name(idx))
        out: list[dict] = []
        try:
            with open(path) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        break  # torn tail of a crash window
        except OSError:
            pass
        return out

    def dump_since(self, seq: int = 0, *, kinds=None,
                   limit: int | None = None) -> list[dict]:
        """Events with ``seq > seq``, oldest first, optionally
        filtered by ``kinds`` and capped at the LAST ``limit``
        events."""
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
            segments = self._segments()
        events: list[dict] = []
        want = set(kinds) if kinds else None
        for idx in segments:
            for ev in self._read_segment(idx):
                if int(ev.get("seq", 0)) <= seq:
                    continue
                if want is not None and ev.get("kind") not in want:
                    continue
                events.append(ev)
        events.sort(key=lambda ev: int(ev.get("seq", 0)))
        if limit is not None and len(events) > limit:
            events = events[-limit:]
        return events

    def verify(self) -> dict:
        """Digest-check every sealed segment; the active (sidecarless)
        one is the crash window and counts ``open``."""
        good = bad = open_ = 0
        for idx in self._segments():
            path = os.path.join(self.directory, _seg_name(idx))
            side = path + ".sha256"
            if not os.path.exists(side):
                open_ += 1
                continue
            digest = hashlib.sha256()
            try:
                with open(path, "rb") as fh:
                    digest.update(fh.read())
                with open(side) as fh:
                    want = fh.read().strip()
                good += 1 if digest.hexdigest() == want else 0
                bad += 0 if digest.hexdigest() == want else 1
            except OSError:
                bad += 1
        return {"sealed_good": good, "sealed_bad": bad, "open": open_}

    def status(self) -> dict:
        with self._lock:
            return {"dir": self.directory, "seq": self._seq,
                    "segments": len(self._segments()),
                    "dropped": int(
                        _metrics.flightrecord_dropped().value)}


# ----------------------------------------------------------------------
# the process-global recorder instrumentation sites write through
# ----------------------------------------------------------------------
_RECORDER: FlightRecorder | None = None
_RECORDER_LOCK = threading.Lock()


def set_recorder(recorder: FlightRecorder | None) -> None:
    """Install (or clear) the process recorder explicitly — dryruns
    and chaos drills point it at their scratch directory."""
    global _RECORDER
    with _RECORDER_LOCK:
        _RECORDER = recorder


def get_recorder() -> FlightRecorder | None:
    """The process recorder, created lazily under the telemetry gate.
    Journal directory: ``root.common.engine.flight_dir`` when set,
    else ``<tmp>/znicz_flight_<pid>`` (bounded either way)."""
    global _RECORDER
    if not _metrics.enabled():
        return _RECORDER  # an explicitly installed recorder still reads
    if _RECORDER is None:
        with _RECORDER_LOCK:
            if _RECORDER is None:
                from znicz_tpu.utils.config import root
                directory = root.common.engine.get("flight_dir", None)
                if not directory:
                    directory = os.path.join(
                        tempfile.gettempdir(),
                        f"znicz_flight_{os.getpid()}")
                try:
                    _RECORDER = FlightRecorder(str(directory))
                except OSError:
                    _metrics.flightrecord_dropped().inc()
                    return None
    return _RECORDER


def record(kind: str, /, **fields) -> bool:
    """Module-level journal hook: one line per consequential ops
    event.  No-op (False) when telemetry is off; never raises."""
    if not _metrics.enabled():
        return False
    rec = get_recorder()
    if rec is None:
        return False
    return rec.record(kind, **fields)
