"""Unified telemetry: metrics registry + host-span tracing.

One observability layer shared by the training engine and the serving
engine (the modern equivalent of the reference's live workflow
introspection — plotters and ``veles/web_status.py``):

- :mod:`znicz_tpu.observe.metrics` — a thread-safe process-local
  registry of counters/gauges/histograms with JSON and Prometheus
  text exposition.  ``WebStatusServer`` serves it at ``/metrics``.
- :mod:`znicz_tpu.observe.tracing` — a host-side span tracer (unit
  fires, epochs, compiles, serving dispatches) exporting
  Chrome-trace/Perfetto JSON, served live at ``/trace.json`` and
  merged with device traces by ``trace_top.py --spans``.
- :func:`profile_window` — capture a ``jax.profiler`` device trace +
  the window's host spans around any region.

Master gate: ``root.common.engine.telemetry`` (default on;
near-zero overhead — hot sites check :func:`enabled` first).
"""

from znicz_tpu.observe.metrics import (  # noqa: F401
    DEFAULT_BUCKETS,
    REGISTRY,
    MetricsRegistry,
    enabled,
)
from znicz_tpu.observe.tracing import (  # noqa: F401
    TRACER,
    SpanTracer,
    now_us,
    profile_window,
)
