"""Unified telemetry: metrics registry + host-span tracing.

One observability layer shared by the training engine and the serving
engine (the modern equivalent of the reference's live workflow
introspection — plotters and ``veles/web_status.py``):

- :mod:`znicz_tpu.observe.metrics` — a thread-safe process-local
  registry of counters/gauges/histograms with JSON and Prometheus
  text exposition.  ``WebStatusServer`` serves it at ``/metrics``.
- :mod:`znicz_tpu.observe.tracing` — a host-side span tracer (unit
  fires, epochs, compiles, serving dispatches) exporting
  Chrome-trace/Perfetto JSON, served live at ``/trace.json`` and
  merged with device traces by ``trace_top.py --spans``.
- :func:`profile_window` — capture a ``jax.profiler`` device trace +
  the window's host spans around any region.
- :mod:`znicz_tpu.observe.recorder` (round 24) — the ops flight
  recorder: a bounded crash-safe JSONL journal of consequential ops
  events (swaps, canary verdicts, restarts, quarantines, breaker
  transitions), served at ``/flightrecord``.
- :mod:`znicz_tpu.observe.federation` (round 24) — gang-level
  metrics federation: supervisor/fleet scrape loops fold child
  ``/metrics`` pages, in-process child registries and the heartbeat
  channel into ``znicz_fed_*`` series with process/pool labels.
- :class:`RequestTrace` (round 24) — the request-scoped trace
  context minted at ``submit()`` that rides a request through every
  hop and renders its life as a parented span tree in /trace.json.

Master gate: ``root.common.engine.telemetry`` (default on;
near-zero overhead — hot sites check :func:`enabled` first).
"""

from znicz_tpu.observe.metrics import (  # noqa: F401
    DEFAULT_BUCKETS,
    REGISTRY,
    MetricsRegistry,
    enabled,
    window_p99,
)
from znicz_tpu.observe.tracing import (  # noqa: F401
    NULL_TRACE,
    TRACER,
    RequestTrace,
    SpanTracer,
    adopt_pending_trace,
    new_request_trace,
    now_us,
    profile_window,
    set_pending_trace,
)
from znicz_tpu.observe.recorder import (  # noqa: F401
    FlightRecorder,
    get_recorder,
    record,
    set_recorder,
)
from znicz_tpu.observe.federation import (  # noqa: F401
    Federator,
)
