"""Metrics federation: fold child scrapes into gang-level series.

Every process in a deployment — each elastic-gang worker, each disagg
pool, each fleet replica host — owns a process-local
:class:`~znicz_tpu.observe.metrics.MetricsRegistry`, so the fleet's
telemetry is N disjoint ``/metrics`` pages that nothing aggregates.
The :class:`Federator` is the aggregation half: a supervisor or
maintenance thread registers its children as sources —

- ``add_http(url, process)`` — a worker's live ``/metrics`` HTTP
  endpoint (the existing ``WebStatusServer`` path; parsed with a
  small text-format reader, no new dependency);
- ``add_registry(process, ...)`` — an in-process child registry merge
  (disagg pools and fleet replica groups live in the parent process —
  their series are re-labeled, not re-scraped);
- ``add_heartbeats(directory, n)`` — the elastic heartbeat channel
  (per-member step + staleness without an HTTP server on workers);

and every :meth:`Federator.scrape` folds them into the canonical
``znicz_fed_*`` families with ``gang``/``process``/``pool`` labels, so
ONE scrape of the folding process answers "which host is slow, which
pool is backed up".  Staleness is first-class: each source carries a
live ``znicz_fed_scrape_age_seconds`` callback gauge — a child whose
exporter died shows up as age, never as silently frozen numbers.
``/readyz`` folds :func:`status` (report-only unless
``engine.ready_max_fed_age_s`` is set).

Gated on ``root.common.engine.telemetry`` like the rest of the
observe layer; a scrape is O(children), runs on the caller's existing
maintenance cadence, and never raises into it.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
import urllib.request

from znicz_tpu.observe import metrics as _metrics

__all__ = ["Federator", "FEDERATORS", "status"]

#: every live federator (for /readyz folding and the status page)
FEDERATORS: list = []
_FEDERATORS_LOCK = threading.Lock()

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)\s*$")
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')

#: the child families a fold extracts (everything else in a child
#: scrape stays child-local — federation is a summary, not a mirror)
_QUEUE_AGE = "znicz_serving_queue_age_seconds"
_REQUESTS = "znicz_serving_requests_total"
_LAST_STEP = "znicz_last_step_timestamp_seconds"


def parse_prometheus(text: str) -> list[tuple[str, dict, float]]:
    """Parse text exposition 0.0.4 into ``(name, labels, value)``
    samples (comment/type lines skipped, unparseable values
    dropped)."""
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        name, raw_labels, raw_val = m.groups()
        try:
            value = float(raw_val.replace("+Inf", "inf"))
        except ValueError:
            continue
        labels = {k: v for k, v in _LABEL_RE.findall(raw_labels or "")}
        out.append((name, labels, value))
    return out


def _fold_samples(gang: str, process: str,
                  samples: list[tuple[str, dict, float]]) -> set:
    """Common fold: child serving samples → fed gauges; returns the
    ``(process, pool)`` children touched."""
    children: set = set()
    age_by_pool: dict[str, float] = {}
    req_by_event: dict[str, float] = {}
    last_step = None
    for name, labels, value in samples:
        if name == _QUEUE_AGE:
            pool = labels.get("pool", "all")
            age_by_pool[pool] = max(age_by_pool.get(pool, 0.0), value)
        elif name == _REQUESTS:
            event = labels.get("event", "?")
            req_by_event[event] = req_by_event.get(event, 0.0) + value
        elif name == _LAST_STEP:
            last_step = max(last_step or 0.0, value)
    for pool, age in age_by_pool.items():
        _metrics.fed_queue_age_seconds(gang, process, pool).set(age)
        children.add((process, pool))
    for event, total in req_by_event.items():
        _metrics.fed_requests(gang, process, event).set(total)
        children.add((process, "-"))
    if last_step is not None:
        _metrics.fed_step(gang, process).set(last_step)
        children.add((process, "-"))
    return children


class Federator:
    """One gang's metrics folder; sources registered once, folded on
    every :meth:`scrape` (the owner's maintenance cadence)."""

    def __init__(self, gang: str) -> None:
        self.gang = str(gang)
        self._sources: list[dict] = []
        self._lock = threading.Lock()
        self._last_children: set = set()
        _metrics.fed_sources(self.gang).set(0)
        with _FEDERATORS_LOCK:
            FEDERATORS.append(self)

    # ------------------------------------------------------------------
    # source registration
    # ------------------------------------------------------------------
    def _add(self, name: str, fold) -> None:
        src = {"name": name, "fold": fold, "last_ok": None,
               "errors": 0}
        # live staleness gauge: reads the fold clock, not a copy
        _metrics.fed_scrape_age_seconds(self.gang, name).set_function(
            lambda s=src: (float("inf") if s["last_ok"] is None
                           else time.monotonic() - s["last_ok"]))
        with self._lock:
            self._sources.append(src)
        _metrics.fed_sources(self.gang).set(len(self._sources))

    def add_http(self, url: str, process: str,
                 timeout_s: float = 2.0) -> None:
        """A child's live ``/metrics`` endpoint."""
        def fold():
            with urllib.request.urlopen(url, timeout=timeout_s) as resp:
                text = resp.read().decode("utf-8", "replace")
            return _fold_samples(self.gang, process,
                                 parse_prometheus(text))
        self._add(f"http:{process}", fold)

    def add_registry(self, process: str, registry=None,
                     pool_of=None) -> None:
        """In-process child-registry merge: re-label this process's
        (or ``registry``'s) serving families under the gang.
        ``pool_of(engine_label) -> pool`` overrides the pool a child
        series folds into (disagg pools share one process registry —
        the engine label is the only thing that tells them apart):
        return ``None`` to skip a series that is not ours, ``""`` to
        keep the series' own ``pool`` label (disagg queue-age series
        already carry one)."""
        reg = registry if registry is not None else _metrics.REGISTRY

        def fold():
            samples = []
            for fam_name in (_QUEUE_AGE, _REQUESTS, _LAST_STEP):
                fam = reg.get(fam_name)
                if fam is None:
                    continue
                for key, child in fam.items():
                    labels = dict(zip(fam.labelnames, key))
                    if pool_of is not None and "engine" in labels:
                        pool = pool_of(labels["engine"])
                        if pool is None:
                            continue  # not one of ours
                        if pool:  # "" keeps the series' own pool
                            labels = {**labels, "pool": pool}
                    samples.append((fam_name, labels,
                                    float(child.value)))
            return _fold_samples(self.gang, process, samples)
        self._add(f"registry:{process}", fold)

    def add_heartbeats(self, directory: str, n_processes: int) -> None:
        """The elastic heartbeat channel: per-member step + staleness
        without any worker-side HTTP."""
        def fold():
            children: set = set()
            now = time.time()
            for i in range(int(n_processes)):
                path = os.path.join(directory, f"hb_{i:04d}.json")
                try:
                    with open(path) as fh:
                        hb = json.load(fh)
                except (OSError, ValueError):
                    continue
                process = f"p{int(hb.get('process', i))}"
                age = max(0.0, now - float(hb.get("time", 0.0)))
                _metrics.fed_heartbeat_age_seconds(
                    self.gang, process).set(age)
                _metrics.fed_step(self.gang, process).set(
                    int(hb.get("step", 0)))
                children.add((process, "-"))
            return children
        self._add("heartbeats", fold)

    # ------------------------------------------------------------------
    # folding
    # ------------------------------------------------------------------
    def scrape(self) -> dict:
        """Fold every source once; returns a summary dict.  A failing
        source only ages (its staleness gauge keeps climbing) — the
        fold never raises into the caller's maintenance thread."""
        if not _metrics.enabled():
            return {"gang": self.gang, "sources": 0, "children": 0}
        with self._lock:
            sources = list(self._sources)
        children: set = set()
        ok = 0
        for src in sources:
            try:
                children |= src["fold"]() or set()
                src["last_ok"] = time.monotonic()
                ok += 1
            except Exception:  # noqa: BLE001 — a dead child must not kill the fold
                src["errors"] += 1
        self._last_children = children
        return {"gang": self.gang, "sources": len(sources),
                "sources_ok": ok, "children": len(children)}

    # ------------------------------------------------------------------
    def max_age_s(self) -> float:
        """Staleness of the WORST source (inf when a source has never
        folded) — what /readyz bounds."""
        with self._lock:
            sources = list(self._sources)
        if not sources:
            return 0.0
        now = time.monotonic()
        return max((float("inf") if s["last_ok"] is None
                    else now - s["last_ok"]) for s in sources)

    def status(self) -> dict:
        with self._lock:
            sources = list(self._sources)
        return {
            "gang": self.gang,
            "sources": [{"name": s["name"], "errors": s["errors"],
                         "age_s": (None if s["last_ok"] is None else
                                   round(time.monotonic()
                                         - s["last_ok"], 3))}
                        for s in sources],
            "children": sorted("/".join(c) for c in
                               self._last_children),
        }

    def close(self) -> None:
        with _FEDERATORS_LOCK:
            if self in FEDERATORS:
                FEDERATORS.remove(self)


def status() -> list[dict]:
    """Every live federator's view (the /readyz fold input)."""
    with _FEDERATORS_LOCK:
        feds = list(FEDERATORS)
    return [f.status() for f in feds]


def max_age_s() -> float:
    """Worst staleness across every live federator (0.0 when none —
    a process with no federation has nothing to bound)."""
    with _FEDERATORS_LOCK:
        feds = list(FEDERATORS)
    if not feds:
        return 0.0
    return max(f.max_age_s() for f in feds)
